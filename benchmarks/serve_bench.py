"""Serving-engine benchmark: batched vs per-request-serialized inference.

Open-loop client over a synthetic MLP with MIXED request shapes (rows
1..4 of a [None, 64] f32 input): the serialized mode replays the legacy
daemon behavior (one ``Predictor.run`` per request, in order), the
batched mode drives the DynamicBatcher + per-bucket AOT engine
(inference/batching.py) with every request submitted up front —
arrivals are not gated on completions.

Prints ONE JSON line; the load-bearing fields:
  batched_reqs_per_s / serial_reqs_per_s / speedup  (target: >= 3x at
      max_batch_size >= 8)
  batch_occupancy, padding_waste, p50/p95/p99_latency_ms  (profiler
      serve stats for the batched run)
  warmup_compiles, compile_count  (compile_count = compiles observed
      AFTER warmup during the measured stream; the compile-bounded
      engine's contract is 0)

CPU-safe: no accelerator reachable -> re-exec once on JAX_PLATFORMS=cpu
(bench.py's _devices_or_cpu_fallback pattern); any failure still emits
parseable JSON with rc 0.

    python benchmarks/serve_bench.py [--requests 400] [--max-batch 16]
    python benchmarks/serve_bench.py --decode   # continuous batching vs
                                                # sequential generation
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _devices_or_cpu_fallback():
    """bench.py's probe-then-reexec pattern: accelerator init failure
    falls back to one CPU retry; a CPU failure emits error JSON rc 0."""
    import jax
    if os.environ.get("_PADDLE_TPU_BENCH_CPU_FALLBACK"):
        try:
            jax.config.update("jax_platforms", "cpu")
        except Exception:
            pass
    try:
        return jax.devices()
    except Exception as e:                      # backend init failure
        if os.environ.get("_PADDLE_TPU_BENCH_CPU_FALLBACK"):
            print(json.dumps({"metric": "serve_bench_backend_error",
                              "value": 0.0, "unit": "reqs/s",
                              "vs_baseline": 0.0,
                              "error": str(e).split("\n")[0]}))
            sys.exit(0)
        sys.stderr.write(
            f"serve_bench: accelerator backend failed to initialize "
            f"({e!r}); retrying on CPU (JAX_PLATFORMS=cpu)\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   _PADDLE_TPU_BENCH_CPU_FALLBACK="1")
        xf = [t for t in env.get("XLA_FLAGS", "").split()
              if not t.startswith("--xla_tpu_")]
        if xf:
            env["XLA_FLAGS"] = " ".join(xf)
        else:
            env.pop("XLA_FLAGS", None)
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__)]
                  + sys.argv[1:], env)


def _error_json(msg):
    print(json.dumps({"metric": "serve_bench_error", "value": 0.0,
                      "unit": "reqs/s", "vs_baseline": 0.0,
                      "error": msg}), flush=True)


def run_bench(args):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import profiler
    from paddle_tpu.inference import Config, Predictor
    from paddle_tpu.inference.batching import DynamicBatcher
    from paddle_tpu.static import InputSpec

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(64, 256)
            self.fc2 = nn.Linear(256, 64)

        def forward(self, x):
            import paddle_tpu.nn.functional as F
            return self.fc2(F.relu(self.fc1(x)))

    paddle.seed(0)
    prefix = os.path.join(tempfile.mkdtemp(prefix="serve_bench_"), "mlp")
    paddle.jit.save(MLP(), prefix,
                    input_spec=[InputSpec([None, 64], "float32")])

    rng = np.random.default_rng(args.seed)
    row_mix = (1, 2, 1, 4)     # mixed request shapes, single-row-heavy
    requests = [rng.normal(size=(row_mix[i % len(row_mix)], 64))
                .astype(np.float32) for i in range(args.requests)]

    # --- serialized mode: the legacy daemon loop (one run per request,
    # global order). Warm each distinct shape first so the comparison is
    # steady-state dispatch, not compile time.
    serial_pred = Predictor(Config(prefix))
    for r in row_mix:
        serial_pred.run([np.zeros((r, 64), np.float32)])
    t0 = time.perf_counter()
    for x in requests:
        serial_pred.run([x])
    serial_s = time.perf_counter() - t0
    serial_rps = args.requests / serial_s

    # --- batched mode: fresh predictor + batcher, full warmup, then an
    # open-loop submit of the whole stream.
    profiler.reset_serve_stats()
    batched_pred = Predictor(Config(prefix))
    batcher = DynamicBatcher(batched_pred, max_batch_size=args.max_batch,
                             batch_timeout_ms=args.batch_timeout_ms)
    warmup_compiles = batcher.warmup()
    c0 = len(profiler.compile_events())
    t0 = time.perf_counter()
    futs = [batcher.submit([x]) for x in requests]
    for f in futs:
        f.result(timeout=300)
    batched_s = time.perf_counter() - t0
    batcher.stop()
    batched_rps = args.requests / batched_s
    steady_compiles = len(profiler.compile_events()) - c0

    from paddle_tpu.observability import REGISTRY
    stats = profiler.serve_stats()
    speedup = batched_rps / serial_rps if serial_rps > 0 else 0.0
    return {
        "metric": "serve_throughput",
        "value": round(batched_rps, 2),
        "unit": "reqs/s",
        # north star: >= 3x over the serialized daemon at max_batch >= 8
        "vs_baseline": round(speedup / 3.0, 3),
        "requests": args.requests,
        "max_batch_size": args.max_batch,
        "batch_timeout_ms": args.batch_timeout_ms,
        "serial_reqs_per_s": round(serial_rps, 2),
        "batched_reqs_per_s": round(batched_rps, 2),
        "speedup": round(speedup, 3),
        "batch_occupancy": stats["batch_occupancy"],
        "padding_waste": stats["padding_waste"],
        "queue_depth_max": stats["queue_depth_max"],
        "p50_latency_ms": stats["p50_latency_ms"],
        "p95_latency_ms": stats["p95_latency_ms"],
        "p99_latency_ms": stats["p99_latency_ms"],
        "warmup_compiles": warmup_compiles,
        "compile_count": steady_compiles,
        # raw registry samples behind the derived numbers above (the
        # serve_* families only — the bench result stays shape-stable)
        "metrics": {k: v for k, v in REGISTRY.flat().items()
                    if k.startswith("paddle_tpu_serve_")},
    }


def run_decode_bench(args):
    """Decode mode: continuous batching vs one-request-at-a-time
    autoregressive generation on a tiny GPT (inference/decode.py).

    Open loop: every prompt is submitted up front; the engine admits
    them into free KV slots between steps. The baseline runs the SAME
    engine code with max_slots=1 and gates each submit on the previous
    completion — i.e. the naive serving loop. Contract: >= 2x aggregate
    tokens/s at concurrency >= 8 with compile_count == 0 after warmup."""
    import threading

    from paddle_tpu import profiler
    from paddle_tpu.inference.decode import (DecodeEngine, kv_page_bytes,
                                             kv_slot_bytes, next_bucket)
    from paddle_tpu.models.gpt import GPT, gpt_tiny
    from paddle_tpu.observability import REGISTRY

    cfg = gpt_tiny()
    model = GPT(cfg)
    rng = np.random.default_rng(args.seed)
    max_new = args.decode_tokens
    if args.shared_prefix:
        # shared-system-prompt workload: N requests, one long common
        # head (page-aligned at the default 16-token pages) + a short
        # unique tail each — the prefix cache's target case
        n = args.shared_prefix
        head_len = 96
        max_new = min(max_new, cfg.max_seq_len - head_len - 8)
        head = rng.integers(0, cfg.vocab_size, size=head_len)
        prompts = [np.concatenate([
            head, rng.integers(0, cfg.vocab_size,
                               size=int(rng.integers(2, 7)))
        ]).astype(np.int32) for _ in range(n)]
    else:
        n = args.decode_requests
        prompts = [rng.integers(
            0, cfg.vocab_size,
            size=int(rng.integers(4, 25))).astype(np.int32)
            for _ in range(n)]

    # --- baseline: one request at a time (slot pool of 1, next submit
    # gated on the previous completion). Same kernels, same warmup.
    base = DecodeEngine(model, max_slots=1, max_new_tokens=max_new)
    base_warmup = base.warmup()
    t0 = time.perf_counter()
    base_tokens = 0
    for p in prompts:
        base_tokens += len(
            base.submit(p, max_new_tokens=max_new).result(timeout=300))
    base_s = time.perf_counter() - t0
    base.stop()
    base_tps = base_tokens / base_s if base_s > 0 else 0.0

    # --- continuous batching: all prompts in flight at once, per-stream
    # TTFT measured from submit to first token event.
    eng = DecodeEngine(model, max_slots=args.decode_slots,
                       max_new_tokens=max_new, max_pending=n)
    warmup_compiles = eng.warmup()
    c0 = len(profiler.compile_events())
    m0 = {k: float(v) for k, v in REGISTRY.flat().items()
          if k.startswith("paddle_tpu_decode_prefix_")}

    ttfts, counts, errors = [], [], []
    lock = threading.Lock()
    occupancy_samples = []
    peak_pages = [0]
    run_done = threading.Event()

    def sample_occupancy():
        while not run_done.wait(0.005):
            st = eng.stats()
            peak_pages[0] = max(peak_pages[0], st["pages"]["pages_used"])
            if st["active"] or st["pending"]:
                occupancy_samples.append(st["active"] / st["max_slots"])

    def consume(prompt):
        t_sub = time.perf_counter()
        try:
            stream = eng.submit(prompt, max_new_tokens=max_new)
            got, first = 0, None
            for _ev in stream.events(timeout=300):
                if first is None:
                    first = time.perf_counter() - t_sub
                got += 1
            with lock:
                ttfts.append(first)
                counts.append(got)
        except Exception as e:
            with lock:
                errors.append(repr(e))

    sampler = threading.Thread(target=sample_occupancy, daemon=True)
    threads = [threading.Thread(target=consume, args=(p,), daemon=True)
               for p in prompts]
    t0 = time.perf_counter()
    sampler.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    wall_s = time.perf_counter() - t0
    run_done.set()
    sampler.join(timeout=10)
    steady_compiles = len(profiler.compile_events()) - c0
    st = eng.stats()
    eng.stop()

    cont_tokens = sum(counts)
    cont_tps = cont_tokens / wall_s if wall_s > 0 else 0.0
    speedup = cont_tps / base_tps if base_tps > 0 else 0.0
    ts = sorted(t for t in ttfts if t is not None)

    def pct(q):
        if not ts:
            return 0.0
        return round(ts[min(len(ts) - 1, int(q * len(ts)))] * 1e3, 3)

    occ = round(sum(occupancy_samples) / len(occupancy_samples), 4) \
        if occupancy_samples else 0.0

    # paged-KV scorecard: prefix-cache efficiency and HBM per slot vs
    # what the old contiguous (batch-rung x kv-rung) pool would reserve
    m1 = {k: float(v) for k, v in REGISTRY.flat().items()
          if k.startswith("paddle_tpu_decode_prefix_")}
    hit_toks = m1.get("paddle_tpu_decode_prefix_hit_tokens_total", 0.0) \
        - m0.get("paddle_tpu_decode_prefix_hit_tokens_total", 0.0)
    lookup_toks = \
        m1.get("paddle_tpu_decode_prefix_lookup_tokens_total", 0.0) \
        - m0.get("paddle_tpu_decode_prefix_lookup_tokens_total", 0.0)
    hit_rate = hit_toks / lookup_toks if lookup_toks else 0.0
    pages_peak = max(peak_pages[0], st["pages"]["pages_used"])
    page_bytes = kv_page_bytes(cfg, st["page_tokens"])
    slots = max(args.decode_slots, 1)
    longest = min(max(len(p) for p in prompts) + max_new,
                  cfg.max_seq_len)
    contig_per_slot = kv_slot_bytes(
        cfg, next_bucket(longest, eng.kv_ladder))
    return {
        "metric": "decode_throughput",
        "value": round(cont_tps, 2),
        "unit": "tokens/s",
        # north star: >= 2x over one-request-at-a-time at >= 8 slots
        "vs_baseline": round(speedup / 2.0, 3),
        "requests": n,
        "errors": errors[:5],
        "decode_slots": args.decode_slots,
        "max_new_tokens": max_new,
        "continuous_tokens_per_s": round(cont_tps, 2),
        "sequential_tokens_per_s": round(base_tps, 2),
        "speedup": round(speedup, 3),
        "tokens_per_s_per_request": round(cont_tps / n, 2) if n else 0.0,
        "total_tokens": cont_tokens,
        "ttft_p50_ms": pct(0.50),
        "ttft_p95_ms": pct(0.95),
        "slot_occupancy": occ,
        "shared_prefix": args.shared_prefix,
        "prefix_hit_rate": round(hit_rate, 4),
        "pages_in_use": int(pages_peak),
        "page_tokens": st["page_tokens"],
        "hbm_bytes_per_slot": int(pages_peak * page_bytes // slots),
        "contiguous_hbm_bytes_per_slot": int(contig_per_slot),
        "page_pool": st["pages"],
        "engine_steps": st["steps"],
        "warmup_compiles": warmup_compiles,
        "baseline_warmup_compiles": base_warmup,
        "compile_count": steady_compiles,
        "metrics": {k: v for k, v in REGISTRY.flat().items()
                    if k.startswith("paddle_tpu_decode_")},
    }


def run_router_bench(args):
    """Fleet mode: N in-process backends behind the ServeRouter, driven
    over the wire by concurrent clients. With ``--kill-one`` a backend
    is stopped abruptly mid-run — the contract under test is ZERO lost
    requests (every client gets a tensor reply for every request) with
    the failover cost reported from the router's own histograms.

    With ``PADDLE_TPU_TRACE_SAMPLE`` set (e.g. 1), every routed request
    is assembled into a JSONL trace line (router pick/forward/reply +
    the backend's relayed breakdown); the bench captures them to a temp
    file (unless ``PADDLE_TPU_TRACE_FILE`` already points somewhere),
    and reports the assembled-trace count, the router-vs-backend
    latency epsilon, and the request-id collision count (contract: 0).
    A ``metrics_delta`` section shows exactly which router/serve
    counters the run moved."""
    import socket
    import threading

    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.inference.router import Backend, ServeRouter
    from paddle_tpu.inference.serve import (InferenceServer, read_reply,
                                            write_tensors)
    from paddle_tpu.observability import REGISTRY
    from paddle_tpu.static import InputSpec

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(64, 256)
            self.fc2 = nn.Linear(256, 64)

        def forward(self, x):
            import paddle_tpu.nn.functional as F
            return self.fc2(F.relu(self.fc1(x)))

    paddle.seed(0)
    prefix = os.path.join(tempfile.mkdtemp(prefix="serve_bench_"), "mlp")
    paddle.jit.save(MLP(), prefix,
                    input_spec=[InputSpec([None, 64], "float32")])

    # trace capture: recorders read the env at construction, so the
    # sink must be decided before any server/router exists
    trace_path = os.environ.get("PADDLE_TPU_TRACE_FILE") or None
    if os.environ.get("PADDLE_TPU_TRACE_SAMPLE") and trace_path is None:
        trace_path = os.path.join(
            tempfile.mkdtemp(prefix="serve_bench_trace_"),
            "traces.jsonl")
        os.environ["PADDLE_TPU_TRACE_FILE"] = trace_path

    srvs = [InferenceServer(prefix, port=0, max_batch_size=args.max_batch,
                            batch_timeout_ms=args.batch_timeout_ms,
                            metrics_port=0)
            for _ in range(args.router)]
    router = ServeRouter(
        [Backend("127.0.0.1", s.port, s.metrics_port) for s in srvs],
        port=0, poll_interval=0.1)

    # traces need the poll loop to have learned each backend speaks
    # PDI2 (statusz trace_wire) before the first request goes out
    deadline = time.perf_counter() + 10.0
    while time.perf_counter() < deadline:
        bs = router.backends()
        if bs and all(b.trace_wire for b in bs):
            break
        time.sleep(0.05)

    rng = np.random.default_rng(args.seed)
    row_mix = (1, 2, 1, 4)
    n_clients = max(args.clients, 1)
    per_client = max(args.requests // n_clients, 1)
    total = per_client * n_clients

    done_lock = threading.Lock()
    completed = [0]
    latencies = []
    lost = []                  # (client, error-or-exception)
    kill_at = total // 3 if args.kill_one and args.router > 1 else None
    killed = {"key": None, "t": None}

    def maybe_kill():
        with done_lock:
            fire = (kill_at is not None and killed["key"] is None
                    and completed[0] >= kill_at)
            if fire:
                killed["key"] = f"127.0.0.1:{srvs[1].port}"
        if fire:
            killed["t"] = time.perf_counter()
            srvs[1].stop()     # abrupt: mid-batch, no drain

    def client(i):
        x = rng.normal(size=(row_mix[i % len(row_mix)], 64)) \
            .astype(np.float32)
        try:
            with socket.create_connection(
                    ("127.0.0.1", router.port)) as s:
                s.settimeout(120)
                for _ in range(per_client):
                    t0 = time.perf_counter()
                    write_tensors(s, [x])
                    out, err = read_reply(s)
                    dt = time.perf_counter() - t0
                    if err is not None:
                        lost.append((i, err))
                        return
                    with done_lock:
                        completed[0] += 1
                        latencies.append(dt)
                    maybe_kill()
        except Exception as e:
            lost.append((i, repr(e)))

    flat0 = REGISTRY.flat()
    t0 = time.perf_counter()
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=600)
    wall_s = time.perf_counter() - t0

    flat = REGISTRY.flat()
    fo_hist = REGISTRY.get("paddle_tpu_router_failover_latency_seconds")
    lat_sorted = sorted(latencies)

    def pct(q):
        if not lat_sorted:
            return 0.0
        k = min(len(lat_sorted) - 1, int(q * len(lat_sorted)))
        return round(lat_sorted[k] * 1e3, 3)

    router.stop()
    for s in srvs:
        s.stop()
    rps = completed[0] / wall_s if wall_s > 0 else 0.0

    # what the run actually moved, not the process lifetime totals
    metrics_delta = {}
    for k, v in flat.items():
        if not (k.startswith("paddle_tpu_router_")
                or k.startswith("paddle_tpu_serve_")):
            continue
        try:
            d = round(float(v) - float(flat0.get(k, 0.0)), 6)
        except (TypeError, ValueError):
            continue
        if d:
            metrics_delta[k] = d

    # assembled traces: count them, prove ids never collide, and bound
    # the epsilon between the router's observed latency (total_s) and
    # the backend's own stage sum (backend_total_s)
    trace_summary = {"file": trace_path, "lines": 0,
                     "router_assembled": 0, "with_backend_breakdown": 0,
                     "id_collisions": 0, "epsilon_ms": None}
    if trace_path and os.path.exists(trace_path):
        ids, eps = [], []
        with open(trace_path) as f:
            for raw in f:
                try:
                    line = json.loads(raw)
                except ValueError:
                    continue
                trace_summary["lines"] += 1
                ids.append(line.get("request_id"))
                if line.get("component") != "router":
                    continue
                trace_summary["router_assembled"] += 1
                if "backend_total_s" in line:
                    trace_summary["with_backend_breakdown"] += 1
                    eps.append(line["total_s"]
                               - line["backend_total_s"])
        trace_summary["id_collisions"] = len(ids) - len(set(ids))
        if eps:
            trace_summary["epsilon_ms"] = {
                "mean": round(sum(eps) / len(eps) * 1e3, 3),
                "min": round(min(eps) * 1e3, 3),
                "max": round(max(eps) * 1e3, 3)}

    return {
        "metric": "serve_router_fleet",
        "value": round(rps, 2),
        "unit": "reqs/s",
        # the contract IS the baseline: 1.0 = zero lost requests
        "vs_baseline": 1.0 if not lost and completed[0] == total else 0.0,
        "fleet": args.router,
        "clients": n_clients,
        "requests": total,
        "completed": completed[0],
        "lost_requests": len(lost),
        "lost_detail": [f"client {i}: {e}" for i, e in lost[:5]],
        "killed_backend": killed["key"],
        "failovers": int(flat.get(
            "paddle_tpu_router_failovers_total", 0)),
        "failover_p95_ms": round(
            fo_hist.percentile(0.95) * 1e3, 3) if fo_hist else 0.0,
        "failover_max_ms": round(
            fo_hist.percentile(1.0) * 1e3, 3) if fo_hist else 0.0,
        "p50_latency_ms": pct(0.50),
        "p95_latency_ms": pct(0.95),
        "p99_latency_ms": pct(0.99),
        "reqs_per_s": round(rps, 2),
        "traces": trace_summary,
        "metrics_delta": metrics_delta,
        "router_metrics": {k: v for k, v in flat.items()
                           if k.startswith("paddle_tpu_router_")},
    }


def main():
    ap = argparse.ArgumentParser(description="serving engine benchmark")
    ap.add_argument("--requests", type=int, default=400)
    ap.add_argument("--max-batch", type=int, default=16)
    ap.add_argument("--batch-timeout-ms", type=float, default=2.0)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--decode", action="store_true",
                    help="decode mode: continuous-batching token "
                         "generation vs one-request-at-a-time on the "
                         "KV-cache engine (tokens/s, TTFT, occupancy)")
    ap.add_argument("--decode-requests", type=int, default=24)
    ap.add_argument("--decode-slots", type=int, default=8)
    ap.add_argument("--decode-tokens", type=int, default=32,
                    help="(decode mode) new tokens per request")
    ap.add_argument("--shared-prefix", type=int, default=0, metavar="N",
                    help="(decode mode) N requests sharing one long "
                         "system prompt + short unique tails — scores "
                         "the paged-KV prefix cache (prefix_hit_rate, "
                         "pages_in_use, hbm_bytes_per_slot)")
    ap.add_argument("--router", type=int, default=0, metavar="N",
                    help="fleet mode: N backends behind the front "
                         "router, driven over the wire (0 = classic "
                         "batched-vs-serial bench)")
    ap.add_argument("--clients", type=int, default=8,
                    help="(fleet mode) concurrent wire clients")
    ap.add_argument("--kill-one", action="store_true",
                    help="(fleet mode) stop one backend abruptly a "
                         "third of the way through; lost_requests must "
                         "stay 0")
    args = ap.parse_args()
    _devices_or_cpu_fallback()
    try:
        if args.decode:
            out = run_decode_bench(args)
        elif args.router:
            out = run_router_bench(args)
        else:
            out = run_bench(args)
    except Exception as e:                       # rc-0 JSON contract
        _error_json(f"{type(e).__name__}: {str(e).splitlines()[0]}")
        return
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
