"""Seeded, replayable multi-tenant traffic scenarios for the serving QoS
layer.

A scenario is a deterministic list of :class:`Arrival` records (offset
seconds, tenant, priority, prompt tokens, decode budget) generated from
a single seed — replaying the same seed replays the same traffic, which
is what makes these usable as a standing regression harness (ISSUE 16).
Four generators cover the shapes a multi-tenant fleet actually sees:

* ``diurnal``      — a smooth sinusoidal wave over the run: the
  steady-state capacity-planning case.
* ``flash_crowd``  — a low baseline with a short burst window at many
  times the baseline rate: launch-day traffic.
* ``long_context`` — mostly short requests plus a straggler tenant
  submitting long prompts with large decode budgets: the head-of-line
  blocking probe.
* ``adversarial_flood`` — a well-behaved tenant at a sustainable rate
  beside a flood tenant submitting at >= 4x capacity: the QoS
  acceptance scenario (the flood must be degraded via quota/shed/
  preempt while the well-behaved tenant loses nothing).

:func:`replay` drives any DecodeEngine-shaped object (``submit(prompt,
tenant=..., priority=..., max_new_tokens=...)`` returning a pollable
stream) open-loop on the arrival clock and records one
:class:`Outcome` per request; :func:`score` folds outcomes into
per-tenant p50/p99 latency and goodput. Everything here is numpy +
stdlib so tests can import the generators without touching jax.

    python benchmarks/serve_bench.py --scenario adversarial_flood
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

__all__ = ["Arrival", "Outcome", "SCENARIOS", "generate", "replay",
           "score", "diurnal", "flash_crowd", "long_context",
           "adversarial_flood"]


@dataclass(frozen=True)
class Arrival:
    """One scheduled request of a scenario."""
    t: float                  # offset from scenario start, seconds
    tenant: str
    priority: int
    prompt: tuple             # token ids
    max_new: int


@dataclass
class Outcome:
    """What happened to one replayed arrival."""
    tenant: str
    t_submit: float           # offsets from replay start, seconds
    t_first: Optional[float] = None
    t_done: Optional[float] = None
    tokens: int = 0
    status: str = "pending"   # ok | shed | error | timeout
    error: str = ""


def _prompt(rng, vocab, lo, hi):
    n = int(rng.integers(lo, max(hi, lo + 1)))
    return tuple(int(t) for t in rng.integers(0, vocab, size=n))


def _poisson_times(rng, rate_fn, duration_s, cap=10000) -> List[float]:
    """Arrival offsets for an inhomogeneous Poisson process via
    thinning against the rate function's peak."""
    peak = max(rate_fn(duration_s * i / 64.0) for i in range(65))
    if peak <= 0:
        return []
    out, t = [], 0.0
    while len(out) < cap:
        t += float(rng.exponential(1.0 / peak))
        if t >= duration_s:
            break
        if rng.random() * peak <= rate_fn(t):
            out.append(t)
    return out


def diurnal(seed=0, duration_s=3.0, rate=12.0, vocab=512,
            tenants=("tenant-a", "tenant-b"), max_new=12) -> List[Arrival]:
    """A full sinusoidal day compressed into the run: rate swings
    between ~25% and ~175% of the mean, tenants interleaved evenly."""
    rng = np.random.default_rng((seed, 0xD1))
    wave = lambda t: rate * (1.0 + 0.75 * np.sin(
        2.0 * np.pi * t / duration_s))
    out = []
    for i, t in enumerate(_poisson_times(rng, wave, duration_s)):
        out.append(Arrival(t, tenants[i % len(tenants)], 0,
                           _prompt(rng, vocab, 4, 17), max_new))
    return out


def flash_crowd(seed=0, duration_s=3.0, base_rate=6.0, burst_rate=48.0,
                vocab=512, tenants=("tenant-a", "crowd"),
                max_new=12) -> List[Arrival]:
    """A steady baseline tenant plus a crowd tenant that goes from zero
    to ``burst_rate`` for the middle third of the run."""
    rng = np.random.default_rng((seed, 0xF1))
    out = [Arrival(t, tenants[0], 0, _prompt(rng, vocab, 4, 17), max_new)
           for t in _poisson_times(rng, lambda t: base_rate, duration_s)]
    lo, hi = duration_s / 3.0, 2.0 * duration_s / 3.0
    burst = lambda t: burst_rate if lo <= t < hi else 0.0
    out += [Arrival(t, tenants[1], 0, _prompt(rng, vocab, 4, 13), max_new)
            for t in _poisson_times(rng, burst, duration_s)]
    out.sort(key=lambda a: a.t)
    return out


def long_context(seed=0, duration_s=3.0, rate=10.0, vocab=512,
                 tenants=("tenant-a", "straggler"), max_new=10,
                 long_prompt=72, long_max_new=48) -> List[Arrival]:
    """Short interactive traffic beside a straggler tenant whose
    requests carry long prompts and large decode budgets — the
    head-of-line blocking / preemption-victim probe."""
    rng = np.random.default_rng((seed, 0x1C))
    out = [Arrival(t, tenants[0], 1, _prompt(rng, vocab, 4, 13), max_new)
           for t in _poisson_times(rng, lambda t: rate, duration_s)]
    out += [Arrival(t, tenants[1], 0,
                    _prompt(rng, vocab, long_prompt, long_prompt + 9),
                    long_max_new)
            for t in _poisson_times(rng, lambda t: rate / 5.0,
                                    duration_s)]
    out.sort(key=lambda a: a.t)
    return out


def adversarial_flood(seed=0, duration_s=3.0, capacity_rps=8.0,
                      flood_factor=4.0, vocab=512,
                      tenants=("tenant-a", "flood"),
                      max_new=12) -> List[Arrival]:
    """The QoS acceptance scenario: the well-behaved tenant submits at
    half of capacity; the flood tenant submits at ``flood_factor`` x
    capacity with low priority. The fleet must degrade the flood (via
    quota, shed, or preemption) while the well-behaved tenant loses
    nothing and keeps its latency."""
    rng = np.random.default_rng((seed, 0xAD))
    good = _poisson_times(rng, lambda t: capacity_rps / 2.0, duration_s)
    out = [Arrival(t, tenants[0], 1, _prompt(rng, vocab, 4, 13), max_new)
           for t in good]
    flood = _poisson_times(
        rng, lambda t: capacity_rps * flood_factor, duration_s)
    out += [Arrival(t, tenants[1], 0, _prompt(rng, vocab, 4, 13),
                    max_new)
            for t in flood]
    out.sort(key=lambda a: a.t)
    return out


SCENARIOS: Dict[str, Callable[..., List[Arrival]]] = {
    "diurnal": diurnal,
    "flash_crowd": flash_crowd,
    "long_context": long_context,
    "adversarial_flood": adversarial_flood,
}


def generate(name: str, seed: int = 0, **kw) -> List[Arrival]:
    """Build a named scenario's arrival list (same seed, same list)."""
    try:
        gen = SCENARIOS[name]
    except KeyError:
        raise ValueError(
            f"unknown scenario {name!r}; one of {sorted(SCENARIOS)}")
    return gen(seed=seed, **kw)


def replay(engine, arrivals: Sequence[Arrival], timeout_s: float = 120.0,
           speedup: float = 1.0) -> List[Outcome]:
    """Drive the engine open-loop on the arrival clock.

    Submits each arrival when its offset elapses (never gated on
    completions — floods really flood), sweeps every live stream from
    one collector loop (per-stream consumer threads would fight the
    scheduler thread for cycles), and returns one Outcome per arrival.
    A shed submit (typed RESOURCE_EXHAUSTED) is an outcome, not a crash.
    ``speedup`` > 1 compresses the arrival clock."""
    outcomes = [Outcome(a.tenant, a.t / speedup) for a in arrivals]
    streams: Dict[int, object] = {}
    t0 = time.perf_counter()
    nxt = 0
    deadline = t0 + timeout_s
    while (nxt < len(arrivals) or streams) \
            and time.perf_counter() < deadline:
        now = time.perf_counter() - t0
        while nxt < len(arrivals) and arrivals[nxt].t / speedup <= now:
            a, o = arrivals[nxt], outcomes[nxt]
            o.t_submit = now
            try:
                streams[nxt] = engine.submit(
                    np.asarray(a.prompt, np.int32), tenant=a.tenant,
                    priority=a.priority, max_new_tokens=a.max_new)
            except Exception as e:
                code = getattr(e, "code", "")
                o.status = ("shed" if code == "RESOURCE_EXHAUSTED"
                            else "error")
                o.error = str(e).split("\n")[0]
            nxt += 1
        moved = False
        for i in list(streams):
            o = outcomes[i]
            while True:
                try:
                    ev = streams[i].poll()
                except Exception as e:
                    o.status, o.error = "error", repr(e)
                    del streams[i]
                    break
                if ev is None:
                    break
                moved = True
                if ev[0] == "done":
                    o.t_done = time.perf_counter() - t0
                    o.status = "ok"
                    del streams[i]
                    break
                if o.t_first is None:
                    o.t_first = time.perf_counter() - t0
                o.tokens += 1
        if not moved:
            time.sleep(0.0005)
    for i in streams:       # replay deadline: anything still open
        outcomes[i].status = "timeout"
    return outcomes


def _pct(vals, q):
    if not vals:
        return 0.0
    vals = sorted(vals)
    return vals[min(len(vals) - 1, int(q * len(vals)))]


def score(outcomes: Sequence[Outcome],
          duration_s: Optional[float] = None) -> Dict[str, dict]:
    """Fold outcomes into per-tenant verdicts: request counts by
    status, p50/p99 completion latency (submit -> done, ms), and
    goodput (completed tokens per second of scenario wall)."""
    if duration_s is None:
        duration_s = max((o.t_done or o.t_submit for o in outcomes),
                         default=0.0) or 1.0
    per: Dict[str, dict] = {}
    for o in outcomes:
        d = per.setdefault(o.tenant, {
            "submitted": 0, "ok": 0, "shed": 0, "error": 0,
            "timeout": 0, "tokens": 0, "_lat": []})
        d["submitted"] += 1
        d[o.status] = d.get(o.status, 0) + 1
        d["tokens"] += o.tokens
        if o.status == "ok" and o.t_done is not None:
            d["_lat"].append((o.t_done - o.t_submit) * 1e3)
    out = {}
    for tenant, d in per.items():
        lat = d.pop("_lat")
        out[tenant] = {
            **d,
            "lost": d["submitted"] - d["ok"],
            "p50_ms": round(_pct(lat, 0.50), 3),
            "p99_ms": round(_pct(lat, 0.99), 3),
            "goodput_tps": round(d["tokens"] / duration_s, 3),
        }
    return out
