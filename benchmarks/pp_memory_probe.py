"""Per-device HBM requirements of the pp training program at 1.3B under
ZeRO stages, measured via XLA's compiled memory analysis on the 8-device
CPU mesh (the sharding is identical to a real slice; only the backend
differs)."""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.optimizer as opt
from paddle_tpu.distributed.fleet.compiler import compile_train_step
from paddle_tpu.distributed.fleet.strategy import DistributedStrategy
from paddle_tpu.models import GPT, GPTConfig

# 1.3B geometry scaled down 8x in layers to keep CPU compile fast, then
# extrapolate linearly in layer count (params/slots scale linearly;
# activations per stage scale with layers/stage)
cfg = GPTConfig(hidden=2048, layers=4, heads=16, max_seq_len=256,
                vocab_size=50304)
for stage in (0, 2):
    paddle.seed(0)
    m = GPT(cfg); m.eval()
    s = DistributedStrategy()
    s.pipeline = True
    s.recompute = True
    s.hybrid_configs.pp_degree = 2
    s.hybrid_configs.dp_degree = 4
    s.pipeline_configs.accumulate_steps = 4
    if stage:
        s.sharding = True
        s.sharding_configs.stage = stage
    adam = opt.Adam(learning_rate=1e-4, parameters=list(m.parameters()))
    prog = compile_train_step(m, adam, s)
    # one executed step ensures the jitted fn is the real one; then pull
    # the compiled memory analysis
    ids = np.random.default_rng(0).integers(0, cfg.vocab_size,
                                            (16, 256)).astype(np.int32)
    prog.step(ids, ids, lr=1e-3)
    lowered = prog._step.lower(prog.params, prog.state, prog.opt_state,
                               jax.random.PRNGKey(0),
                               np.float32(1e-3),
                               tuple(prog._put_data(d) for d in (ids, ids)))
    ma = lowered.compile().memory_analysis()
    print(f"stage={stage}: args={ma.argument_size_in_bytes/2**30:.3f}G "
          f"out={ma.output_size_in_bytes/2**30:.3f}G "
          f"temp={ma.temp_size_in_bytes/2**30:.3f}G "
          f"total={(ma.argument_size_in_bytes+ma.temp_size_in_bytes)/2**30:.3f}G per device")
