"""BASELINE.md config runner — the five target configs, each reachable
purely through the public API. Prints one JSON line per config.

    python benchmarks/run.py --config 4            # GPT-2 345M ZeRO-2
    python benchmarks/run.py --all --smoke         # tiny shapes, any host

Off-TPU: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
(smoke mode engages automatically on CPU).

| # | config (BASELINE.md) | parallelism |
|---|---|---|
| 1 | MNIST LeNet via Model.fit | single chip |
| 2 | ResNet-50 train step | single chip |
| 3 | ERNIE/BERT-base pretrain (MLM) | dp over devices |
| 4 | GPT-2 345M, ZeRO-2 | sharding over dp |
| 5 | GPT-3 1.3B, pipeline + recompute | pp x dp |
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # NOT redundant with the env var: a TPU PJRT plugin (axon) outranks
    # JAX_PLATFORMS during backend registration — the config update is
    # what actually keeps this process off the chip (see conftest.py)
    import jax as _jax
    _jax.config.update("jax_platforms", "cpu")

import numpy as np


def _timed_steps(step_fn, n_short=2, n_long=10):
    """Marginal step seconds: time(n_long chained steps) minus
    time(n_short), ONE host fetch per window. step_fn() must return the
    on-device loss WITHOUT fetching — a per-step float() pays a full
    tunnel RTT (~150ms) and was the dominant term in the r4 config
    numbers (bench.py's estimator, applied here; VERDICT r4 Weak #1)."""
    def run(n):
        t0 = time.perf_counter()
        out = None
        for _ in range(n):
            out = step_fn()
        out = out.numpy() if hasattr(out, "numpy") else out
        float(np.asarray(out))               # the window's single sync
        return time.perf_counter() - t0
    run(1)                                   # compile + warm
    estimates, dl = [], None
    for _ in range(2):
        ds = run(n_short)
        dl = run(n_long)
        if dl > ds:
            estimates.append((dl - ds) / (n_long - n_short))
    # all-jitter fallback: the amortised long window bounds the step
    return min(estimates) if estimates else dl / n_long


def _emit(name, value, unit, extra=None):
    rec = {"config": name, "value": round(value, 2), "unit": unit}
    rec.update(extra or {})
    print(json.dumps(rec), flush=True)


def _peak_flops():
    import bench
    return bench.peak_flops()


def _mfu(tokens_per_sec, model, T):
    """tokens/s -> model FLOPs utilization on this chip (the model must
    expose flops_per_token — the marginal-step estimator's counterpart,
    bench.py methodology)."""
    return round(tokens_per_sec * model.flops_per_token(T)
                 / _peak_flops(), 4)


def config1_lenet(smoke):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.hapi import Model
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    n = 256 if smoke else 8192
    B = 64 if smoke else 256
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 1, 28, 28)).astype(np.float32)
    y = rng.integers(0, 10, (n, 1)).astype(np.int64)
    model = Model(LeNet())
    import paddle_tpu.optimizer as opt
    model.prepare(opt.Adam(learning_rate=1e-3,
                           parameters=model.parameters()),
                  paddle.nn.CrossEntropyLoss())
    ds = TensorDataset([x, y])
    model.fit(ds, epochs=1, batch_size=B, verbose=0)   # warmup/compile
    t0 = time.perf_counter()
    model.fit(ds, epochs=1, batch_size=B, verbose=0)
    dt = time.perf_counter() - t0
    _emit("1_mnist_lenet_fit", n / dt, "samples/s")


def config2_resnet50(smoke):
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.fleet.compiler import compile_train_step
    from paddle_tpu.distributed.fleet.strategy import DistributedStrategy
    from paddle_tpu.vision.models import resnet18, resnet50

    paddle.seed(0)
    # PT_BENCH_CONV_FORMAT=NHWC measures the channels-last zoo option
    fmt = os.environ.get("PT_BENCH_CONV_FORMAT", "NCHW")
    inner = resnet18(data_format=fmt) if smoke else \
        resnet50(data_format=fmt)

    # jitted train step through the strategy compiler: on TPU the eager
    # op-at-a-time executor pays a dispatch round-trip per op (~1k ops in
    # ResNet-50) — the compiled path is the intended executor there
    class Wrap(nn.Layer):
        def __init__(self):
            super().__init__()
            self.net = inner

        def loss(self, x, y):
            return F.cross_entropy(self.net(x), y)

    model = Wrap()
    B, H = (4, 32) if smoke else (256, 224)
    s = DistributedStrategy()
    s.amp = not smoke
    s.amp_configs.use_pure_bf16 = not smoke
    mom = opt.Momentum(learning_rate=0.1,
                       parameters=list(model.parameters()))
    import jax
    prog = compile_train_step(
        model, mom, s,
        mesh=s.build_mesh(devices=jax.devices()[:1]))
    rng = np.random.default_rng(0)
    # pre-stage the batch on device: measuring compute, not the host link
    # (the real input pipeline overlaps transfers via device_prefetch)
    shape = (B, 3, H, H) if fmt == "NCHW" else (B, H, H, 3)
    x = prog._put_data(rng.normal(size=shape).astype(np.float32))
    y = prog._put_data(rng.integers(0, 1000, (B,)).astype(np.int64))

    def step():
        return prog.step(x, y)

    dt = _timed_steps(step)
    _emit("2_resnet50_train" if not smoke else "2_resnet18_smoke",
          B / dt, "images/s", {"data_format": fmt, "batch": B})


def _compiled_lm(model_cfg_fn, strategy_fn, B, T, smoke):
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.fleet.compiler import compile_train_step
    from paddle_tpu.models import GPT

    paddle.seed(0)
    model = model_cfg_fn()
    model.eval()
    s = strategy_fn(len(jax.devices()))
    adam = opt.Adam(learning_rate=1e-4,
                    parameters=list(model.parameters()))
    prog = compile_train_step(model, adam, s, loss_method="loss")
    rng = np.random.default_rng(0)
    V = model.cfg.vocab_size if hasattr(model, "cfg") else 512
    ids = prog._put_data(rng.integers(0, V, (B, T)).astype(np.int64))

    def step():
        return prog.step(ids, ids)

    dt = _timed_steps(step)
    return B * T / dt, prog


def config3_bert(smoke):
    import jax
    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.fleet.compiler import compile_train_step
    from paddle_tpu.distributed.fleet.strategy import DistributedStrategy
    from paddle_tpu.models import Bert, bert_tiny, ernie_base

    paddle.seed(0)
    model = Bert(bert_tiny() if smoke else ernie_base())
    model.eval()
    B, T = (8, 64) if smoke else (64, 512)
    s = DistributedStrategy()
    s.amp = not smoke
    # pure-bf16 (O2) — the flagship bench.py treatment; O1's f32 params
    # with per-op casts left config 3 at ~23% MFU (VERDICT r4 Weak #1)
    s.amp_configs.use_pure_bf16 = not smoke
    adam = opt.Adam(learning_rate=1e-4,
                    parameters=list(model.parameters()))
    prog = compile_train_step(model, adam, s, loss_method="mlm_loss")
    rng = np.random.default_rng(0)
    V = model.cfg.vocab_size
    ids = prog._put_data(rng.integers(0, V, (B, T)).astype(np.int64))

    def step():
        return prog.step(ids, ids)

    dt = _timed_steps(step)
    tps = B * T / dt
    _emit("3_ernie_base_pretrain" if not smoke else "3_bert_tiny_smoke",
          tps, "tokens/s",
          {"dp": int(prog.mesh.shape.get("dp", 1)),
           "mfu": None if smoke else _mfu(tps, model, T)})


def config4_gpt2_345m_zero2(smoke):
    from paddle_tpu.distributed.fleet.strategy import DistributedStrategy
    from paddle_tpu.models import GPT, gpt2_345m, gpt_tiny

    def mk():
        from paddle_tpu.models import GPT
        return GPT(gpt_tiny() if smoke else gpt2_345m())

    def strat(n):
        s = DistributedStrategy()
        s.amp = not smoke
        s.amp_configs.use_pure_bf16 = not smoke
        s.sharding = True
        s.sharding_configs.stage = 2
        return s

    B, T = (8, 64) if smoke else (8, 1024)
    tps, prog = _compiled_lm(mk, strat, B, T, smoke)
    _emit("4_gpt2_345m_zero2" if not smoke else "4_gpt_tiny_zero2_smoke",
          tps, "tokens/s", {"dp": int(prog.mesh.shape.get("dp", 1)),
                            "mfu": None if smoke else
                            _mfu(tps, prog.layer, T)})


def config5_gpt3_1p3b_pp(smoke):
    from paddle_tpu.distributed.fleet.strategy import DistributedStrategy
    from paddle_tpu.models import GPT, gpt3_1p3b, gpt_tiny

    def mk():
        from paddle_tpu.models import GPT
        return GPT(gpt_tiny() if smoke else gpt3_1p3b())

    import jax
    n = len(jax.devices())

    if n == 1 and not smoke:
        # single chip (the TPU bench box): 1.3B fits 16 GB HBM as pure
        # bf16 — params 2.6 GB + Adam m/v slots 5.2 GB (zeros_like
        # follows the bf16 param dtype) + remat'd activations. The
        # pp=2 x dp=4 virtual-mesh run below (--smoke / dryrun) stays
        # the multi-chip correctness artifact.
        import paddle_tpu as paddle
        import paddle_tpu.optimizer as opt
        from paddle_tpu.distributed.fleet.compiler import \
            compile_train_step
        from paddle_tpu.models import GPT

        paddle.seed(0)
        # build on HOST: eager construction would otherwise leave f32
        # originals + bf16 casts resident in HBM next to the compiled
        # program's own param/slot buffers (that transient peak is what
        # OOMed, not the steady state)
        try:
            cpu0 = jax.devices("cpu")[0]
        except RuntimeError:        # cpu backend excluded by JAX_PLATFORMS
            cpu0 = None
        with jax.default_device(cpu0):
            # fused_head_ce: stream the tied-head CE through the Pallas
            # kernel — the two ~1.5 GB f32 logits buffers (fwd live +
            # bwd remat) never materialize
            model = GPT(gpt3_1p3b(fused_head_ce=True)).bfloat16()
        model.eval()
        s = DistributedStrategy()
        s.recompute = True
        # reduced-precision optimizer state (the 16 GB fit): Momentum's
        # single bf16 slot. Adam's two slots fit arithmetically, but the
        # tunnel's AOT execution path does not honor buffer donation, so
        # step in+out Adam state alone (2 x 7.9 GB) exceeds HBM.
        mom = opt.Momentum(learning_rate=1e-4, momentum=0.9,
                           parameters=list(model.parameters()))
        prog = compile_train_step(model, mom, s, loss_method="loss")
        rng = np.random.default_rng(0)
        B, T = 4, 2048
        ids = prog._put_data(
            rng.integers(0, model.cfg.vocab_size, (B, T)).astype(np.int64))

        def step():
            return prog.step(ids, ids)

        dt = _timed_steps(step, n_short=1, n_long=5)
        tps = B * T / dt
        _emit("5_gpt3_1p3b_single_chip_bf16_remat", tps, "tokens/s",
              {"mfu": _mfu(tps, model, T), "params_dtype": "bfloat16",
               "optimizer": "momentum_bf16", "recompute": "per-block"})
        return

    def strat(nn_):
        s = DistributedStrategy()
        s.amp = not smoke
        s.recompute = True
        s.pipeline = True
        s.hybrid_configs.pp_degree = 2 if nn_ >= 2 else 1
        s.pipeline_configs.accumulate_steps = 4
        return s

    pp = 2 if n >= 2 else 1
    dp = max(n // pp, 1)
    # microbatch dim (B / accumulate_steps) must divide by dp
    B = 4 * dp * (1 if smoke else 4)
    T = 64 if smoke else 2048
    tps, prog = _compiled_lm(mk, strat, B, T, smoke)
    _emit("5_gpt3_1p3b_pp_recompute" if not smoke
          else "5_gpt_tiny_pp_smoke", tps, "tokens/s",
          {"pp": int(prog.mesh.shape.get("pp", 1)),
           "dp": int(prog.mesh.shape.get("dp", 1))})


CONFIGS = {1: config1_lenet, 2: config2_resnet50, 3: config3_bert,
           4: config4_gpt2_345m_zero2, 5: config5_gpt3_1p3b_pp}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", type=int, choices=sorted(CONFIGS))
    ap.add_argument("--all", action="store_true")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes (auto on CPU)")
    ns = ap.parse_args()
    import jax
    smoke = ns.smoke or jax.devices()[0].platform == "cpu"
    targets = sorted(CONFIGS) if ns.all or ns.config is None else [ns.config]
    for c in targets:
        CONFIGS[c](smoke)


if __name__ == "__main__":
    main()
