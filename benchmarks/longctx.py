"""Long-context single-chip sweep (benchmarks/RESULTS.md table): GPT-2
124M geometry at T in {1024, 4096, 8192, 16384}, bf16 AMP, strategy-
compiled train step. Prints one JSON line per length with tokens/s and
MFU (flops_per_token includes the quadratic attention term).

    python benchmarks/longctx.py                 # full sweep on TPU
    python benchmarks/longctx.py --seqs 4096
    PT_FLASH_FWD_BLOCKS=1024,2048 python benchmarks/longctx.py ...
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_one(T, batch, n_warm=2, n_meas=6):
    import jax

    import paddle_tpu as paddle
    import paddle_tpu.optimizer as opt
    from paddle_tpu.distributed.fleet.strategy import DistributedStrategy
    from paddle_tpu.distributed.fleet.compiler import compile_train_step
    from paddle_tpu.models import GPT, GPTConfig
    from bench import peak_flops

    cfg = GPTConfig(max_seq_len=max(T, 1024))      # GPT-2 124M geometry
    paddle.seed(0)
    model = GPT(cfg)
    model.eval()
    s = DistributedStrategy()
    s.amp = True
    adam = opt.Adam(learning_rate=1e-4, parameters=list(model.parameters()))
    prog = compile_train_step(model, adam, s, loss_method="loss")
    rng = np.random.default_rng(0)
    ids = prog._put_data(
        rng.integers(0, cfg.vocab_size, (batch, T)).astype(np.int32))

    # marginal-step estimator (bench.py): through the remote-TPU tunnel
    # the only reliable sync is a VALUE fetch (block_until_ready doesn't
    # round-trip), so time two window sizes ending in one float() each —
    # the constant RTT cancels in the difference
    def window(n):
        t0 = time.perf_counter()
        for _ in range(n):
            loss = prog.step(ids, ids)
        float(loss)
        return time.perf_counter() - t0

    window(n_warm)
    n_short, n_long = 2, 2 + n_meas
    dts = []
    for _ in range(2):
        t_s = window(n_short)
        t_l = window(n_long)
        dts.append((t_l - t_s) / (n_long - n_short))
    dt = min(d for d in dts if d > 0)
    tps = batch * T / dt
    mfu = tps * model.flops_per_token(T) / peak_flops()
    rec = {"seq_len": T, "batch": batch, "tokens_per_s": round(tps),
           "step_ms": round(dt * 1e3, 1), "mfu": round(mfu, 4)}
    print(json.dumps(rec), flush=True)
    return rec


BATCHES = {1024: 16, 4096: 4, 8192: 2, 16384: 1}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", type=int, nargs="*",
                    default=[1024, 4096, 8192, 16384])
    ap.add_argument("--batch", type=int, default=None)
    args = ap.parse_args()
    for T in args.seqs:
        run_one(T, args.batch or BATCHES[T])


if __name__ == "__main__":
    main()
