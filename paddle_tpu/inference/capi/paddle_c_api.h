/* paddle_tpu inference C API.
 *
 * Reference shape: /root/reference/paddle/fluid/inference/capi/
 * (PD_NewPredictor / PD_PredictorRun / PD_ZeroCopy tensors) and the Go
 * bindings layered on it (go/paddle/{config,predictor,tensor}.go).
 * There the predictor links into the client process; the TPU runtime
 * (XLA/PJRT + Python) cannot, so this client speaks the serve daemon's
 * wire protocol (inference/serve.py) over TCP — same capability, the
 * process-separated deployment shape TPU serving uses anyway.
 *
 * Wire dialects: this client speaks 'PDI1' (legacy) frames only. The
 * server also understands an optional 'PDI2' trace-context dialect
 * (docs/observability.md) but replies PDI2 ONLY to PDI2 requests, so
 * a PDI1 client never sees a byte it does not expect — no change here
 * is needed as servers upgrade.
 *
 * Decode mode (serve --decode, docs/serving.md): per-token streaming
 * rides the PDI2 dialect only. A PDI1 client posting an int32 token
 * prompt to a decode daemon gets ONE reply frame carrying the fully
 * accumulated generated tokens at server-default settings — again a
 * frame layout this client already parses, so no change here either.
 *
 * Build:  cc -o app app.c paddle_c_api.c
 * Use:
 *   PD_Predictor* p = PD_PredictorConnect("127.0.0.1", 9000);
 *   PD_Tensor in = {PD_FLOAT32, 2, (int64_t[]){1, 784}, data};
 *   PD_Tensor* outs; int n_out;
 *   PD_PredictorRun(p, &in, 1, &outs, &n_out);
 *   ... outs[0].data ...
 *   PD_FreeTensors(outs, n_out);
 *   PD_PredictorDelete(p);
 */
#ifndef PADDLE_TPU_C_API_H_
#define PADDLE_TPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef enum {
  PD_FLOAT32 = 0,
  PD_FLOAT64 = 1,
  PD_INT32 = 2,
  PD_INT64 = 3,
  PD_UINT8 = 4,
  PD_BOOL = 5,
} PD_DataType;

typedef struct {
  PD_DataType dtype;
  int32_t ndim;
  int64_t* shape; /* length ndim */
  void* data;     /* row-major payload */
} PD_Tensor;

typedef struct PD_Predictor PD_Predictor;

/* NULL on connection failure. */
PD_Predictor* PD_PredictorConnect(const char* host, int port);

/* Run one inference. Returns 0 on success; on failure returns -1 and
 * PD_GetLastError() describes the cause (including model-side errors
 * relayed from the server). *outs is malloc'd (free with PD_FreeTensors). */
int PD_PredictorRun(PD_Predictor* p, const PD_Tensor* ins, int n_in,
                    PD_Tensor** outs, int* n_out);

void PD_FreeTensors(PD_Tensor* ts, int n);
void PD_PredictorDelete(PD_Predictor* p);
const char* PD_GetLastError(void);

/* Wall-clock budget for one request/reply round trip (applies to both
 * send and recv). Under the daemon's dynamic batching a request may wait
 * up to its batch deadline before executing; this caps how long the
 * client blocks on a wedged daemon instead of hanging forever. seconds
 * <= 0 restores fully blocking I/O. Returns 0 on success.
 *
 * A round trip that times out (or otherwise fails mid-frame) POISONS the
 * connection: the stream may hold partial reply bytes, so every later
 * PD_PredictorRun on the handle fails fast with a "poisoned" error
 * instead of parsing stale bytes. Delete the predictor and reconnect. */
int PD_PredictorSetTimeout(PD_Predictor* p, double seconds);

/* Re-dial the endpoint this predictor was created with and reset the
 * poisoned flag — the recovery half of the poisoning contract above: a
 * retry loop keeps the same PD_Predictor* across a daemon restart or a
 * timed-out round trip instead of rebuilding its state. The configured
 * timeout (PD_PredictorSetTimeout) is re-applied to the new connection.
 * On failure returns -1 and the handle is left unchanged (a poisoned
 * handle stays poisoned, so callers can keep retrying). */
int PD_PredictorReconnect(PD_Predictor* p);

int64_t PD_TensorNumel(const PD_Tensor* t);

#ifdef __cplusplus
}
#endif

#endif /* PADDLE_TPU_C_API_H_ */
