/* C client for the paddle_tpu inference serve daemon (serve.py protocol).
 * See paddle_c_api.h for the reference-parity rationale. */
#include "paddle_c_api.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/socket.h>
#include <sys/time.h>
#include <unistd.h>

#define PD_MAGIC 0x31494450u /* 'PDI1' */
#define PD_ERR 0xFFFFFFFFu

static __thread char g_err[512];

struct PD_Predictor {
  int fd;
  /* Set after a timed-out or short read/write mid-round-trip: the stream
   * may hold a partial frame, so any further request would parse stale
   * bytes as a fresh reply. Poisoned handles fail fast; reconnect. */
  int broken;
  /* Remembered endpoint + timeout so PD_PredictorReconnect can re-dial
   * and restore the handle in place (failover/retry loops keep the same
   * PD_Predictor* across backend restarts). */
  char host[64];
  int port;
  double timeout_s; /* <= 0: fully blocking */
};

const char* PD_GetLastError(void) { return g_err; }

static void set_err(const char* msg) {
  snprintf(g_err, sizeof(g_err), "%s", msg);
}

static int read_full(int fd, void* buf, size_t n) {
  char* p = (char*)buf;
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return -1;
    p += r;
    n -= (size_t)r;
  }
  return 0;
}

static int write_full(int fd, const void* buf, size_t n) {
  const char* p = (const char*)buf;
  while (n) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) return -1;
    p += r;
    n -= (size_t)r;
  }
  return 0;
}

static size_t dtype_size(PD_DataType dt) {
  switch (dt) {
    case PD_FLOAT32: return 4;
    case PD_FLOAT64: return 8;
    case PD_INT32: return 4;
    case PD_INT64: return 8;
    case PD_UINT8: return 1;
    case PD_BOOL: return 1;
  }
  return 0;
}

int64_t PD_TensorNumel(const PD_Tensor* t) {
  int64_t n = 1;
  for (int32_t i = 0; i < t->ndim; ++i) n *= t->shape[i];
  return n;
}

static int dial(const char* host, int port) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) {
    set_err("socket() failed");
    return -1;
  }
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  if (inet_pton(AF_INET, host, &addr.sin_addr) != 1) {
    set_err("inet_pton: numeric IPv4 host required");
    close(fd);
    return -1;
  }
  if (connect(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0) {
    set_err("connect() failed — is the serve daemon running?");
    close(fd);
    return -1;
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

static int apply_timeout(int fd, double seconds) {
  struct timeval tv;
  if (seconds <= 0) {
    tv.tv_sec = 0; /* zero timeval = blocking mode */
    tv.tv_usec = 0;
  } else {
    tv.tv_sec = (time_t)seconds;
    tv.tv_usec = (suseconds_t)((seconds - (double)tv.tv_sec) * 1e6);
  }
  if (setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv)) != 0 ||
      setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv)) != 0) {
    set_err("setsockopt(SO_RCVTIMEO/SO_SNDTIMEO) failed");
    return -1;
  }
  return 0;
}

PD_Predictor* PD_PredictorConnect(const char* host, int port) {
  int fd = dial(host, port);
  if (fd < 0) return NULL;
  PD_Predictor* p = (PD_Predictor*)malloc(sizeof(PD_Predictor));
  p->fd = fd;
  p->broken = 0;
  snprintf(p->host, sizeof(p->host), "%s", host);
  p->port = port;
  p->timeout_s = 0;
  return p;
}

int PD_PredictorSetTimeout(PD_Predictor* p, double seconds) {
  if (apply_timeout(p->fd, seconds) != 0) return -1;
  p->timeout_s = seconds;
  return 0;
}

int PD_PredictorReconnect(PD_Predictor* p) {
  if (!p) {
    set_err("NULL predictor");
    return -1;
  }
  int fd = dial(p->host, p->port);
  if (fd < 0) return -1; /* handle unchanged (still poisoned if it was) */
  if (p->timeout_s > 0 && apply_timeout(fd, p->timeout_s) != 0) {
    close(fd);
    return -1;
  }
  close(p->fd);
  p->fd = fd;
  p->broken = 0;
  return 0;
}

int PD_PredictorRun(PD_Predictor* p, const PD_Tensor* ins, int n_in,
                    PD_Tensor** outs, int* n_out) {
  *outs = NULL;
  *n_out = 0;
  if (p->broken) {
    set_err(
        "connection poisoned by an earlier timeout/short read — the wire "
        "stream is desynced; delete this predictor and reconnect");
    return -1;
  }
  uint32_t hdr[2] = {PD_MAGIC, (uint32_t)n_in};
  if (write_full(p->fd, hdr, sizeof(hdr)) != 0) goto io_err;
  for (int i = 0; i < n_in; ++i) {
    uint8_t meta[2] = {(uint8_t)ins[i].dtype, (uint8_t)ins[i].ndim};
    if (write_full(p->fd, meta, 2) != 0) goto io_err;
    if (write_full(p->fd, ins[i].shape,
                   sizeof(int64_t) * (size_t)ins[i].ndim) != 0)
      goto io_err;
    if (write_full(p->fd, ins[i].data,
                   dtype_size(ins[i].dtype) *
                       (size_t)PD_TensorNumel(&ins[i])) != 0)
      goto io_err;
  }
  uint32_t rhdr[2];
  if (read_full(p->fd, rhdr, sizeof(rhdr)) != 0) goto io_err;
  if (rhdr[0] != PD_MAGIC) {
    p->broken = 1;
    set_err("protocol desync (bad magic)");
    return -1;
  }
  if (rhdr[1] == PD_ERR) {
    uint32_t mlen;
    if (read_full(p->fd, &mlen, 4) != 0) goto io_err;
    /* drain the WHOLE message (keeps the persistent connection in sync),
     * truncate only the copy into g_err */
    uint32_t keep = mlen < sizeof(g_err) - 1 ? mlen : sizeof(g_err) - 1;
    if (read_full(p->fd, g_err, keep) != 0) goto io_err;
    g_err[keep] = '\0';
    for (uint32_t left = mlen - keep; left;) {
      char sink[256];
      uint32_t take = left < sizeof(sink) ? left : (uint32_t)sizeof(sink);
      if (read_full(p->fd, sink, take) != 0) goto io_err;
      left -= take;
    }
    return -1;
  }
  int n = (int)rhdr[1];
  PD_Tensor* ts = (PD_Tensor*)calloc((size_t)n, sizeof(PD_Tensor));
  for (int i = 0; i < n; ++i) {
    uint8_t meta[2];
    if (read_full(p->fd, meta, 2) != 0) goto io_err_free;
    ts[i].dtype = (PD_DataType)meta[0];
    ts[i].ndim = meta[1];
    ts[i].shape = (int64_t*)malloc(sizeof(int64_t) * (size_t)meta[1]);
    if (read_full(p->fd, ts[i].shape,
                  sizeof(int64_t) * (size_t)meta[1]) != 0)
      goto io_err_free;
    size_t bytes = dtype_size(ts[i].dtype) * (size_t)PD_TensorNumel(&ts[i]);
    ts[i].data = malloc(bytes);
    if (read_full(p->fd, ts[i].data, bytes) != 0) goto io_err_free;
  }
  *outs = ts;
  *n_out = n;
  return 0;

io_err_free:
  PD_FreeTensors(ts, n);
io_err:
  /* a failed round trip (timeout included) leaves an unknown number of
   * frame bytes in flight: poison the handle so the next Run cannot
   * parse stale bytes as its reply */
  p->broken = 1;
  set_err("i/o error talking to serve daemon");
  return -1;
}

void PD_FreeTensors(PD_Tensor* ts, int n) {
  if (!ts) return;
  for (int i = 0; i < n; ++i) {
    free(ts[i].shape);
    free(ts[i].data);
  }
  free(ts);
}

void PD_PredictorDelete(PD_Predictor* p) {
  if (!p) return;
  close(p->fd);
  free(p);
}
