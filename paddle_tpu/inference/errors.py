"""Typed serving errors: machine-parseable failure classes on the wire.

The serve wire protocol's error frame carries one UTF-8 message. For a
resilient fleet that is not enough — a front router, a retrying client,
and a load shedder all need to tell "this request is malformed" (never
retry) from "the fleet is overloaded" (back off) from "a backend died
mid-flight" (fail over). The convention here mirrors gRPC status codes:
a typed error's frame message is ``CODE: detail`` with CODE one of the
``ERR_*`` constants, and :func:`error_code` recovers the code from a
received message (``None`` for legacy untyped errors, which clients
must treat as non-retryable).

Every layer raises :class:`TypedServeError` (or stamps ``.code`` onto
an existing exception via :func:`tag_code`); the wire layer in
``serve.py`` formats the frame, and ``router.py`` both parses incoming
codes and emits its own.
"""
from __future__ import annotations

__all__ = ["TypedServeError", "error_code", "tag_code",
           "ERR_UNAVAILABLE", "ERR_RESOURCE_EXHAUSTED",
           "ERR_DEADLINE_EXCEEDED", "ERR_INVALID_ARGUMENT",
           "ERR_INTERNAL", "ERR_FAILED_PRECONDITION",
           "RETRYABLE_CODES", "WIRE_ERROR_CODES"]

# a dead/draining dependency: safe to fail over to another backend
ERR_UNAVAILABLE = "UNAVAILABLE"
# admission control refused the request: back off, do NOT fail over
# (every backend is past its watermark — retrying amplifies the overload)
ERR_RESOURCE_EXHAUSTED = "RESOURCE_EXHAUSTED"
# the server-side request deadline expired in queue+execute
ERR_DEADLINE_EXCEEDED = "DEADLINE_EXCEEDED"
# the request itself is malformed; retrying anywhere cannot help
ERR_INVALID_ARGUMENT = "INVALID_ARGUMENT"
# an unexpected server-side fault (model error, bug)
ERR_INTERNAL = "INTERNAL"
# the operation's precondition does not hold on THIS peer (e.g. a
# kv_handoff whose page geometry / dtype / model fingerprint mismatch
# the receiving engine): retrying the same operation cannot help, but
# the caller has a defined fallback (re-prefill locally)
ERR_FAILED_PRECONDITION = "FAILED_PRECONDITION"

WIRE_ERROR_CODES = (ERR_UNAVAILABLE, ERR_RESOURCE_EXHAUSTED,
                    ERR_DEADLINE_EXCEEDED, ERR_INVALID_ARGUMENT,
                    ERR_INTERNAL, ERR_FAILED_PRECONDITION)

# codes a router may answer by trying ANOTHER backend; everything else is
# either deterministic (INVALID_ARGUMENT, INTERNAL) or made worse by a
# retry (RESOURCE_EXHAUSTED, DEADLINE_EXCEEDED)
RETRYABLE_CODES = frozenset({ERR_UNAVAILABLE})


class TypedServeError(RuntimeError):
    """A serving-path failure with a wire-visible status code."""

    def __init__(self, code: str, detail: str = ""):
        if code not in WIRE_ERROR_CODES:
            raise ValueError(f"unknown serve error code {code!r}")
        self.code = code
        super().__init__(f"{code}: {detail}" if detail else code)


def tag_code(exc: BaseException, code: str) -> BaseException:
    """Stamp a wire error code onto an existing exception (best effort —
    some builtin exceptions refuse new attributes)."""
    try:
        exc.code = code
    except Exception:
        pass
    return exc


def error_code(message: str):
    """The ``ERR_*`` code a wire error message carries, or ``None`` for
    a legacy untyped message."""
    if not message:
        return None
    head = message.split(":", 1)[0].strip()
    return head if head in WIRE_ERROR_CODES else None
