"""Continuous-batching autoregressive decode engine (paged KV cache).

The DynamicBatcher serves stateless one-shot requests; LLM traffic is
iterative — every request is a prefill followed by many single-token
steps, and requests arrive and finish mid-flight. This engine is the
token-level analog of the batcher's shape-bucket design, over a PAGED
KV cache instead of per-slot contiguous panels:

  * the KV store is one device-resident page pool
    (`[layers, pages, page_tokens, heads, head_dim]` for K and V) plus
    a per-sequence int32 block table; `memory.page_allocator` hands out
    refcounted page ids. Admission allocates pages, eviction releases
    them — capacity growth is a wider block table, never a cache copy
    (the contiguous engine re-packed the whole pool on every rung
    change);
  * the compute core is `models.gpt.gpt_paged_decode_fns` — `prefill`
    builds a request's K/V panel in one pass (panel rows are then
    scattered into pool pages), `paged_step` advances EVERY active
    request one token, writing through the block table and attending
    via `ops.pallas.decode_attention.paged_decode_attention`;
  * all device entry points run through an `AotCache` — prefill per
    prompt rung, the step per (batch-rung x page-rung) bucket, page
    writes per page rung, plus one traced-scalar copy-on-write
    executable — so after `warmup()` a steady-state token stream
    compiles nothing, across any admission/eviction churn;
  * **prefix sharing**: a hash trie caches page-aligned prompt
    prefixes. A second request with the same system prompt maps the
    cached pages (refcount++) and only prefills its tail — the tail
    tokens ride the normal batched decode step, so a hit admission does
    zero extra device work. A slot's first write into a shared page
    triggers copy-on-write through the allocator's refcounts;
  * pool exhaustion is typed RESOURCE_EXHAUSTED backpressure on the
    victim stream (after LRU-evicting cold prefix-cache pages), never
    an engine crash — batch-mates keep streaming;
  * sampling is host-side numpy (greedy, or temperature with optional
    top-k), so the device graph stays deterministic per shape.

Streams: `submit()` returns a `DecodeStream`; tokens are pushed as they
are sampled (serve.py forwards them as incremental PDI2 frames), and a
failed request gets a typed error while its batch-mates keep streaming.
Chaos sites: `decode.stream` fires per token delivery,
`decode.page_alloc` per page allocation, `decode.preempt` per
preemption attempt, `page.migrate` per host-tier migration batch.

**Host-RAM KV tiering** (docs/serving.md "KV tiering", opt-in via
``host_pages=`` / PADDLE_TPU_DECODE_HOST_PAGES): with a
`memory.migration.TieredPageAllocator` + `MigrationEngine` behind the
pool, HBM becomes a cache over a much larger host-RAM page store.
Under pool pressure the engine *spills* cold trie-only pages (cold
shared prefixes, preempted streams' stashed state, finished
conversations) to pinned host arenas instead of destructively evicting
them — the trie entry swaps its device page for a negative host
handle. An admission whose prefix continues in the host tier parks on
an async *refetch* (only that stream waits; its slot stays free) and
then resumes with a full device hit, byte-identical content. QoS
preemption composes: the stash-to-trie pages ride the same
spill/restore path, so preempt-resume becomes a page copy instead of a
recompute.

Multi-tenant QoS (docs/serving.md "Multi-tenant QoS"): every request
carries a ``tenant`` (default ``"default"``) and an integer
``priority``. Admission is weighted-fair — the scheduler picks the
most-underserved tenant by weighted virtual time (tokens served /
weight, PADDLE_TPU_TENANT_WEIGHTS) — and per-tenant token-rate quotas
(PADDLE_TPU_TENANT_QUOTA, a token bucket per tenant) defer a tenant's
queued requests instead of running them. When a strictly
higher-priority request cannot be admitted, the lowest-priority active
slot is *preempted to host*: its pages go back to the allocator (full
pages are stashed in the prefix cache so a quick resume re-maps them),
prompt + tokens-so-far + seed stay host-side, and the request re-enters
admission when pressure drops. Resume is a fresh admission over
``prompt + generated``; the per-(seed, position) counter RNG makes the
resumed stream token-identical to an unpreempted run, and the live
`DecodeStream` survives preemption so the client-facing seq stream is
gapless.

`SpecDecodeEngine` layers draft-and-verify speculative decoding on the
same machinery: a small draft GPT runs k greedy steps per tick over its
own page pool (same allocator, same block tables), the target scores
all k+1 positions in one `gpt_paged_verify_fns` forward, and a
rejection rolls back by truncating `cache_len` and releasing the
stranded block-table tail (`PageAllocator.release_range`). Enabled via
PADDLE_TPU_DECODE_SPECULATE / PADDLE_TPU_DECODE_DRAFT_MODEL or serve's
--speculate-k/--draft-model; default off.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .. import profiler
from ..core import flags as _flags
from ..core import monitor
from ..jit.compile_cache import AotCache
from ..memory.migration import (HostPageStore, MigrationEngine,
                                TieredPageAllocator, deserialize_pages,
                                serialize_pages, tier_metrics)
from ..memory.page_allocator import (PageAllocator, PageExhausted,
                                     copy_page, gather_pages, write_pages)
from ..models.gpt import (GPTConfig, gpt_paged_decode_fns,
                          gpt_paged_prefill_fns, gpt_paged_rollout_fns,
                          gpt_paged_verify_fns)
from ..observability import counter, gauge, histogram
from ..observability import memz as _memz
from ..observability.spans import SpanRecorder, next_request_id
from ..observability.tracez import RING as _RING
from ..quant.kv import (kv_pool_sds, kv_pool_zeros, quantize_kv,
                        validate_kv_dtype)
from ..quant.ptq import is_quantized as _params_quantized
from ..quant.ptq import quantize_params
from ..testing import chaos
from .batching import (_WARMUP_SIG_CAP, bucket_ladder, next_bucket,
                       tenant_quotas as _tenant_quotas,
                       tenant_weights as _tenant_weights)
from .errors import (ERR_FAILED_PRECONDITION, ERR_INVALID_ARGUMENT,
                     ERR_RESOURCE_EXHAUSTED, ERR_UNAVAILABLE,
                     TypedServeError)

DEFAULT_MAX_SLOTS = 8          # CPU fallback when HBM stats are absent
DEFAULT_MAX_NEW_TOKENS = 64
DEFAULT_PAGE_TOKENS = 16       # mirrors PADDLE_TPU_DECODE_PAGE_TOKENS

_METRICS = None


def _decode_metrics():
    """Register (idempotently) and return the paddle_tpu_decode_* family."""
    global _METRICS
    if _METRICS is None:
        _METRICS = {
            "tokens": counter(
                "paddle_tpu_decode_tokens_total",
                "Tokens sampled by the decode engine (prefill + steps)"),
            "steps": counter(
                "paddle_tpu_decode_steps_total",
                "Batched decode steps executed (one per token column)"),
            "prefills": counter(
                "paddle_tpu_decode_prefills_total",
                "Requests admitted through the prefill phase"),
            "evictions": counter(
                "paddle_tpu_decode_cache_evictions_total",
                "KV-cache slot evictions by reason",
                labelnames=("reason",)),
            "occupancy": gauge(
                "paddle_tpu_decode_slot_occupancy",
                "Active sequences / slot-pool capacity (0..1)"),
            "active": gauge(
                "paddle_tpu_decode_active_requests",
                "Sequences currently holding a KV slot"),
            "prefill_latency": histogram(
                "paddle_tpu_decode_prefill_latency_seconds",
                "Prefill execution latency per admitted request"),
            "step_latency": histogram(
                "paddle_tpu_decode_step_latency_seconds",
                "Batched decode-step execution latency"),
            "ttft": histogram(
                "paddle_tpu_decode_ttft_seconds",
                "Submit-to-first-token latency per request"),
            # paged KV pool
            "page_pool_size": gauge(
                "paddle_tpu_decode_page_pool_pages",
                "Allocatable KV pages in the decode page pool"),
            "page_in_use": gauge(
                "paddle_tpu_decode_page_in_use",
                "KV pages currently allocated (refcount >= 1)"),
            "page_shared": gauge(
                "paddle_tpu_decode_page_shared",
                "KV pages mapped by more than one owner (refcount > 1)"),
            "page_fragmentation": gauge(
                "paddle_tpu_decode_page_fragmentation",
                "Free-list fragmentation of the KV page pool (0..1)"),
            "page_allocs": counter(
                "paddle_tpu_decode_page_allocs_total",
                "KV pages handed out by the decode page allocator"),
            "page_alloc_failures": counter(
                "paddle_tpu_decode_page_alloc_failures_total",
                "Page allocations refused (pool exhausted or chaos)"),
            "cow": counter(
                "paddle_tpu_decode_page_cow_copies_total",
                "Copy-on-write page copies (first write into a shared "
                "page)"),
            # prefix cache
            "prefix_hits": counter(
                "paddle_tpu_decode_prefix_hits_total",
                "Admissions that mapped at least one cached prefix page"),
            "prefix_misses": counter(
                "paddle_tpu_decode_prefix_misses_total",
                "Admissions that found no cached prefix page"),
            "prefix_hit_tokens": counter(
                "paddle_tpu_decode_prefix_hit_tokens_total",
                "Prompt tokens served from cached prefix pages"),
            "prefix_lookup_tokens": counter(
                "paddle_tpu_decode_prefix_lookup_tokens_total",
                "Prompt tokens offered to prefix-cache lookup"),
            "prefix_cached_pages": gauge(
                "paddle_tpu_decode_prefix_cached_pages",
                "Pages pinned by the prefix-cache trie"),
            "prefix_evictions": counter(
                "paddle_tpu_decode_prefix_evictions_total",
                "Prefix-cache entries LRU-evicted under pool pressure"),
            # speculative decoding
            "spec_draft_steps": counter(
                "paddle_tpu_decode_spec_draft_steps_total",
                "Batched draft-model decode steps executed"),
            "spec_accepted": counter(
                "paddle_tpu_decode_spec_accepted_tokens_total",
                "Drafted tokens accepted by target verification"),
            "spec_rejected": counter(
                "paddle_tpu_decode_spec_rejected_tokens_total",
                "Drafted tokens rejected by target verification"),
            "spec_acceptance": gauge(
                "paddle_tpu_decode_spec_acceptance_rate",
                "Cumulative accepted/drafted token ratio (0..1)"),
            "page_rollback_released": counter(
                "paddle_tpu_decode_page_rollback_released_total",
                "Page references released by speculative rollback "
                "(pages stranded past the last accepted token)"),
            # multi-tenant QoS
            "tenant_tokens": counter(
                "paddle_tpu_tenant_decode_tokens_total",
                "Tokens sampled by the decode engine per tenant",
                labelnames=("tenant",)),
            "tenant_admissions": counter(
                "paddle_tpu_tenant_admissions_total",
                "Requests admitted into a decode slot per tenant "
                "(resumes after preemption count again)",
                labelnames=("tenant",)),
            "tenant_shed": counter(
                "paddle_tpu_tenant_shed_total",
                "Requests refused at decode admission because the "
                "tenant was past its weighted share of the pending "
                "queue (typed RESOURCE_EXHAUSTED)",
                labelnames=("tenant",)),
            "tenant_quota_deferred": counter(
                "paddle_tpu_tenant_quota_deferred_total",
                "Requests deferred in the pending queue because the "
                "tenant's token-rate quota bucket was empty "
                "(PADDLE_TPU_TENANT_QUOTA)",
                labelnames=("tenant",)),
            "preemptions": counter(
                "paddle_tpu_decode_preemptions_total",
                "Active decode slots evicted to host so a "
                "higher-priority request could run"),
            "preempt_resumes": counter(
                "paddle_tpu_decode_preempt_resumes_total",
                "Preempted requests re-admitted into a decode slot"),
            "preempted_tokens": counter(
                "paddle_tpu_decode_preempted_tokens_total",
                "Generated tokens stashed host-side at preemption "
                "(re-prefilled or prefix-cache-mapped at resume)"),
            "preempted_waiting": gauge(
                "paddle_tpu_decode_preempted_waiting",
                "Preempted requests currently parked host-side "
                "awaiting re-admission"),
            # quantized serving
            "kv_page_bytes": gauge(
                "paddle_tpu_decode_kv_page_bytes",
                "HBM bytes one K+V page occupies at the engine's pool "
                "dtype (int8 pools: payload + per-row scales)"),
            "kv_quantized": gauge(
                "paddle_tpu_decode_kv_quantized",
                "1 when the engine's KV page pool is int8, 0 for fp32"),
        }
    return _METRICS


_HANDOFF_METRICS = None


def _handoff_metrics():
    """Register (idempotently) and return the paddle_tpu_handoff_*
    family — the engine-side half of disaggregated prefill/decode
    serving (docs/observability.md). Router-side orchestration counters
    live in `router.py` under paddle_tpu_router_*."""
    global _HANDOFF_METRICS
    if _HANDOFF_METRICS is None:
        _HANDOFF_METRICS = {
            "exports": counter(
                "paddle_tpu_handoff_exports_total",
                "KV-page handoffs exported by a prefill worker"),
            "imports": counter(
                "paddle_tpu_handoff_imports_total",
                "KV-page handoffs landed by a decode worker"),
            "rejects": counter(
                "paddle_tpu_handoff_rejects_total",
                "KV handoffs the receiving engine refused, by reason "
                "(compat, structure, checksum, exhausted, disabled)",
                labelnames=("reason",)),
            "pages": counter(
                "paddle_tpu_handoff_pages_total",
                "KV pages moved by handoffs, by direction "
                "(export, import)", labelnames=("direction",)),
            "bytes": counter(
                "paddle_tpu_handoff_bytes_total",
                "Serialized KV payload bytes moved by handoffs, by "
                "direction (export, import)", labelnames=("direction",)),
            "latency": histogram(
                "paddle_tpu_handoff_seconds",
                "Engine-side handoff latency by stage (export = "
                "prefill-if-miss + gather + serialize, import = "
                "validate + scatter + trie insert)",
                labelnames=("stage",)),
        }
    return _HANDOFF_METRICS


def kv_fingerprint(cfg: GPTConfig, eps: float, params: Dict) -> str:
    """16-hex-char identity of (config, eps, parameter names/shapes/
    dtypes). Two engines with equal fingerprints run the same forward
    over the same weights *layout*, so their KV pages are
    interchangeable — the model-identity leg of the KV-handoff compat
    contract. Weight VALUES are deliberately not hashed (hashing GBs of
    params per engine start is not worth catching an operator loading
    two different checkpoints of the same architecture under one
    fingerprint — the serve artifact prefix already pins the weights)."""
    spec = json.dumps(
        {"config": dataclasses.asdict(cfg), "eps": float(eps),
         "params": sorted((str(k), list(v.shape),
                           str(np.dtype(v.dtype)))
                          for k, v in params.items())},
        sort_keys=True)
    return hashlib.sha1(spec.encode()).hexdigest()[:16]


class _HandoffJob:
    """Pseudo-request for allocator accounting inside a KV handoff —
    `_alloc_pages` only reads `.id` (chaos detail, error messages) and
    `_owner_for` stamps its pages ``("handoff", id)``."""
    __slots__ = ("id",)

    def __init__(self):
        self.id = next_request_id()


_POOL_SEQ = [0]
_POOL_SEQ_LOCK = threading.Lock()


def _next_pool_label() -> str:
    """Unique page-pool label per engine in this process ("kv", "kv2",
    ...) so /memz and the mem gauges keep concurrent engines apart."""
    with _POOL_SEQ_LOCK:
        _POOL_SEQ[0] += 1
        n = _POOL_SEQ[0]
    return "kv" if n == 1 else f"kv{n}"


def _trie_owner(digest: bytes) -> tuple:
    """Allocator owner tag for a prefix-trie node (short digest hex)."""
    return ("trie", digest.hex()[:12])


def kv_slot_bytes(cfg: GPTConfig, capacity: Optional[int] = None) -> int:
    """HBM bytes one sequence's full K+V panel occupies at `capacity`
    (the contiguous-pool cost model; the paged analog is
    `kv_page_bytes` x pages actually mapped)."""
    cap = capacity or cfg.max_seq_len
    return cfg.layers * 2 * cap * cfg.heads * cfg.head_dim * 4


def kv_page_bytes(cfg: GPTConfig, page_tokens: int,
                  kv_dtype: str = "float32") -> int:
    """HBM bytes one K+V page occupies at the pool dtype. The int8 pool
    (quant/kv.py) pays 1 byte per element plus one fp32 scale per
    (token row, head) — 1 + 4/head_dim bytes/element vs 4 for fp32."""
    rows = cfg.layers * 2 * int(page_tokens) * cfg.heads
    if validate_kv_dtype(kv_dtype) == "int8":
        return rows * cfg.head_dim + rows * 4
    return rows * cfg.head_dim * 4


def default_slot_count(cfg: GPTConfig, hbm_fraction: float = 0.5,
                       fallback: int = DEFAULT_MAX_SLOTS) -> int:
    """Size the slot pool from live HBM stats: how many full-capacity KV
    panels fit in `hbm_fraction` of the free bytes. CPU (stats (0, 0))
    gets the fixed fallback so tests and benches behave identically."""
    used, limit = monitor.hbm_usage()
    if limit <= 0:
        return fallback
    free = max(limit - used, 0) * hbm_fraction
    return max(1, min(int(free // kv_slot_bytes(cfg)), 256))


def kv_capacity_ladder(max_seq_len: int,
                       floor: Optional[int] = None) -> List[int]:
    """Powers of two (times the floor) from the floor up to — and
    including — max_seq_len. The floor defaults to the page size so
    every rung is a formable page-granular capacity (no warmup
    signature the pool cannot realize)."""
    lo = int(floor) if floor else DEFAULT_PAGE_TOKENS
    if max_seq_len <= lo:
        return [int(max_seq_len)]
    vals, v = [], lo
    while v < max_seq_len:
        vals.append(v)
        v *= 2
    vals.append(int(max_seq_len))
    return sorted(set(vals))


class DecodeStream:
    """Consumer handle for one request's token stream.

    Events arrive in order: zero or more ``("token", tok, eos)`` then
    exactly one ``("done", tokens)`` — or a `TypedServeError` raised out
    of `next_event` / `result` if the stream died (engine stop, chaos,
    per-request failure)."""

    def __init__(self, req_id: int, prompt: List[int]):
        self.request_id = req_id
        self.prompt = list(prompt)
        self.tokens: List[int] = []      # generated so far (mirror)
        self.spec_drafted = 0            # speculative-decode stats
        self.spec_accepted = 0           # (stay 0 on the plain engine)
        self._q: queue.Queue = queue.Queue()
        self._pending: deque = deque()   # consumer-side unbatch buffer
        self._closed = False             # producer-side latch

    # -- producer (engine thread) ------------------------------------
    def _push_token(self, tok: int, eos: bool):
        if not self._closed:
            self.tokens.append(int(tok))
            self._q.put(("token", int(tok), bool(eos)))

    def _push_tokens(self, toks: List[int], eos: bool):
        # One queue put for a whole burst of committed tokens (the
        # speculative engine lands several per tick); `eos` applies to
        # the final token only — commits stop at the first eos, so an
        # earlier one can't occur. Consumers still see per-token
        # events: `_unbatch` expands the burst on their side.
        if not self._closed:
            toks = [int(t) for t in toks]
            self.tokens.extend(toks)
            self._q.put(("tokens", toks, bool(eos)))

    def _push_done(self):
        if not self._closed:
            self._closed = True
            self._q.put(("done", list(self.tokens)))

    def _push_error(self, err: TypedServeError):
        if not self._closed:
            self._closed = True
            self._q.put(("error", err))

    # -- consumer ----------------------------------------------------
    def _unbatch(self, ev):
        if ev[0] == "tokens":
            toks, eos = ev[1], ev[2]
            last = len(toks) - 1
            for i, t in enumerate(toks):
                self._pending.append(("token", t, eos and i == last))
            return self._pending.popleft()
        return ev

    def next_event(self, timeout: Optional[float] = None):
        if self._pending:
            return self._pending.popleft()
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TypedServeError(
                ERR_UNAVAILABLE,
                f"decode stream {self.request_id}: no event within "
                f"{timeout}s") from None
        if ev[0] == "error":
            raise ev[1]
        return self._unbatch(ev)

    def poll(self):
        """Non-blocking `next_event`: the next pending event, or None
        when the queue is momentarily empty. Raises the stream's typed
        error like `next_event` if the stream died. Lets a single
        collector sweep many streams without parking one blocked
        thread per stream."""
        if self._pending:
            return self._pending.popleft()
        try:
            ev = self._q.get_nowait()
        except queue.Empty:
            return None
        if ev[0] == "error":
            raise ev[1]
        return self._unbatch(ev)

    def events(self, timeout: Optional[float] = None):
        """Yield ("token", tok, eos) events until done; raises on error."""
        while True:
            ev = self.next_event(timeout=timeout)
            if ev[0] == "done":
                return
            yield ev

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream completes; returns generated tokens."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            left = None if deadline is None \
                else max(deadline - time.monotonic(), 0.0)
            ev = self.next_event(timeout=left)
            if ev[0] == "done":
                return ev[1]


DEFAULT_TENANT = "default"


class _Req:
    __slots__ = ("id", "prompt", "max_new", "temperature", "top_k",
                 "eos_id", "seed", "stream", "cache_len", "last_tok",
                 "generated", "pages", "input_tail", "feeding",
                 "t_submit", "t_admit", "prefill_s", "tenant", "priority",
                 "preempts", "deferred")

    def __init__(self, prompt, max_new, temperature, top_k, eos_id,
                 seed=None, tenant=DEFAULT_TENANT, priority=0):
        self.id = next_request_id()
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.seed = seed         # per-stream sampling seed (None -> engine RNG)
        self.stream = DecodeStream(self.id, prompt)
        self.cache_len = 0
        self.last_tok = 0
        self.generated: List[int] = []
        self.pages: List[int] = []       # block table (page ids, in order)
        self.input_tail: deque = deque() # prompt tokens still to feed
        self.feeding = False             # consuming prompt via the step
        self.t_submit = time.monotonic()
        self.t_admit = 0.0
        self.prefill_s = 0.0
        self.tenant = tenant
        self.priority = priority         # higher wins; may preempt lower
        self.preempts = 0                # times evicted to host
        self.deferred = False            # quota deferral counted once


class _SpecReq(_Req):
    """_Req plus speculative-decode state: how far the draft pool has
    been written, the slot's adaptive speculation depth, and acceptance
    accounting for the adaptive-k policy."""
    __slots__ = ("draft_len", "spec_k", "accept_ema", "drafted",
                 "accepted")

    def __init__(self, prompt, max_new, temperature, top_k, eos_id,
                 seed=None, tenant=DEFAULT_TENANT, priority=0):
        super().__init__(prompt, max_new, temperature, top_k, eos_id,
                         seed=seed, tenant=tenant, priority=priority)
        self.draft_len = 0       # draft-pool rows written (positions)
        self.spec_k = 1          # per-slot adaptive k (set at admission)
        self.accept_ema = 1.0    # EMA of per-tick acceptance rate
        self.drafted = 0
        self.accepted = 0


class _PrefixCache:
    """Hash trie of page-aligned prompt prefixes -> pool pages.

    Keys are a SHA-1 hash *chain* over full pages of prompt tokens —
    entry i's digest commits to pages 0..i, so one dict lookup per page
    walks the trie without storing token arrays. Every device-resident
    entry holds one allocator reference; `lookup` retains matched pages
    on the caller's behalf (so an entry evicted a microsecond later
    cannot free a page the caller is about to map).

    Eviction is **leaf-first LRU**: among evictable entries, ones with
    no live child go first (ordered by last-touch tick), and only when
    every candidate is mid-chain does the oldest interior entry go —
    so surviving entries stay reachable instead of silently orphaned.
    Each entry tracks its parent digest and a live-child count to make
    leaf status O(1); forced mid-chain removals bump the `orphaned`
    stat (the children remain cached but can never be looked up again).

    With a :class:`~paddle_tpu.memory.TieredPageAllocator` behind it,
    an entry's location may also be a negative **host handle**: the
    page content was spilled to the host tier. `lookup` stops at a
    spilled entry (the device chain ends there); the engine's tier path
    reads the continuation via `host_chain` and swaps locations back
    with `restore_entry` once the migration engine lands the pages.
    Single leaf lock, no device work or blocking calls under it; lock
    order is trie -> allocator everywhere."""

    def __init__(self, alloc: PageAllocator, page_tokens: int):
        self._alloc = alloc
        self._pt = int(page_tokens)
        self._lock = threading.Lock()
        # digest -> [loc, tick, parent_digest|None]; loc >= 0 is a
        # device page (one ref held), loc < 0 a host-tier handle
        self._entries: Dict[bytes, List] = {}
        self._kids: Dict[bytes, int] = {}     # digest -> live children
        self._tick = 0
        self._evictions = 0
        self._orphaned = 0

    def _digests(self, prompt: Sequence[int]) -> List[bytes]:
        h, out = b"", []
        for i in range(len(prompt) // self._pt):
            chunk = np.asarray(prompt[i * self._pt:(i + 1) * self._pt],
                               np.int64).tobytes()
            h = hashlib.sha1(h + chunk).digest()
            out.append(h)
        return out

    def _remove(self, d: bytes, ent: List):
        """Drop one entry (lock held): release its device ref or host
        slot, unlink from its parent, count stranded descendants."""
        del self._entries[d]
        parent = ent[2]
        if parent is not None and parent in self._kids:
            self._kids[parent] -= 1
            if self._kids[parent] <= 0:
                del self._kids[parent]
        self._orphaned += self._kids.pop(d, 0)
        if ent[0] >= 0:
            self._alloc.release(ent[0], owner=_trie_owner(d))
        else:
            self._alloc.host_drop(ent[0])

    def lookup(self, prompt: Sequence[int],
               owner: Optional[tuple] = None) -> Tuple[List[int], int]:
        """Longest *device-resident* cached page-aligned prefix of
        `prompt`. Returns (pages, hit_tokens); each returned page has
        been retained for the caller — attributed to the caller's
        `owner` tag — who owns releasing every one."""
        pages: List[int] = []
        with self._lock:
            self._tick += 1
            for d in self._digests(prompt):
                ent = self._entries.get(d)
                if ent is None or ent[0] < 0:
                    break
                self._alloc.retain(ent[0], owner=owner)
                ent[1] = self._tick
                pages.append(ent[0])
        return pages, len(pages) * self._pt

    def host_chain(self, prompt: Sequence[int],
                   start: int) -> List[Tuple[bytes, int]]:
        """The contiguous run of HOST-resident entries continuing the
        device hit (`start` = device pages matched). Returns
        [(digest, handle)]; an IN_FLIGHT or missing entry ends the run
        — the caller just gets a shorter refetch, which is always
        correct."""
        from ..memory.migration import Residency

        out: List[Tuple[bytes, int]] = []
        with self._lock:
            for d in self._digests(prompt)[max(start, 0):]:
                ent = self._entries.get(d)
                if ent is None or ent[0] >= 0:
                    break
                if self._alloc.residency(ent[0]) != Residency.HOST:
                    break
                out.append((d, ent[0]))
        return out

    def insert(self, prompt: Sequence[int], pages: Sequence[int]):
        """Cache `prompt`'s full pages (pages[i] holds prompt rows
        [i*pt, (i+1)*pt)); already-cached prefixes are left in place.
        A spilled (host) entry whose content is being re-inserted live
        is upgraded back to the device page — the host copy is
        redundant from that moment."""
        from ..memory.migration import Residency

        with self._lock:
            self._tick += 1
            prev = None
            for d, p in zip(self._digests(prompt), pages):
                ent = self._entries.get(d)
                if ent is None:
                    self._alloc.retain(p, owner=_trie_owner(d))
                    self._entries[d] = [int(p), self._tick, prev]
                    if prev is not None and prev in self._entries:
                        self._kids[prev] = self._kids.get(prev, 0) + 1
                elif ent[0] < 0 and \
                        self._alloc.residency(ent[0]) == Residency.HOST:
                    self._alloc.retain(p, owner=_trie_owner(d))
                    self._alloc.host_drop(ent[0])
                    ent[0] = int(p)
                    ent[1] = self._tick
                prev = d

    def _leaf_key(self, d: bytes, ent: List):
        return (1 if self._kids.get(d) else 0, ent[1])

    def evict(self, n: int) -> int:
        """Release up to `n` device-resident entries' pages, leaf-first
        LRU, re-deriving leaf status after every removal (so evicting a
        whole chain walks it tip-to-root instead of orphaning it)."""
        removed = 0
        with self._lock:
            while removed < max(n, 0):
                cands = [(d, e) for d, e in self._entries.items()
                         if e[0] >= 0]
                if not cands:
                    break
                d, e = min(cands, key=lambda x: self._leaf_key(*x))
                self._remove(d, e)
                removed += 1
            self._evictions += removed
        return removed

    # ------------------------------------------------- host-tier hooks

    def spill_victims(self, n: int) -> List[Tuple[bytes, int]]:
        """Up to `n` spillable entries, coldest leaves first: device-
        resident and trie-only (refcount 1 — nothing active maps the
        page, so its content is immutable and nobody stalls on it)."""
        with self._lock:
            cands = [(d, e) for d, e in self._entries.items()
                     if e[0] >= 0 and self._alloc.refcount(e[0]) == 1]
            cands.sort(key=lambda x: self._leaf_key(*x))
            return [(d, e[0]) for d, e in cands[:max(n, 0)]]

    def mark_spilled(self, d: bytes, page: int, handle: int) -> bool:
        """Swap an entry's location to its host handle and release the
        trie's device ref (this is what actually frees the page)."""
        with self._lock:
            ent = self._entries.get(d)
            if ent is None or ent[0] != page:
                return False
            ent[0] = int(handle)
            self._alloc.release(page, owner=_trie_owner(d))
            return True

    def restore_entry(self, d: bytes, handle: int, page: int) -> bool:
        """A refetch landed: point the entry back at a device page. The
        caller transfers its allocator reference to the trie. False if
        the entry moved on meanwhile (caller keeps the ref)."""
        with self._lock:
            ent = self._entries.get(d)
            if ent is None or ent[0] != handle:
                return False
            ent[0] = int(page)
            ent[1] = self._tick
            # the caller's allocator ref changes hands: attribution
            # follows it from the tier to this trie node
            self._alloc.retag(page, ("tier", handle), _trie_owner(d))
            return True

    def drop_by_handle(self, handle: int) -> bool:
        """Remove the entry parked on `handle` (failed migration): the
        cached content is gone, the stream degrades to a re-prefill."""
        with self._lock:
            for d, ent in self._entries.items():
                if ent[0] == handle:
                    self._remove(d, ent)
                    return True
        return False

    def drop_host_lru(self, n: int) -> int:
        """Drop up to `n` coldest HOST-resident entries to make room in
        the host tier (never IN_FLIGHT ones — a migration owns those
        slots)."""
        from ..memory.migration import Residency

        dropped = 0
        with self._lock:
            cands = sorted(
                ((d, e) for d, e in self._entries.items()
                 if e[0] < 0
                 and self._alloc.residency(e[0]) == Residency.HOST),
                key=lambda x: x[1][1])
            for d, e in cands[:max(n, 0)]:
                self._remove(d, e)
                dropped += 1
        return dropped

    def clear(self):
        with self._lock:
            for d, ent in self._entries.items():
                if ent[0] >= 0:
                    self._alloc.release(ent[0], owner=_trie_owner(d))
                else:
                    self._alloc.host_drop(ent[0])
            self._entries.clear()
            self._kids.clear()

    def stats(self) -> Dict:
        with self._lock:
            host = sum(1 for e in self._entries.values() if e[0] < 0)
            return {"cached_pages": len(self._entries),
                    "host_entries": host,
                    "evictions": self._evictions,
                    "orphaned": self._orphaned}


# Pure pool entry points (jit + AotCache'd by the engine): K and V move
# together so one executable covers both writes. Rows arrive fp32 from
# prefill; an int8 (data, scale) pool quantizes them inside the same
# executable, so the host never materializes a quantized panel.

def _write_kv_pages(k_pool, v_pool, k_rows, v_rows, page_ids):
    if isinstance(k_pool, tuple):
        k_rows = quantize_kv(k_rows)
        v_rows = quantize_kv(v_rows)
    return (write_pages(k_pool, k_rows, page_ids),
            write_pages(v_pool, v_rows, page_ids))


def _copy_kv_page(k_pool, v_pool, src, dst):
    return (copy_page(k_pool, src, dst), copy_page(v_pool, src, dst))


class DecodeEngine:
    """Slot-pool continuous batcher over the paged incremental GPT
    forward: fixed device page pool + per-slot block tables, prefix
    sharing with copy-on-write, typed backpressure on exhaustion."""

    _req_cls = _Req       # SpecDecodeEngine swaps in _SpecReq

    def __init__(self, model=None, *, cfg: Optional[GPTConfig] = None,
                 params: Optional[Dict] = None, eps: Optional[float] = None,
                 max_slots: Optional[int] = None,
                 max_new_tokens: int = DEFAULT_MAX_NEW_TOKENS,
                 eos_id: Optional[int] = None,
                 hbm_fraction: float = 0.5, seed: int = 0,
                 max_pending: Optional[int] = None,
                 page_tokens: Optional[int] = None,
                 num_pages: Optional[int] = None,
                 prefix_cache: Optional[bool] = None,
                 tenant_weights=None, tenant_quota=None,
                 preempt: Optional[bool] = None,
                 kv_dtype: Optional[str] = None,
                 host_pages: Optional[int] = None,
                 handoff: Optional[bool] = None):
        if model is not None:
            from .. import framework
            cfg = model.cfg
            params = framework.param_arrays(model)
            eps = model.ln_f._epsilon if eps is None else eps
        if cfg is None or params is None:
            raise ValueError("DecodeEngine needs a model or (cfg, params)")
        self.cfg = cfg
        self.eps = 1e-5 if eps is None else float(eps)
        self.params = {k: jnp.asarray(v) for k, v in params.items()}
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.max_slots = int(max_slots) if max_slots \
            else default_slot_count(cfg, hbm_fraction)
        self.max_pending = int(max_pending) if max_pending is not None \
            else 4 * self.max_slots
        self.page_tokens = int(
            page_tokens or _flags.env_value("PADDLE_TPU_DECODE_PAGE_TOKENS"))
        if self.page_tokens < 1:
            raise ValueError(f"page_tokens must be >= 1, "
                             f"got {self.page_tokens}")
        self.kv_dtype = validate_kv_dtype(
            kv_dtype if kv_dtype is not None
            else _flags.env_value("PADDLE_TPU_DECODE_KV_DTYPE"))
        self.batch_ladder = bucket_ladder(
            self.max_slots, env=_flags.env_value("PADDLE_TPU_DECODE_BUCKETS"))
        self.kv_ladder = kv_capacity_ladder(cfg.max_seq_len,
                                            floor=self.page_tokens)
        # block-table width rungs: pages needed to hold each kv rung
        self.page_ladder = sorted(
            {-(-r // self.page_tokens) for r in self.kv_ladder})
        self.pages_per_seq = -(-cfg.max_seq_len // self.page_tokens)
        # +1: page 0 is the reserved null/scratch page (table padding
        # and padded-batch writes land there, never on live data)
        self.num_pages = int(num_pages) if num_pages \
            else self.max_slots * self.pages_per_seq + 1
        hp = int(host_pages) if host_pages is not None \
            else int(_flags.env_value("PADDLE_TPU_DECODE_HOST_PAGES"))
        self.host_pages = max(hp, 0)
        pool_label = _next_pool_label()
        self._alloc = TieredPageAllocator(
            self.num_pages, host_pages=self.host_pages,
            label=pool_label) \
            if self.host_pages \
            else PageAllocator(self.num_pages, label=pool_label)
        # disaggregated prefill/decode KV handoff (docs/serving.md):
        # export gathers a prompt's full pages through `pgather`, import
        # lands them through `ptier` + a prefix-trie insert so the
        # follow-up stream admits as a prefix hit
        self.handoff = bool(_flags.env_value("PADDLE_TPU_DECODE_HANDOFF")) \
            if handoff is None else bool(handoff)
        use_prefix = prefix_cache if prefix_cache is not None \
            else bool(_flags.env_value("PADDLE_TPU_DECODE_PREFIX_CACHE"))
        # tiering spills and refetches *through* the trie — its entries
        # are the spill candidates and the resume index — and a handoff
        # import lands as a trie entry, so either mode implies the
        # prefix cache
        if self.host_pages or self.handoff:
            use_prefix = True
        self._prefix = _PrefixCache(self._alloc, self.page_tokens) \
            if use_prefix else None

        prefill_fn, step_fn = gpt_paged_decode_fns(
            cfg, eps=self.eps, page_tokens=self.page_tokens)
        # Pool args are donated: every call site rebinds the pools from
        # the result, so XLA updates the multi-MB pool buffers in place
        # instead of copying them per dispatch (the copy dominated
        # step/verify cost on CPU).
        self._prefill_aot = AotCache(jax.jit(prefill_fn), "decode.prefill")
        self._step_aot = AotCache(jax.jit(step_fn, donate_argnums=(1, 2)),
                                  "decode.pstep", donate_argnums=(1, 2))
        self._write_aot = AotCache(
            jax.jit(_write_kv_pages, donate_argnums=(0, 1)), "decode.pwrite",
            donate_argnums=(0, 1))
        self._copy_aot = AotCache(
            jax.jit(_copy_kv_page, donate_argnums=(0, 1)), "decode.pcow",
            donate_argnums=(0, 1))
        # host-tier / handoff executables: `pgather` snapshots pages
        # into an independent buffer (pools NOT donated — the engine
        # keeps stepping on them), `ptier` scatters rows back in. The
        # KV handoff rides the same two executables — export gathers,
        # import scatters — so disaggregation adds zero new
        # pool-threading executables
        self._gather_aot = self._tier_write_aot = None
        if self.host_pages or self.handoff:
            self._gather_aot = AotCache(jax.jit(gather_pages),
                                        "decode.pgather")
            self._tier_write_aot = AotCache(
                jax.jit(write_pages, donate_argnums=(0,)), "decode.ptier",
                donate_argnums=(0,))

        self.fingerprint = kv_fingerprint(cfg, self.eps, self.params)
        self._hm = _handoff_metrics() if self.handoff else None
        self._handoff_counts = {"exports": 0, "imports": 0, "rejects": 0}

        self._m = _decode_metrics()
        self._m["kv_page_bytes"].set(
            kv_page_bytes(cfg, self.page_tokens, self.kv_dtype))
        self._m["kv_quantized"].set(1 if self.kv_dtype == "int8" else 0)
        self._spans = SpanRecorder(
            component="decode", metric="paddle_tpu_decode_span_seconds",
            help="Decode request stage latency (queue/prefill/decode)")
        self._rng = np.random.default_rng(seed)

        self._pending: deque = deque()
        self._paused: deque = deque()    # preempted-to-host requests
        self._active: List[_Req] = []
        # multi-tenant QoS: fair-share weights, token-rate quota buckets,
        # weighted virtual time per tenant (tokens served / weight)
        self._weights = _tenant_weights(tenant_weights)
        self._quota = _tenant_quotas(tenant_quota)
        self._vtokens: Dict[str, float] = {}
        self._quota_tokens: Dict[str, float] = {}
        self._quota_ts = time.monotonic()
        self._preempt_on = bool(
            _flags.env_value("PADDLE_TPU_DECODE_PREEMPT")) \
            if preempt is None else bool(preempt)
        self._kpool = None           # [L, P, page_tokens, nh, D], lazy
        self._vpool = None
        # host tier (lazy with the pools): arena store + migration
        # worker + requests parked on an in-flight refetch
        self._store = None
        self._migrate: Optional[MigrationEngine] = None
        self._migrating: List = []   # [ticket, req, [(digest, handle)]]
        # KV-handoff jobs parked for the scheduler thread (pools are
        # donated on every step — only that thread may touch them);
        # each entry is (closure, reply Queue(1))
        self._handoff_q: deque = deque()
        self._handoff_live: set = set()   # handoff job ids holding pages
        # requests popped by _schedule but not yet in _active: they hold
        # pages during _admit, so the ghost audit must see them as live
        self._admitting: List = []
        self._tm = tier_metrics() if self.host_pages else None
        self._last_b_rung = self.batch_ladder[0]
        self._last_w_rung = self.page_ladder[0]
        self._steps = 0
        self._tokens = 0
        self._stop = False
        self._cond = threading.Condition()
        # memory plane: /memz renders this pool's owner attribution,
        # and the context callback feeds the ghost-page audit the set
        # of stream ids still alive (registered after _cond exists —
        # _memz_context reads the queues under it)
        _memz.register_pool(self._alloc, context_fn=self._memz_context)
        self._thread = threading.Thread(
            target=self._loop, name="decode-scheduler", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ API

    def submit(self, prompt: Sequence[int], max_new_tokens=None,
               temperature: float = 0.0, top_k: int = 0,
               eos_id=None, seed=None, tenant=None,
               priority=None) -> DecodeStream:
        toks = [int(t) for t in np.asarray(prompt, dtype=np.int64).reshape(-1)]
        if not toks:
            raise TypedServeError(ERR_INVALID_ARGUMENT, "empty prompt")
        if any(t < 0 or t >= self.cfg.vocab_size for t in toks):
            raise TypedServeError(
                ERR_INVALID_ARGUMENT,
                f"prompt token out of range [0, {self.cfg.vocab_size})")
        if len(toks) >= self.cfg.max_seq_len:
            raise TypedServeError(
                ERR_INVALID_ARGUMENT,
                f"prompt length {len(toks)} leaves no room to generate "
                f"(max_seq_len={self.cfg.max_seq_len})")
        tenant = str(tenant).strip() if tenant else DEFAULT_TENANT
        req = self._req_cls(toks,
                            int(max_new_tokens or self.max_new_tokens),
                            float(temperature), int(top_k),
                            self.eos_id if eos_id is None else int(eos_id),
                            seed=None if seed is None else int(seed),
                            tenant=tenant,
                            priority=0 if priority is None else int(priority))
        with self._cond:
            if self._stop:
                raise TypedServeError(ERR_UNAVAILABLE,
                                      "decode engine stopped")
            # each tenant gets a weighted share of the pending queue, so
            # a flood tenant saturates its own share while others keep
            # a clear path to admission. A single tenant's share is the
            # whole queue — the pre-QoS backpressure behavior. With
            # several tenants queued the per-tenant share IS the
            # watermark (a flood filling the global queue must not shed
            # everyone else); 2x the watermark is the hard backstop.
            mine = sum(1 for r in self._pending if r.tenant == tenant)
            tset = {r.tenant for r in self._pending}
            tset.add(tenant)
            if len(tset) <= 1:
                share = self.max_pending
                over = len(self._pending) >= self.max_pending
            else:
                wsum = sum(self._weight(t) for t in tset)
                share = max(1, int(round(
                    self.max_pending * self._weight(tenant) / wsum)))
                over = (mine >= share
                        or len(self._pending) >= 2 * self.max_pending)
            if over:
                self._m["tenant_shed"].labels(tenant=tenant).inc()
                raise TypedServeError(
                    ERR_RESOURCE_EXHAUSTED,
                    f"decode queue full ({self.max_pending} pending): "
                    f"tenant {tenant!r} holds {mine} of its "
                    f"{share}-slot share")
            self._pending.append(req)
            self._cond.notify_all()
        return req.stream

    def _weight(self, tenant: str) -> float:
        return self._weights.get(tenant, self._weights["*"])

    def _quota_rate(self, tenant: str) -> float:
        return self._quota.get(tenant, self._quota["*"])

    def _pool_shape(self):
        L, nh, D = self.cfg.layers, self.cfg.heads, self.cfg.head_dim
        return (L, self.num_pages, self.page_tokens, nh, D)

    def _pool_sds(self):
        return kv_pool_sds(self._pool_shape(), self.kv_dtype)

    # The tier moves every pool an engine owns as ONE pytree — the base
    # engine's (k, v), the speculative engine's (k, v, dk, dv) — so one
    # gather/scatter executable per page rung migrates a page's full
    # footprint. Subclasses that add pools override these three hooks.

    def _pools(self):
        return (self._kpool, self._vpool)

    def _set_pools(self, pools):
        self._kpool, self._vpool = pools

    def _pools_sds(self):
        p = self._pool_sds()
        return (p, p)

    def _ensure_pool(self):
        if self._kpool is None:
            self._kpool = kv_pool_zeros(self._pool_shape(), self.kv_dtype)
            self._vpool = kv_pool_zeros(self._pool_shape(), self.kv_dtype)
        if self.host_pages and self._migrate is None:
            self._store = HostPageStore(self._pools_sds(), self.host_pages)
            self._migrate = MigrationEngine(
                self._store, window=2, name="kv-migrate",
                wake=self._tier_wake)

    def _tier_wake(self):
        """Migration-worker completion callback: poke the scheduler so
        `_tier_poll` runs promptly (no other lock is ever held here)."""
        with self._cond:
            self._cond.notify_all()

    def warmup(self, verbose: bool = False) -> int:
        """AOT-compile the prefill prompt rungs, the page-write rungs,
        the copy-on-write executable, and the decode
        (batch-rung x page-rung) cross product (capped, largest rungs
        first dropped last). Returns the number of fresh compiles."""
        before = len(profiler.compile_events())
        L, nh, D = self.cfg.layers, self.cfg.heads, self.cfg.head_dim
        i32, f32 = jnp.int32, jnp.float32
        pool = self._pool_sds()
        pt = self.page_tokens
        for r in self.kv_ladder:
            self._prefill_aot.get_or_compile(
                self.params,
                jax.ShapeDtypeStruct((1, r), i32),
                jax.ShapeDtypeStruct((1,), i32),
                key=("prefill", 1, r))
        for w in self.page_ladder:
            self._write_aot.get_or_compile(
                pool, pool,
                jax.ShapeDtypeStruct((L, w, pt, nh, D), f32),
                jax.ShapeDtypeStruct((L, w, pt, nh, D), f32),
                jax.ShapeDtypeStruct((w,), i32),
                key=("pwrite", w))
        self._copy_aot.get_or_compile(
            pool, pool,
            jax.ShapeDtypeStruct((), i32), jax.ShapeDtypeStruct((), i32),
            key=("pcow",))
        if self.host_pages or self.handoff:
            # tier/handoff executables per page rung: gather (spill or
            # handoff export) + scatter (refetch or handoff import)
            # over the full pool tuple, so steady-state migration AND
            # steady-state handoff — like steady-state decode —
            # compile nothing
            pools = self._pools_sds()
            for w in self.page_ladder:
                ids = jax.ShapeDtypeStruct((w,), i32)
                rows = jax.tree.map(
                    lambda s, _w=w: jax.ShapeDtypeStruct(
                        (s.shape[0], _w) + s.shape[2:], s.dtype), pools)
                self._gather_aot.get_or_compile(
                    pools, ids, key=("pgather", w))
                self._tier_write_aot.get_or_compile(
                    pools, rows, ids, key=("ptier", w))
        sigs = [(b, w) for b in self.batch_ladder for w in self.page_ladder]
        if len(sigs) > _WARMUP_SIG_CAP:
            sigs = sigs[:_WARMUP_SIG_CAP]
        for b, w in sigs:
            self._step_aot.get_or_compile(
                self.params, pool, pool,
                jax.ShapeDtypeStruct((b, w), i32),
                jax.ShapeDtypeStruct((b,), i32),
                jax.ShapeDtypeStruct((b,), i32),
                key=("pstep", b, w))
        n = len(profiler.compile_events()) - before
        if verbose:
            print(f"DECODE WARMUP compiles={n} "
                  f"prefill_rungs={self.kv_ladder} "
                  f"page_rungs={self.page_ladder} "
                  f"step_sigs={len(sigs)}", flush=True)
        return n

    def stats(self) -> Dict:
        st = {
            "active": len(self._active),
            "pending": len(self._pending),
            "paused": len(self._paused),
            "max_slots": self.max_slots,
            "steps": self._steps,
            "tokens": self._tokens,
            # rung of the most recent dispatch; the smallest formable
            # rung before the first one (never a bogus 0)
            "batch_rung": int(self._last_b_rung),
            "kv_rung": int(self._last_w_rung * self.page_tokens),
            "batch_ladder": list(self.batch_ladder),
            "kv_ladder": list(self.kv_ladder),
            "page_tokens": self.page_tokens,
            "kv_dtype": self.kv_dtype,
            "fingerprint": self.fingerprint,
            "kv_page_bytes": kv_page_bytes(self.cfg, self.page_tokens,
                                           self.kv_dtype),
            "pages": self._alloc.stats(),
            "tenants": {t: round(v, 4)
                        for t, v in sorted(dict(self._vtokens).items())},
        }
        if self._prefix is not None:
            st["prefix_cache"] = self._prefix.stats()
        if self.handoff:
            st["handoff"] = dict(self._handoff_counts)
        if self.host_pages:
            ps = st["pages"]
            tier = {
                "host_pages_total": ps.get("host_pages_total",
                                           self.host_pages),
                "host_pages_used": ps.get("host_pages_used", 0),
                "spilled_total": ps.get("spilled_total", 0),
                "refetched_total": ps.get("refetched_total", 0),
                "parked_refetches": len(self._migrating),
            }
            if self._migrate is not None:
                tier.update(self._migrate.stats())
            st["kv_tier"] = tier
        return st

    def stop(self):
        """Stop the scheduler; open streams get typed UNAVAILABLE."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=30)
        if self._migrate is not None:
            self._migrate.stop()
        leftovers = (list(self._active) + list(self._pending)
                     + list(self._paused)
                     + [item[1] for item in self._migrating])
        self._active, self._pending = [], deque()
        self._paused = deque()
        self._migrating = []
        while self._handoff_q:
            _, box = self._handoff_q.popleft()
            box.put(("err", TypedServeError(
                ERR_UNAVAILABLE, "decode engine stopped")))
        for req in leftovers:
            req.stream._push_error(TypedServeError(
                ERR_UNAVAILABLE, "decode engine stopped"))
            self._release_pages(req)
        if self._prefix is not None:
            self._prefix.clear()
        self._m["active"].set(0)
        self._m["occupancy"].set(0.0)
        self._spans.close()

    # ------------------------------------------------------- scheduler

    def _loop(self):
        while True:
            newly, victims = [], []
            with self._cond:
                while (not self._stop and not self._pending
                       and not self._paused and not self._active
                       and not self._migrating and not self._handoff_q):
                    self._cond.wait(timeout=0.1)
                if self._stop:
                    return
                self._refill_quota()
                newly, victims = self._schedule()
                self._admitting = list(newly) + list(victims)
                if not newly and not victims and not self._active \
                        and not self._handoff_q:
                    # everything queued is quota-blocked (or parked on
                    # an in-flight refetch): wait for the bucket refill
                    # / migration wake instead of spinning
                    self._cond.wait(timeout=0.02)
            try:
                if self._handoff_q:
                    self._handoff_drain()
                if self._migrating:
                    self._tier_poll()
                for vic in victims:
                    self._preempt(vic)
                for req in newly:
                    if len(self._active) >= self.max_slots:
                        # a preemption was abandoned (chaos) and its
                        # candidate has no slot: requeue at the front
                        with self._cond:
                            if req.preempts:
                                self._paused.appendleft(req)
                            else:
                                self._pending.appendleft(req)
                        continue
                    t_adm = time.perf_counter()
                    admitted = self._admit(req)
                    _RING.complete("decode.admit", t_adm,
                                   time.perf_counter(), {"req": req.id})
                    if admitted:
                        self._active.append(req)
                        self._m["tenant_admissions"].labels(
                            tenant=req.tenant).inc()
                        if req.preempts:
                            self._m["preempt_resumes"].inc()
                if self._admitting:
                    with self._cond:
                        self._admitting = []
                if newly or victims:
                    self._update_gauges()
                if self._active:
                    self._step_once()
            except Exception as exc:  # engine-level failure: fail the
                # batch (typed), free its pages, keep serving newcomers
                err = exc if isinstance(exc, TypedServeError) else \
                    TypedServeError(ERR_UNAVAILABLE,
                                    f"decode scheduler failure: {exc}")
                for req in self._active:
                    req.stream._push_error(err)
                    self._m["evictions"].labels(reason="error").inc()
                    self._release_pages(req)
                self._active = []
                self._update_gauges()

    # ------------------------------------------------- QoS scheduling

    def _schedule(self):
        """Pick this tick's admissions — and preemption victims — under
        `_cond`.

        Weighted fair queuing over tenants: a tenant's virtual time
        advances by tokens_served / weight, and each free slot goes to
        the quota-eligible tenant head with the smallest virtual time
        (preempted requests queue ahead of their tenant's fresh ones).
        A tenant whose quota bucket is in debt is skipped — its requests
        wait, they are never dropped. When no slot is free and
        preemption is enabled, a head with strictly higher priority than
        the lowest-priority active slot evicts it and takes the slot."""
        newly: List[_Req] = []
        victims: List[_Req] = []
        free = self.max_slots - len(self._active)
        preemptable = list(self._active)
        while True:
            heads: Dict[str, tuple] = {}
            for q in (self._paused, self._pending):
                for r in q:
                    heads.setdefault(r.tenant, (q, r))
            eligible: Dict[str, tuple] = {}
            for t, (q, r) in heads.items():
                if self._quota_ok(t):
                    eligible[t] = (q, r)
                elif not r.deferred:
                    r.deferred = True
                    self._m["tenant_quota_deferred"].labels(
                        tenant=t).inc()
            if not eligible:
                return newly, victims
            if free > 0:
                t = min(eligible,
                        key=lambda x: self._vtokens.get(x, 0.0))
                q, r = eligible[t]
                q.remove(r)
                free -= 1
            else:
                if not self._preempt_on or not preemptable:
                    return newly, victims
                # the highest-priority eligible head justifies evicting
                # the lowest-priority (most recently admitted) active
                # slot — and takes that slot itself, so a third tenant
                # cannot slip into the preempt-freed capacity
                t, (q, r) = max(eligible.items(),
                                key=lambda kv: kv[1][1].priority)
                vic = min(preemptable,
                          key=lambda a: (a.priority, -a.t_admit))
                if r.priority <= vic.priority:
                    return newly, victims
                q.remove(r)
                preemptable.remove(vic)
                victims.append(vic)
            newly.append(r)
            # an idle tenant re-entering service starts at the busy
            # tenants' floor, not at the ancient credit it banked
            floor = min((self._vtokens.get(a.tenant, 0.0)
                         for a in self._active), default=0.0)
            self._vtokens[r.tenant] = max(
                self._vtokens.get(r.tenant, 0.0), floor)

    def _preempt(self, req: _Req) -> bool:
        """Evict an active slot to host so a higher-priority request can
        run: stash resumable state, release every page, park the request
        in `_paused`. The live `DecodeStream` is untouched — the client
        just sees a pause. On chaos the preemption is abandoned and the
        victim keeps decoding."""
        try:
            chaos.maybe_fail("decode.preempt", detail=req.id)
        except Exception:
            return False
        self._preempt_stash(req)
        self._release_pages(req)
        req.cache_len = 0
        req.last_tok = 0
        req.input_tail = deque()
        req.feeding = False
        req.preempts += 1
        self._m["preemptions"].inc()
        self._m["preempted_tokens"].inc(len(req.generated))
        self._active = [r for r in self._active if r.id != req.id]
        with self._cond:
            self._paused.append(req)
        return True

    def _preempt_stash(self, req: _Req):
        """Keep a victim's FULL pages alive in the prefix cache, keyed
        by the tokens they hold, so a quick resume re-maps them instead
        of re-prefilling. The partial last page is excluded — its rows
        past the last page boundary were never written."""
        if self._prefix is None:
            return
        pt = self.page_tokens
        toks = (req.prompt + req.generated)[:req.cache_len]
        if len(toks) >= pt:
            self._prefix.insert(toks, req.pages[:len(toks) // pt])

    def _refill_quota(self):
        """Advance every tenant's token bucket by elapsed wall time
        (rate tokens/s, burst = max(rate, 1)). Loop thread only."""
        now = time.monotonic()
        dt = now - self._quota_ts
        if dt <= 0:
            return
        self._quota_ts = now
        for t in list(self._quota_tokens):
            rate = self._quota_rate(t)
            if rate > 0:
                self._quota_tokens[t] = min(
                    self._quota_tokens[t] + dt * rate, max(rate, 1.0))

    def _quota_ok(self, tenant: str) -> bool:
        rate = self._quota_rate(tenant)
        if rate <= 0:
            return True
        if tenant not in self._quota_tokens:
            self._quota_tokens[tenant] = max(rate, 1.0)
        return self._quota_tokens[tenant] > 0.0

    def _note_token(self, req: _Req, n: int = 1):
        """Charge `n` sampled tokens to the request's tenant: advances
        its weighted virtual time and drains its quota bucket (which may
        go negative — the debt defers the tenant's next admission)."""
        t = req.tenant
        self._vtokens[t] = self._vtokens.get(t, 0.0) + n / self._weight(t)
        rate = self._quota_rate(t)
        if rate > 0:
            self._quota_tokens[t] = self._quota_tokens.get(
                t, max(rate, 1.0)) - n
        self._m["tenant_tokens"].labels(tenant=t).inc(n)

    # ---------------------------------------------------- page plumbing

    def _owner_for(self, req) -> tuple:
        """The memz owner tag stamped on pages `req` holds: handoff
        jobs own as ``("handoff", id)``, decode slots as
        ``("slot", id, tenant)`` (SpecDecodeEngine retags its streams
        ``("draft", id)`` so spec pages roll up separately)."""
        if isinstance(req, _HandoffJob):
            return ("handoff", req.id)
        return ("slot", req.id, getattr(req, "tenant", DEFAULT_TENANT))

    def _memz_context(self) -> Dict:
        """Engine context for /memz snapshots and OOM dumps: the ids of
        every stream legitimately holding pages (the ghost-page audit's
        live set) plus the ladder state that shapes allocations."""
        with self._cond:
            live = [r.id for r in self._active]
            live += [r.id for r in self._pending]
            live += [r.id for r in self._paused]
            live += [r.id for r in self._admitting]
            live += [item[1].id for item in self._migrating]
            live += list(self._handoff_live)
        return {"live_owner_ids": [str(i) for i in live],
                "kv_ladder": list(self.kv_ladder),
                "page_ladder": list(self.page_ladder),
                "page_tokens": self.page_tokens,
                "prefix_cache": self._prefix is not None}

    def _release_pages(self, req: _Req):
        """Drop the slot's reference on every page it maps (exactly one
        ref per block-table entry). Idempotent via the list reset."""
        owner = self._owner_for(req)
        pages, req.pages = req.pages, []
        for p in pages:
            try:
                self._alloc.release(p, owner=owner)
            except ValueError:       # never expected; don't mask the
                pass                 # caller's error path if it happens
        self._update_gauges()

    def _alloc_pages(self, n: int, req: _Req,
                     owner: Optional[tuple] = None) -> List[int]:
        """Allocate `n` pages for `req`: chaos site, then the pool, then
        — under pressure — LRU-evict cold prefix-cache pages and retry
        once. Failure is typed RESOURCE_EXHAUSTED for THIS request.
        `owner` overrides the request-derived memz tag (tier restores
        allocate on behalf of the tier, not the parked slot)."""
        owner = owner or self._owner_for(req)
        try:
            chaos.maybe_fail("decode.page_alloc", detail=req.id)
        except Exception as exc:
            self._m["page_alloc_failures"].inc()
            raise TypedServeError(
                ERR_RESOURCE_EXHAUSTED,
                f"decode request {req.id}: page allocation failed: "
                f"{exc}") from exc
        retried = False
        while True:
            try:
                pages = self._alloc.alloc(n, owner=owner)
            except PageExhausted as exc:
                if not retried and self._prefix is not None:
                    shortfall = max(n - self._alloc.free_count(), 1)
                    # host tier first: spilling parks the content in
                    # host RAM (a later resume is a page copy, not a
                    # re-prefill); destructive LRU eviction only covers
                    # whatever the tier could not take
                    freed = self._tier_spill(shortfall) \
                        if self._migrate is not None else 0
                    evicted = 0
                    if freed < shortfall:
                        evicted = self._prefix.evict(shortfall - freed)
                        if evicted:
                            self._m["prefix_evictions"].inc(evicted)
                    if freed or evicted:
                        retried = True
                        continue
                self._m["page_alloc_failures"].inc()
                try:
                    # the OOM forensic dump: who held every page when
                    # this RESOURCE_EXHAUSTED fired (served /memz?oom=1)
                    _memz.capture_oom(self._alloc, owner=owner,
                                      requested=n,
                                      context=self._memz_context())
                except Exception:    # forensics must not mask the error
                    pass
                raise TypedServeError(
                    ERR_RESOURCE_EXHAUSTED,
                    f"decode request {req.id}: KV page pool exhausted "
                    f"({exc})") from exc
            self._m["page_allocs"].inc(n)
            return pages

    def _cow(self, req: _Req, slot: int):
        """First write into a shared page: copy it to a fresh page and
        repoint this slot's block table (the other owners keep the
        original — that's the isolation)."""
        old = req.pages[slot]
        (new,) = self._alloc_pages(1, req)
        exe = self._copy_aot.get_or_compile(
            self._kpool, self._vpool,
            jax.ShapeDtypeStruct((), jnp.int32),
            jax.ShapeDtypeStruct((), jnp.int32),
            key=("pcow",))
        self._kpool, self._vpool = exe(
            self._kpool, self._vpool,
            jnp.asarray(old, jnp.int32), jnp.asarray(new, jnp.int32))
        req.pages[slot] = new
        self._alloc.release(old, owner=self._owner_for(req))
        self._m["cow"].inc()

    # ---------------------------------------------------- host KV tier
    #
    # All tier work below runs on the scheduler thread (pool buffers
    # are DONATED on every step — only this thread may touch them); the
    # migration worker only ever sees independent buffers (the gather
    # snapshot, the device_put result) plus allocator/trie bookkeeping
    # behind their own leaf locks. Requests that need a refetch are
    # PARKED in `_migrating` — their slot stays free for other streams,
    # so a slow or chaos-hung migration stalls only the touching
    # stream.

    def _tier_spill(self, n: int) -> int:
        """Spill up to `n` cold trie-only pages to the host tier.
        Returns how many device pages were freed. The gather snapshot
        happens BEFORE the trie refs drop, so the pages being copied
        out are still allocated at gather dispatch; after
        `mark_spilled` they are free for the allocation that triggered
        the pressure."""
        victims = self._prefix.spill_victims(n)
        if not victims:
            return 0
        handles = self._alloc.spill_begin(len(victims))
        if len(handles) < len(victims):
            # host tier full: age out its coldest entries and retry —
            # anything still short of `n` falls to destructive evict
            if self._prefix.drop_host_lru(len(victims) - len(handles)):
                handles += self._alloc.spill_begin(
                    len(victims) - len(handles))
        victims = victims[:len(handles)]
        if not victims:
            return 0
        w = next_bucket(len(victims), self.page_ladder)
        ids = np.zeros(w, np.int32)
        ids[:len(victims)] = [p for _, p in victims]
        exe = self._gather_aot.get_or_compile(
            self._pools(), jax.ShapeDtypeStruct((w,), jnp.int32),
            key=("pgather", w))
        chunk = exe(self._pools(), jnp.asarray(ids))
        for (d, p), h in zip(victims, handles):
            self._prefix.mark_spilled(d, p, h)
        prefix, alloc = self._prefix, self._alloc

        def on_done(t):
            # migration-worker thread: pure bookkeeping. Failure drops
            # the trie entries — the content degrades to a re-prefill,
            # which is always token-identical, never wrong.
            for h in t.handles:
                try:
                    if t.error is not None:
                        raise ValueError
                    alloc.spill_commit(h)
                except ValueError:
                    prefix.drop_by_handle(h)
                    alloc.host_drop(h)

        self._migrate.spill(chunk, handles, len(victims), on_done=on_done)
        return len(victims)

    def _tier_fetch(self, req: _Req, chain) -> bool:
        """Launch an async refetch of `chain` ([(digest, handle)]) and
        park `req` until it lands. False when nothing could be pinned
        (the caller proceeds with its partial device hit)."""
        pinned = []
        for d, h in chain:
            try:
                self._alloc.refetch_begin(h)
            except ValueError:
                break
            pinned.append((d, h))
        if not pinned:
            return False
        w = next_bucket(len(pinned), self.page_ladder)
        t = self._migrate.refetch([h for _, h in pinned], rung=w)
        self._migrating.append([t, req, pinned])
        return True

    def _tier_poll(self):
        """Non-blocking sweep over parked refetches (scheduler thread,
        outside `_cond`): a landed ticket gets its pages written back
        into the pool and its request reinjected at the head of its
        queue; a failed one drops the spilled entries and the request
        degrades to the ordinary prefill path."""
        still, done = [], []
        for item in self._migrating:
            (done if item[0].poll() != "pending" else still).append(item)
        if not done:
            return
        self._migrating = still
        for t, req, pinned in done:
            ok = t.poll() == "ok" and self._tier_restore(t, req, pinned)
            if not ok:
                for d, h in pinned:
                    self._prefix.drop_by_handle(h)
                    self._alloc.host_drop(h)
            with self._cond:
                if req.preempts:
                    self._paused.appendleft(req)
                else:
                    self._pending.appendleft(req)
                self._cond.notify_all()
        self._update_gauges()

    def _tier_restore(self, t, req: _Req, pinned) -> bool:
        """Scatter a landed refetch into fresh pool pages and point the
        trie back at them; the request's next admission then sees a
        full device hit. False on allocation pressure — the entries
        drop and the request re-prefills instead."""
        try:
            # the tier (not the parked slot) owns these pages until
            # restore_entry retags each one to its trie node
            pages = self._alloc_pages(len(pinned), req,
                                      owner=("tier", req.id))
        except TypedServeError:
            return False
        w = t.rung
        ids = np.zeros(w, np.int32)
        ids[:len(pages)] = pages
        exe = self._tier_write_aot.get_or_compile(
            self._pools(), t.rows,
            jax.ShapeDtypeStruct((w,), jnp.int32), key=("ptier", w))
        self._set_pools(exe(self._pools(), t.rows, jnp.asarray(ids)))
        for (d, h), p in zip(pinned, pages):
            if self._prefix.restore_entry(d, h, p):
                self._alloc.refetch_commit(h)
            else:                 # entry moved on: keep nothing
                self._alloc.release(p, owner=("tier", req.id))
                self._alloc.host_drop(h)
        return True

    # ----------------------------------------- prefill/decode KV handoff
    #
    # Disaggregated serving (docs/serving.md "Disaggregated
    # prefill/decode"): a prefill worker calls `export_kv` — run the
    # prompt forward if its full pages are not already cached, gather
    # them through the non-donating `pgather` snapshot, serialize with
    # per-page crc32 — and the router ships the payload to a decode
    # worker, whose `import_kv` validates compat, scatters the pages in
    # through `ptier`, and seeds the prefix trie so the follow-up
    # decode stream admits as an ordinary prefix hit. Both halves run
    # ON THE SCHEDULER THREAD (pool buffers are donated on every step)
    # via a parked-work queue the loop drains; the calling connection
    # thread waits on a one-slot reply box. Only the prompt's FULL
    # pages travel — the decode side re-feeds the tail and samples
    # every token itself, so token identity with a unified engine falls
    # out of the per-(seed, position) RNG, and a failed or refused
    # handoff degrades to a plain re-prefill (token-identical, same
    # contract as a failed tier refetch).

    def kv_compat(self) -> Dict:
        """The engine-identity facts a KV handoff must match to land
        here (the compat contract; docs/serving.md)."""
        return {"page_tokens": self.page_tokens,
                "kv_dtype": self.kv_dtype,
                "fingerprint": self.fingerprint}

    def _handoff_call(self, fn, timeout: float):
        """Park `fn` for the scheduler thread; wait for its reply."""
        if not self.handoff:
            raise TypedServeError(
                ERR_FAILED_PRECONDITION,
                "KV handoff is disabled on this engine (enable with "
                "handoff= / PADDLE_TPU_DECODE_HANDOFF)")
        box: queue.Queue = queue.Queue(1)
        with self._cond:
            if self._stop:
                raise TypedServeError(ERR_UNAVAILABLE,
                                      "decode engine stopped")
            self._handoff_q.append((fn, box))
            self._cond.notify_all()
        try:
            status, val = box.get(timeout=timeout)
        except queue.Empty:
            raise TypedServeError(
                ERR_UNAVAILABLE,
                f"KV handoff did not complete within {timeout}s") \
                from None
        if status == "err":
            raise val
        return val

    def _handoff_drain(self):
        """Run parked handoff jobs (scheduler thread, outside `_cond`).
        A job's failure goes back through its reply box — it must never
        poison the active batch the way a step failure does."""
        while True:
            with self._cond:
                if not self._handoff_q:
                    return
                fn, box = self._handoff_q.popleft()
            try:
                box.put(("ok", fn()))
            except BaseException as exc:
                self._handoff_counts["rejects"] += 1
                box.put(("err", exc))

    def export_kv(self, prompt: Sequence[int],
                  timeout: float = 30.0) -> Dict:
        """Prefill-side half of a KV handoff: ensure the prompt's full
        pages exist (prefix-cache hit, else one prefill), snapshot and
        serialize them. Returns the wire payload — compat metadata,
        the prompt tokens, per-leaf page arrays (int8 as uint8 views)
        and per-page checksums. ``n_pages`` may be 0 for a sub-page
        prompt; the importer then just seeds nothing and the decode
        worker re-prefills, which is still token-identical."""
        toks = [int(t)
                for t in np.asarray(prompt, np.int64).reshape(-1)]
        if not toks:
            raise TypedServeError(ERR_INVALID_ARGUMENT, "empty prompt")
        if any(t < 0 or t >= self.cfg.vocab_size for t in toks):
            raise TypedServeError(
                ERR_INVALID_ARGUMENT,
                f"prompt token out of range [0, {self.cfg.vocab_size})")
        if len(toks) >= self.cfg.max_seq_len:
            raise TypedServeError(
                ERR_INVALID_ARGUMENT,
                f"prompt length {len(toks)} exceeds "
                f"max_seq_len={self.cfg.max_seq_len}")
        return self._handoff_call(lambda: self._export_kv(toks), timeout)

    def import_kv(self, payload: Dict, timeout: float = 30.0) -> int:
        """Decode-side half of a KV handoff: validate the compat
        contract and the payload integrity, scatter the pages into the
        pool, and seed the prefix trie so the follow-up stream admits
        as a prefix hit. Returns the number of pages landed. Raises
        typed FAILED_PRECONDITION on any compat / structure / checksum
        mismatch — never a silent garbage admission."""
        return self._handoff_call(lambda: self._import_kv(payload),
                                  timeout)

    def _export_kv(self, toks: List[int]) -> Dict:
        t0 = time.perf_counter()
        pt = self.page_tokens
        n_full = len(toks) // pt
        self._ensure_pool()
        payload = self.kv_compat()
        payload["prompt"] = list(toks)
        if n_full == 0:
            payload.update(n_pages=0, leaves=[], crcs=[], arrays=[])
        else:
            job = _HandoffJob()
            owner = self._owner_for(job)
            with self._cond:
                self._handoff_live.add(job.id)
            try:
                pages = self._handoff_pages(toks, n_full, job)
                try:
                    w = next_bucket(n_full, self.page_ladder)
                    ids = np.zeros(w, np.int32)
                    ids[:n_full] = pages
                    exe = self._gather_aot.get_or_compile(
                        self._pools(),
                        jax.ShapeDtypeStruct((w,), jnp.int32),
                        key=("pgather", w))
                    chunk = exe(self._pools(), jnp.asarray(ids))
                    arrays, meta = serialize_pages(chunk, n_full)
                finally:
                    for p in pages:
                        self._alloc.release(p, owner=owner)
            finally:
                with self._cond:
                    self._handoff_live.discard(job.id)
            payload.update(meta)
            payload["arrays"] = arrays
        nbytes = sum(a.nbytes for a in payload["arrays"])
        self._handoff_counts["exports"] += 1
        self._hm["exports"].inc()
        self._hm["pages"].labels(direction="export").inc(n_full)
        self._hm["bytes"].labels(direction="export").inc(nbytes)
        self._hm["latency"].labels(stage="export").observe(
            time.perf_counter() - t0)
        _RING.complete("handoff.export", t0, time.perf_counter(),
                       {"pages": n_full, "bytes": nbytes})
        return payload

    def _handoff_pages(self, toks: List[int], n_full: int,
                       job: _HandoffJob) -> List[int]:
        """Device pages holding `toks`' first `n_full` full pages, one
        reference each held for the caller (attributed to `job`'s
        ``("handoff", id)`` tag): the cached chain when the trie
        already covers them, else one prefill + scatter (which also
        seeds the trie — the next export of this prompt is pure
        gather)."""
        pt = self.page_tokens
        owner = self._owner_for(job)
        hit_pages, _ = self._prefix.lookup(toks, owner=owner)
        if len(hit_pages) >= n_full:
            for p in hit_pages[n_full:]:
                self._alloc.release(p, owner=owner)
            return hit_pages[:n_full]
        for p in hit_pages:
            self._alloc.release(p, owner=owner)
        plen = len(toks)
        rung = next_bucket(plen, self.kv_ladder)
        inp = np.zeros((1, rung), np.int32)
        inp[0, :plen] = toks
        exe = self._prefill_aot.get_or_compile(
            self.params,
            jax.ShapeDtypeStruct((1, rung), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            key=("prefill", 1, rung))
        t0 = time.perf_counter()
        _, k, v = exe(self.params, jnp.asarray(inp),
                      jnp.asarray([plen], np.int32))
        self._m["prefills"].inc()
        self._m["prefill_latency"].observe(time.perf_counter() - t0)
        pages = self._alloc_pages(n_full, job)
        L, nh, D = self.cfg.layers, self.cfg.heads, self.cfg.head_dim
        w = next_bucket(n_full, self.page_ladder)
        ids = np.zeros(w, np.int32)
        ids[:n_full] = pages
        krows = np.zeros((L, w * pt, nh, D), np.float32)
        vrows = np.zeros_like(krows)
        krows[:, :n_full * pt] = np.asarray(k)[:, 0, :n_full * pt]
        vrows[:, :n_full * pt] = np.asarray(v)[:, 0, :n_full * pt]
        wexe = self._write_aot.get_or_compile(
            self._kpool, self._vpool,
            jax.ShapeDtypeStruct((L, w, pt, nh, D), jnp.float32),
            jax.ShapeDtypeStruct((L, w, pt, nh, D), jnp.float32),
            jax.ShapeDtypeStruct((w,), jnp.int32),
            key=("pwrite", w))
        self._kpool, self._vpool = wexe(
            self._kpool, self._vpool,
            jnp.asarray(krows.reshape(L, w, pt, nh, D)),
            jnp.asarray(vrows.reshape(L, w, pt, nh, D)),
            jnp.asarray(ids))
        self._prefix.insert(toks[:n_full * pt], pages)
        return pages

    def _handoff_reject(self, reason: str, detail: str):
        self._hm["rejects"].labels(reason=reason).inc()
        raise TypedServeError(ERR_FAILED_PRECONDITION,
                              f"kv_handoff refused: {detail}")

    def _import_kv(self, payload: Dict) -> int:
        t0 = time.perf_counter()
        mine = self.kv_compat()
        for key in ("page_tokens", "kv_dtype", "fingerprint"):
            theirs = payload.get(key)
            if theirs != mine[key]:
                self._handoff_reject(
                    "compat",
                    f"{key} mismatch (sender {theirs!r}, receiver "
                    f"{mine[key]!r})")
        toks = [int(t) for t in payload.get("prompt") or []]
        n = int(payload.get("n_pages") or 0)
        pt = self.page_tokens
        if not toks or n != len(toks) // pt:
            self._handoff_reject(
                "structure",
                f"page count {n} does not cover prompt length "
                f"{len(toks)} at page_tokens={pt}")
        self._ensure_pool()
        if n > 0:
            self._import_pages(payload, toks, n)
        self._handoff_counts["imports"] += 1
        self._hm["imports"].inc()
        self._hm["pages"].labels(direction="import").inc(n)
        self._hm["bytes"].labels(direction="import").inc(
            sum(np.asarray(a).nbytes for a in payload.get("arrays") or []))
        self._hm["latency"].labels(stage="import").observe(
            time.perf_counter() - t0)
        _RING.complete("handoff.import", t0, time.perf_counter(),
                       {"pages": n})
        return n

    def _import_pages(self, payload: Dict, toks: List[int], n: int):
        try:
            leaves = deserialize_pages(
                payload.get("arrays") or [],
                {"n_pages": n, "leaves": payload.get("leaves"),
                 "crcs": payload.get("crcs")})
        except ValueError as e:
            self._handoff_reject(
                "checksum" if "checksum" in str(e) else "structure",
                str(e))
        # the payload's leaf structure must be THIS engine's pool
        # structure — a speculative engine's 4-pool footprint can never
        # land in a plain engine's 2-pool one, nor across draft shapes
        sds = jax.tree_util.tree_flatten(self._pools_sds())[0]
        if len(leaves) != len(sds):
            self._handoff_reject(
                "structure",
                f"pool structure mismatch ({len(leaves)} payload "
                f"leaves, engine has {len(sds)})")
        for i, (a, s) in enumerate(zip(leaves, sds)):
            want = (s.shape[0], n) + tuple(s.shape[2:])
            if tuple(a.shape) != want \
                    or np.dtype(a.dtype) != np.dtype(s.dtype):
                self._handoff_reject(
                    "structure",
                    f"leaf {i} is {np.dtype(a.dtype)}{list(a.shape)}, "
                    f"engine pool wants "
                    f"{np.dtype(s.dtype)}{list(want)}")
        job = _HandoffJob()
        with self._cond:
            self._handoff_live.add(job.id)
        try:
            self._land_pages(leaves, toks, n, job)
        finally:
            with self._cond:
                self._handoff_live.discard(job.id)

    def _land_pages(self, leaves, toks: List[int], n: int,
                    job: _HandoffJob):
        """Scatter validated handoff leaves into fresh pool pages and
        seed the trie; pages are attributed to `job` while held."""
        try:
            pages = self._alloc_pages(n, job)
        except TypedServeError:
            self._hm["rejects"].labels(reason="exhausted").inc()
            raise
        w = next_bucket(n, self.page_ladder)
        padded = []
        for a in leaves:
            out = np.zeros((a.shape[0], w) + a.shape[2:], a.dtype)
            out[:, :n] = a
            padded.append(out)
        rows = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(self._pools_sds()), padded)
        ids = np.zeros(w, np.int32)
        ids[:n] = pages
        exe = self._tier_write_aot.get_or_compile(
            self._pools(), rows,
            jax.ShapeDtypeStruct((w,), jnp.int32), key=("ptier", w))
        self._set_pools(exe(self._pools(), rows, jnp.asarray(ids)))
        # the trie takes its own reference per inserted page; dropping
        # ours makes it the sole owner — imported pages age out (or
        # spill to the host tier) exactly like any cached prefix
        self._prefix.insert(toks[:n * self.page_tokens], pages)
        owner = self._owner_for(job)
        for p in pages:
            self._alloc.release(p, owner=owner)

    # ------------------------------------------------------- admission

    def _admit(self, req: _Req) -> bool:
        """Give the request KV pages and a first token source.

        Prefix hit: map the cached pages (refcount++), queue the
        uncached prompt tail to be fed through the batched decode step
        — no prefill, no device work here at all. Miss: classic B=1
        prefill at the prompt rung, scatter the panel into fresh pages,
        deliver the first sampled token immediately. True if the
        request now occupies a decode slot.

        A preempted request resumes through this same path over
        ``prompt + generated`` (for a fresh request that IS the prompt):
        replayed tokens are teacher-forced — prefix-mapped or prefilled,
        then tail-fed without sampling — and the per-(seed, position)
        RNG picks up sampling at the first unseen position, so the
        resumed stream is token-identical to an unpreempted run."""
        toks = req.prompt + req.generated
        plen = len(toks)
        pt = self.page_tokens
        self._ensure_pool()
        req.t_admit = time.monotonic()

        usable, hit_pages = 0, []
        owner = self._owner_for(req)
        if self._prefix is not None:
            hit_pages, hit_tokens = self._prefix.lookup(toks, owner=owner)
            self._m["prefix_lookup_tokens"].inc(plen)
            if self._migrate is not None:
                # the device hit may continue in the host tier (spilled
                # cold prefixes, a preempted stream's stashed pages):
                # when refetching would lengthen the usable prefix,
                # park the request on an async refetch instead of
                # re-prefilling content that already exists host-side
                chain = self._prefix.host_chain(toks, len(hit_pages))
                gain = min((len(hit_pages) + len(chain)) * pt, plen - 1)
                if chain and gain > min(hit_tokens, plen - 1) \
                        and self._tier_fetch(req, chain):
                    for p in hit_pages:
                        self._alloc.release(p, owner=owner)
                    return False     # parked in _migrating, no slot held
            # at least one prompt token is always re-fed so the step
            # has logits to sample the first generated token from
            usable = min(hit_tokens, plen - 1)
            n_map = min(len(hit_pages), -(-(usable + 1) // pt)) \
                if usable else 0
            for p in hit_pages[n_map:]:
                self._alloc.release(p, owner=owner)
            hit_pages = hit_pages[:n_map]
            self._m["prefix_hits" if usable else "prefix_misses"].inc()
            if usable:
                self._m["prefix_hit_tokens"].inc(usable)

        if usable:
            req.pages = hit_pages
            req.cache_len = usable
            req.last_tok = toks[usable]
            req.input_tail = deque(toks[usable + 1:])
            req.feeding = True
            return True

        # miss: full prefill at the prompt's kv rung
        rung = next_bucket(plen, self.kv_ladder)
        inp = np.zeros((1, rung), np.int32)
        inp[0, :plen] = toks
        exe = self._prefill_aot.get_or_compile(
            self.params,
            jax.ShapeDtypeStruct((1, rung), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            key=("prefill", 1, rung))
        t0 = time.perf_counter()
        logits, k, v = exe(self.params, jnp.asarray(inp),
                           jnp.asarray([plen], np.int32))
        row = np.asarray(logits)[0]
        req.prefill_s = time.perf_counter() - t0
        self._m["prefills"].inc()
        self._m["prefill_latency"].observe(req.prefill_s)
        try:
            pages = self._alloc_pages(-(-plen // pt), req)
        except TypedServeError as err:
            req.stream._push_error(err)
            self._m["evictions"].labels(reason="exhausted").inc()
            return False
        # scatter the panel into the pages (zero padding past plen —
        # rung garbage must never enter the pool; table padding -> null)
        L, nh, D = self.cfg.layers, self.cfg.heads, self.cfg.head_dim
        w = -(-rung // pt)
        ids = np.zeros(w, np.int32)
        ids[:len(pages)] = pages
        krows = np.zeros((L, w * pt, nh, D), np.float32)
        vrows = np.zeros_like(krows)
        krows[:, :plen] = np.asarray(k)[:, 0, :plen]
        vrows[:, :plen] = np.asarray(v)[:, 0, :plen]
        wexe = self._write_aot.get_or_compile(
            self._kpool, self._vpool,
            jax.ShapeDtypeStruct((L, w, pt, nh, D), jnp.float32),
            jax.ShapeDtypeStruct((L, w, pt, nh, D), jnp.float32),
            jax.ShapeDtypeStruct((w,), jnp.int32),
            key=("pwrite", w))
        self._kpool, self._vpool = wexe(
            self._kpool, self._vpool,
            jnp.asarray(krows.reshape(L, w, pt, nh, D)),
            jnp.asarray(vrows.reshape(L, w, pt, nh, D)),
            jnp.asarray(ids))
        req.pages = pages
        if not req.generated:        # resumes already saw first-token
            self._m["ttft"].observe(time.monotonic() - req.t_submit)
        try:
            chaos.maybe_fail("decode.stream", detail=req.id)
            tok = self._sample(row, req)
        except Exception as exc:
            req.stream._push_error(TypedServeError(
                ERR_UNAVAILABLE, f"decode stream killed: {exc}"))
            self._m["evictions"].labels(reason="error").inc()
            self._release_pages(req)
            return False
        req.cache_len = plen
        req.last_tok = tok
        req.generated.append(tok)
        self._tokens += 1
        self._m["tokens"].inc()
        self._note_token(req)
        if self._prefix is not None:
            self._prefix.insert(toks, pages[:plen // pt])
        eos = req.eos_id is not None and tok == req.eos_id
        req.stream._push_token(tok, eos)
        _RING.instant("decode.emit", {"req": req.id})
        if eos or len(req.generated) >= req.max_new \
                or req.cache_len >= self.cfg.max_seq_len:
            self._finish(req, "eos" if eos else "length")
            self._release_pages(req)
            return False
        return True

    # ------------------------------------------------------------ step

    def _step_once(self):
        t_tick = time.perf_counter()
        pt = self.page_tokens
        # provision the write target for row cache_len: a fresh page at
        # a page boundary, a copy-on-write if the target page is shared
        victims = []
        for req in self._active:
            slot = req.cache_len // pt
            try:
                if slot >= len(req.pages):
                    req.pages.extend(self._alloc_pages(1, req))
                elif self._alloc.refcount(req.pages[slot]) > 1:
                    t_cow = time.perf_counter()
                    self._cow(req, slot)
                    _RING.complete("decode.cow", t_cow,
                                   time.perf_counter(), {"req": req.id})
            except TypedServeError as err:
                req.stream._push_error(err)
                self._m["evictions"].labels(reason="exhausted").inc()
                self._release_pages(req)
                victims.append(req)
        if victims:
            dead = {r.id for r in victims}
            self._active = [r for r in self._active if r.id not in dead]
            self._update_gauges()
        reqs = self._active
        if not reqs:
            return
        b_rung = next_bucket(len(reqs), self.batch_ladder)
        w_rung = next_bucket(max(len(r.pages) for r in reqs),
                             self.page_ladder)
        tables = np.zeros((b_rung, w_rung), np.int32)   # pad -> null page
        ltok = np.zeros(b_rung, np.int32)
        clen = np.zeros(b_rung, np.int32)
        for j, req in enumerate(reqs):
            tables[j, :len(req.pages)] = req.pages
            ltok[j] = req.last_tok
            clen[j] = req.cache_len
        exe = self._step_aot.get_or_compile(
            self.params, self._kpool, self._vpool,
            jax.ShapeDtypeStruct((b_rung, w_rung), jnp.int32),
            jax.ShapeDtypeStruct((b_rung,), jnp.int32),
            jax.ShapeDtypeStruct((b_rung,), jnp.int32),
            key=("pstep", b_rung, w_rung))
        t0 = time.perf_counter()
        logits, self._kpool, self._vpool = exe(
            self.params, self._kpool, self._vpool,
            jnp.asarray(tables), jnp.asarray(ltok), jnp.asarray(clen))
        lognp = np.asarray(logits)
        self._m["step_latency"].observe(time.perf_counter() - t0)
        self._last_b_rung, self._last_w_rung = b_rung, w_rung
        self._steps += 1
        self._m["steps"].inc()
        t_sample = time.perf_counter()
        finished = []
        for j, req in enumerate(reqs):
            req.cache_len += 1
            if req.input_tail:           # still consuming prompt tail:
                req.last_tok = req.input_tail.popleft()
                continue                 # logits are mid-prompt, discard
            if req.feeding:
                # the step just consumed the final prompt token — its
                # pages now hold the whole prompt: cache them, and fall
                # through to sample this request's FIRST token
                req.feeding = False
                if self._prefix is not None:
                    self._prefix.insert(
                        req.prompt, req.pages[:len(req.prompt) // pt])
            first = not req.generated
            try:
                chaos.maybe_fail("decode.stream", detail=req.id)
                tok = self._sample(lognp[j], req)
            except Exception as exc:
                req.stream._push_error(TypedServeError(
                    ERR_UNAVAILABLE, f"decode stream killed: {exc}"))
                self._m["evictions"].labels(reason="error").inc()
                self._release_pages(req)
                finished.append(req)
                continue
            req.generated.append(tok)
            req.last_tok = tok
            self._tokens += 1
            self._m["tokens"].inc()
            self._note_token(req)
            if first:
                self._m["ttft"].observe(time.monotonic() - req.t_submit)
            eos = req.eos_id is not None and tok == req.eos_id
            req.stream._push_token(tok, eos)
            _RING.instant("decode.emit", {"req": req.id})
            if eos or len(req.generated) >= req.max_new \
                    or req.cache_len >= self.cfg.max_seq_len:
                self._finish(req, "eos" if eos else "length")
                self._release_pages(req)
                finished.append(req)
        now = time.perf_counter()
        _RING.complete("decode.sample", t_sample, now, {"reqs": len(reqs)})
        _RING.complete("decode.step", t_tick, now,
                       {"batch": len(reqs), "b_rung": b_rung,
                        "w_rung": w_rung})
        if finished:
            done = {r.id for r in finished}
            self._active = [r for r in reqs if r.id not in done]
            self._update_gauges()

    def _finish(self, req: _Req, reason: str):
        req.stream._push_done()
        self._m["evictions"].labels(reason=reason).inc()
        now = time.monotonic()
        self._spans.record(req.id, {
            "queue": req.t_admit - req.t_submit,
            "prefill": req.prefill_s,
            "decode": now - req.t_admit,
        }, extra={"tokens": len(req.generated),
                  "prompt_len": len(req.prompt)})

    def _dist(self, row: np.ndarray, req: _Req) -> np.ndarray:
        """The request's sampling distribution over the vocab (its
        temperature/top-k transform of one logit row) — shared by
        `_sample` and speculative rejection sampling."""
        logits = row.astype(np.float64) / max(req.temperature, 1e-6)
        if 0 < req.top_k < logits.shape[0]:
            kth = np.partition(logits, -req.top_k)[-req.top_k]
            logits = np.where(logits >= kth, logits, -np.inf)
        logits -= logits.max()
        p = np.exp(logits)
        p /= p.sum()
        return p

    def _req_rng(self, req: _Req, pos: int):
        """Sampling generator for the token at absolute sequence
        position `pos`. Seeded streams draw from a counter-based RNG
        keyed on (seed, position), so a resumed stream — resubmitted as
        `prompt + tokens_emitted_so_far` with the same seed — samples
        the remaining positions draw-for-draw identically to the
        uninterrupted run, regardless of engine history or batch mates.
        Unseeded requests share the engine RNG."""
        if req.seed is None:
            return self._rng
        return np.random.default_rng((req.seed, pos))

    def _sample(self, row: np.ndarray, req: _Req, pos=None) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(row))
        p = self._dist(row, req)
        if pos is None:
            pos = len(req.prompt) + len(req.generated)
        return int(self._req_rng(req, pos).choice(p.shape[0], p=p))

    def _update_gauges(self):
        n = len(self._active)
        self._m["active"].set(n)
        self._m["occupancy"].set(n / max(self.max_slots, 1))
        self._m["preempted_waiting"].set(len(self._paused))
        ps = self._alloc.stats()
        self._m["page_pool_size"].set(ps["pages_total"])
        self._m["page_in_use"].set(ps["pages_used"])
        self._m["page_shared"].set(ps["pages_shared"])
        self._m["page_fragmentation"].set(ps["fragmentation"])
        if self._prefix is not None:
            self._m["prefix_cached_pages"].set(
                self._prefix.stats()["cached_pages"])
        if self._tm is not None:
            self._tm["resident"].labels(tier="device").set(
                ps["pages_used"])
            self._tm["resident"].labels(tier="host").set(
                ps.get("host_pages_used", 0))


# ------------------------------------------------- speculative decoding

def spec_k_ladder(k_max: int) -> List[int]:
    """Powers of two from 1 up to — and including — `k_max`: the
    adaptive speculation-depth rungs. Every rung's verify width (k+1)
    is AOT-warmed, so per-slot k moves along the ladder without a
    steady-state compile."""
    k_max = int(k_max)
    if k_max <= 1:
        return [1]
    vals, v = [], 1
    while v < k_max:
        vals.append(v)
        v *= 2
    vals.append(k_max)
    return sorted(set(vals))


class SpecDecodeEngine(DecodeEngine):
    """Draft-and-verify speculative decoding over the paged KV pool.

    A small draft GPT (same vocab) runs up to k greedy steps per
    scheduler tick over its OWN page pool — same shape discipline, same
    `PageAllocator`, same per-slot block tables, so one page id names
    one target page AND one draft page. The target then scores all
    drafted positions in a single `gpt_paged_verify_fns` forward (which
    also writes their target K/V rows); acceptance is
    sample-then-compare — the committed token at each position is the
    target's own (argmax, or the per-(seed, position) sampler over the
    verify logits) and a draft is accepted iff it guessed it, so
    speculative output is token-for-token the plain engine's for greedy
    AND seeded-sampled decode. A rejection is pure host bookkeeping:
    truncate
    `cache_len`, drop the block-table tail through
    `PageAllocator.release_range` (stale rows inside kept pages are
    masked by `lengths` and overwritten next tick — no contiguous-rung
    copy to unwind, which is what makes speculation cheap on pages).

    Everything else — admission, prefix sharing, eviction, streaming,
    typed backpressure — is inherited. Copy-on-write copies BOTH pools
    so divergent continuations stay isolated in draft space too, and
    `warmup()` extends the AOT surface with draft-prefill, draft-step,
    draft-write/COW and the (batch-rung x page-rung x k-rung) verify
    cross product, keeping the zero-steady-state-compile invariant
    across churn including rejections and rollbacks.

    Per-slot adaptive k: each slot starts at `speculate_k` and walks a
    power-of-two ladder by an EMA of its acceptance rate — repetitive
    continuations earn deep speculation, adversarial streams degrade
    toward plain decode instead of burning draft steps.
    """

    _req_cls = _SpecReq

    def __init__(self, model=None, *, draft_model=None,
                 draft_cfg: Optional[GPTConfig] = None,
                 draft_params: Optional[Dict] = None,
                 draft_eps: Optional[float] = None,
                 speculate_k: Optional[int] = None, **kw):
        if draft_model is not None:
            from .. import framework
            draft_cfg = draft_model.cfg
            draft_params = framework.param_arrays(draft_model)
            draft_eps = draft_model.ln_f._epsilon \
                if draft_eps is None else draft_eps
        if draft_cfg is None or draft_params is None:
            raise ValueError(
                "SpecDecodeEngine needs a draft model or "
                "(draft_cfg, draft_params)")
        k = int(speculate_k) if speculate_k is not None \
            else int(_flags.env_value("PADDLE_TPU_DECODE_SPECULATE"))
        if k < 1:
            raise ValueError(f"speculate_k must be >= 1, got {k}")
        # validate against the target BEFORE the scheduler thread starts
        tcfg = model.cfg if model is not None else kw.get("cfg")
        if tcfg is not None:
            if draft_cfg.vocab_size != tcfg.vocab_size:
                raise ValueError(
                    f"draft vocab {draft_cfg.vocab_size} != target "
                    f"vocab {tcfg.vocab_size}")
            if draft_cfg.max_seq_len < tcfg.max_seq_len:
                raise ValueError(
                    f"draft max_seq_len {draft_cfg.max_seq_len} < target "
                    f"max_seq_len {tcfg.max_seq_len}")
        super().__init__(model, **kw)
        self.draft_cfg = draft_cfg
        self.draft_eps = 1e-5 if draft_eps is None else float(draft_eps)
        self._draft_params = {n: jnp.asarray(v)
                              for n, v in draft_params.items()}
        self.k_ladder = spec_k_ladder(k)
        dprefill = gpt_paged_prefill_fns(
            draft_cfg, eps=self.draft_eps, page_tokens=self.page_tokens)
        rollout = gpt_paged_rollout_fns(
            draft_cfg, eps=self.draft_eps, page_tokens=self.page_tokens)
        verify = gpt_paged_verify_fns(
            self.cfg, eps=self.eps, page_tokens=self.page_tokens)
        # Draft/target pools donated for the same in-place-update
        # reason as the base engine's executables.
        self._dprefill_aot = AotCache(
            jax.jit(dprefill, donate_argnums=(1, 2)), "decode.dprefill",
            donate_argnums=(1, 2))
        self._droll_aot = AotCache(
            jax.jit(rollout, donate_argnums=(1, 2)), "decode.droll",
            donate_argnums=(1, 2))
        self._dcopy_aot = AotCache(
            jax.jit(_copy_kv_page, donate_argnums=(0, 1)), "decode.dcow",
            donate_argnums=(0, 1))
        self._verify_aot = AotCache(
            jax.jit(verify, donate_argnums=(1, 2)), "decode.verify",
            donate_argnums=(1, 2))
        self._dkpool = None          # draft pools, lazy like the target's
        self._dvpool = None
        self._drafted_total = 0
        self._accepted_total = 0

    # ----------------------------------------------------- pool plumbing

    def _owner_for(self, req) -> tuple:
        """Speculative streams own their pages as ``("draft", id)`` —
        one page id names a target AND a draft page, so the draft kind
        keeps the spec footprint distinct in /memz rollups. Handoff
        jobs keep the base tag."""
        if isinstance(req, _HandoffJob):
            return super()._owner_for(req)
        return ("draft", req.id)

    def _dpool_shape(self):
        c = self.draft_cfg
        return (c.layers, self.num_pages, self.page_tokens, c.heads,
                c.head_dim)

    def _dpool_sds(self):
        return kv_pool_sds(self._dpool_shape(), self.kv_dtype)

    # Host tiering migrates the draft pools with the target pools: one
    # page id names a page in all four, so a spilled page's full
    # footprint moves as one chunk and a restore brings the draft rows
    # back warm. (Even when restored draft rows are stale, acceptance
    # is sample-then-compare — draft content can only cost acceptance
    # rate, never change emitted tokens.)

    def _pools(self):
        return (self._kpool, self._vpool, self._dkpool, self._dvpool)

    def _set_pools(self, pools):
        (self._kpool, self._vpool,
         self._dkpool, self._dvpool) = pools

    def _pools_sds(self):
        p, d = self._pool_sds(), self._dpool_sds()
        return (p, p, d, d)

    def _ensure_pool(self):
        super()._ensure_pool()
        if self._dkpool is None:
            self._dkpool = kv_pool_zeros(self._dpool_shape(), self.kv_dtype)
            self._dvpool = kv_pool_zeros(self._dpool_shape(), self.kv_dtype)

    def _cow(self, req: _Req, slot: int):
        """Copy-on-write for speculation copies the page in BOTH pools —
        one page id names a target page and a draft page."""
        old = req.pages[slot]
        (new,) = self._alloc_pages(1, req)
        i32 = jnp.int32
        exe = self._copy_aot.get_or_compile(
            self._kpool, self._vpool,
            jax.ShapeDtypeStruct((), i32), jax.ShapeDtypeStruct((), i32),
            key=("pcow",))
        self._kpool, self._vpool = exe(
            self._kpool, self._vpool,
            jnp.asarray(old, i32), jnp.asarray(new, i32))
        dexe = self._dcopy_aot.get_or_compile(
            self._dkpool, self._dvpool,
            jax.ShapeDtypeStruct((), i32), jax.ShapeDtypeStruct((), i32),
            key=("dcow",))
        self._dkpool, self._dvpool = dexe(
            self._dkpool, self._dvpool,
            jnp.asarray(old, i32), jnp.asarray(new, i32))
        req.pages[slot] = new
        self._alloc.release(old, owner=self._owner_for(req))
        self._m["cow"].inc()

    # ---------------------------------------------------------- warmup

    def warmup(self, verbose: bool = False) -> int:
        """Base warmup plus the draft/verify surface: fused draft
        prefill-into-pages per prompt rung, draft COW, the fused draft
        rollout (batch-rung x page-rung x k-rung) grid and the verify
        (batch-rung x page-rung x k-rung) cross product — each grid
        capped like the base step's."""
        before = len(profiler.compile_events())
        super().warmup(verbose=False)
        i32 = jnp.int32
        pool, dpool = self._pool_sds(), self._dpool_sds()
        pt = self.page_tokens
        for r in self.kv_ladder:
            self._dprefill_aot.get_or_compile(
                self._draft_params, dpool, dpool,
                jax.ShapeDtypeStruct((1, r), i32),
                jax.ShapeDtypeStruct((1, -(-r // pt)), i32),
                jax.ShapeDtypeStruct((1,), i32),
                key=("dprefill", 1, r))
        self._dcopy_aot.get_or_compile(
            dpool, dpool,
            jax.ShapeDtypeStruct((), i32), jax.ShapeDtypeStruct((), i32),
            key=("dcow",))
        # When the full (batch x page x k) cross product overflows the
        # warmup cap, shrink the k ladder itself — dropping middle rungs,
        # keeping k=1 and k_max — instead of silently truncating tail
        # signatures. Adaptive k then only walks warmed rungs, so the
        # no-steady-state-compiles invariant survives large k_max.
        grid = len(self.batch_ladder) * len(self.page_ladder)
        while len(self.k_ladder) > 1 \
                and grid * len(self.k_ladder) > _WARMUP_SIG_CAP:
            self.k_ladder.pop(len(self.k_ladder) // 2)
        sigs = [(b, w, kk) for b in self.batch_ladder
                for w in self.page_ladder for kk in self.k_ladder]
        if len(sigs) > _WARMUP_SIG_CAP:
            sigs = sigs[:_WARMUP_SIG_CAP]
        for b, w, kk in sigs:
            self._droll_aot.get_or_compile(
                self._draft_params, dpool, dpool,
                jax.ShapeDtypeStruct((b, w), i32),
                jax.ShapeDtypeStruct((b, kk), i32),
                jax.ShapeDtypeStruct((b,), i32),
                key=("droll", b, w, kk))
        vsigs = [(b, w, kk + 1) for b in self.batch_ladder
                 for w in self.page_ladder for kk in self.k_ladder]
        if len(vsigs) > _WARMUP_SIG_CAP:
            vsigs = vsigs[:_WARMUP_SIG_CAP]
        for b, w, k1 in vsigs:
            self._verify_aot.get_or_compile(
                self.params, pool, pool,
                jax.ShapeDtypeStruct((b, w), i32),
                jax.ShapeDtypeStruct((b, k1), i32),
                jax.ShapeDtypeStruct((b,), i32),
                key=("verify", b, w, k1))
        n = len(profiler.compile_events()) - before
        if verbose:
            print(f"SPEC DECODE WARMUP compiles={n} "
                  f"k_ladder={self.k_ladder} "
                  f"rollout_sigs={len(sigs)} verify_sigs={len(vsigs)}",
                  flush=True)
        return n

    # ------------------------------------------------------- admission

    def _admit(self, req: _Req) -> bool:
        req.spec_k = self.k_ladder[-1]      # start optimistic, adapt down
        if not super()._admit(req):
            return False
        if not req.feeding:
            # prefill miss: the target panel is in the pages; mirror the
            # prompt into the draft pool so drafting starts warm
            self._draft_prefill(req)
        # prefix hit: the mapped pages already carry the draft rows the
        # original (speculative) prefill wrote — nothing to do
        req.draft_len = req.cache_len
        return True

    def _draft_prefill(self, req: _Req):
        """One fused B=1 draft prefill-into-pages dispatch over the
        committed sequence (the prompt — or prompt + replayed tokens on
        a preempt resume), scattered into the SAME page ids the target
        panel landed in. These writes deliberately skip the COW check:
        the rows hold committed K/V — the one thing every mapper of a
        shared prefix page agrees on."""
        seq = (req.prompt + req.generated)[:req.cache_len]
        plen = len(seq)
        pt = self.page_tokens
        rung = next_bucket(plen, self.kv_ladder)
        toks = np.zeros((1, rung), np.int32)
        toks[0, :plen] = seq
        w = -(-rung // pt)
        tables = np.zeros((1, w), np.int32)
        tables[0, :len(req.pages)] = req.pages
        exe = self._dprefill_aot.get_or_compile(
            self._draft_params, self._dkpool, self._dvpool,
            jax.ShapeDtypeStruct((1, rung), jnp.int32),
            jax.ShapeDtypeStruct((1, w), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            key=("dprefill", 1, rung))
        _, self._dkpool, self._dvpool = exe(
            self._draft_params, self._dkpool, self._dvpool,
            jnp.asarray(toks), jnp.asarray(tables),
            jnp.asarray([plen], np.int32))

    def _preempt_stash(self, req: _Req):
        """Stash only PROMPT-region pages at preemption. Generated-region
        pages may carry draft rows past the commit point (speculation in
        flight); a resume that prefix-mapped them would skip the draft
        re-prefill and let stale draft rows steer the greedy draft chain
        — diverging the rejection-sampling draw sequence from an
        unpreempted run. The prompt resume path re-drafts the generated
        region instead. Rows the draft catch-up has not reached yet
        (`draft_len` lagging `cache_len`) are excluded the same way."""
        if self._prefix is not None:
            full = min(req.cache_len, req.draft_len,
                       len(req.prompt)) // self.page_tokens
            if full:
                self._prefix.insert(req.prompt, req.pages[:full])
        req.draft_len = 0

    # ------------------------------------------------------------ tick

    def _step_once(self):
        t_tick = time.perf_counter()
        pt = self.page_tokens
        cap = self.cfg.max_seq_len
        tick_k = max(r.spec_k for r in self._active)
        K1 = tick_k + 1
        # 1. provision every page this tick can write: draft rows
        # [draft_len, draft_len+k) and verify rows [cache_len,
        # cache_len+k]; COW any shared page in that window (both pools)
        victims = []
        for req in self._active:
            lo = min(req.cache_len, req.draft_len) // pt
            hi_row = min(max(req.cache_len + tick_k,
                             req.draft_len + tick_k - 1), cap - 1)
            need = hi_row // pt + 1
            try:
                if need > len(req.pages):
                    req.pages.extend(
                        self._alloc_pages(need - len(req.pages), req))
                for s in range(lo, need):
                    if self._alloc.refcount(req.pages[s]) > 1:
                        t_cow = time.perf_counter()
                        self._cow(req, s)
                        _RING.complete("decode.cow", t_cow,
                                       time.perf_counter(),
                                       {"req": req.id})
            except TypedServeError as err:
                req.stream._push_error(err)
                self._m["evictions"].labels(reason="exhausted").inc()
                self._release_pages(req)
                victims.append(req)
        if victims:
            dead = {r.id for r in victims}
            self._active = [r for r in self._active if r.id not in dead]
            self._update_gauges()
        reqs = self._active
        if not reqs:
            return
        b_rung = next_bucket(len(reqs), self.batch_ladder)
        w_rung = next_bucket(max(len(r.pages) for r in reqs),
                             self.page_ladder)
        tables = np.zeros((b_rung, w_rung), np.int32)   # pad -> null page
        for j, req in enumerate(reqs):
            tables[j, :len(req.pages)] = req.pages
        tables_j = jnp.asarray(tables)
        # 2. draft phase: tick_k greedy draft steps fused into ONE
        # rollout dispatch. Step i consumes one token per slot — a
        # committed token the draft has not seen yet (catch-up, passed
        # via `forced`; its output is discarded) or the slot's own
        # previous draft (forced = -1: the rollout chains its argmax).
        t_draft = time.perf_counter()
        seqs = [req.prompt + req.generated for req in reqs]
        forced = np.zeros((b_rung, tick_k), np.int32)
        forced[len(reqs):] = 0              # padded rows: null-page writes
        dlen = np.zeros(b_rung, np.int32)
        for j, req in enumerate(reqs):
            dl, seq = req.draft_len, seqs[j]
            dlen[j] = dl
            for i in range(tick_k):
                forced[j, i] = seq[dl + i] if dl + i < len(seq) else -1
        dexe = self._droll_aot.get_or_compile(
            self._draft_params, self._dkpool, self._dvpool,
            jax.ShapeDtypeStruct((b_rung, w_rung), jnp.int32),
            jax.ShapeDtypeStruct((b_rung, tick_k), jnp.int32),
            jax.ShapeDtypeStruct((b_rung,), jnp.int32),
            key=("droll", b_rung, w_rung, tick_k))
        dout, self._dkpool, self._dvpool = dexe(
            self._draft_params, self._dkpool, self._dvpool,
            tables_j, jnp.asarray(forced), jnp.asarray(dlen))
        dnp = np.asarray(dout)
        self._m["spec_draft_steps"].inc(tick_k)
        chains: List[List[int]] = [[] for _ in reqs]
        for j, req in enumerate(reqs):
            for i in range(tick_k):
                if req.draft_len >= len(seqs[j]) - 1:
                    chains[j].append(int(dnp[j, i]))
                req.draft_len += 1
        t_verify = time.perf_counter()
        _RING.complete("decode.draft", t_draft, t_verify, {"k": tick_k})
        # 3. verify: one multi-token target forward scores (and writes
        # the K/V of) up to K1 positions per slot — the un-consumed
        # committed tokens first, then this tick's drafts
        vtoks = np.zeros((b_rung, K1), np.int32)
        clen = np.zeros(b_rung, np.int32)
        meta = []
        for j, req in enumerate(reqs):
            known = seqs[j][req.cache_len:]
            n_known = min(len(known), K1, cap - req.cache_len)
            nd = min(len(chains[j]), req.spec_k, K1 - n_known)
            row = known[:n_known] + chains[j][:nd]
            vtoks[j, :len(row)] = row
            vtoks[j, len(row):] = row[-1]   # padding rows roll back
            clen[j] = req.cache_len
            meta.append((n_known, nd))
        vexe = self._verify_aot.get_or_compile(
            self.params, self._kpool, self._vpool,
            jax.ShapeDtypeStruct((b_rung, w_rung), jnp.int32),
            jax.ShapeDtypeStruct((b_rung, K1), jnp.int32),
            jax.ShapeDtypeStruct((b_rung,), jnp.int32),
            key=("verify", b_rung, w_rung, K1))
        t0 = time.perf_counter()
        logits, amax, self._kpool, self._vpool = vexe(
            self.params, self._kpool, self._vpool,
            tables_j, jnp.asarray(vtoks), jnp.asarray(clen))
        amaxnp = np.asarray(amax)
        lognp = None   # full logits only cross to host when sampling
        self._m["step_latency"].observe(time.perf_counter() - t0)
        self._last_b_rung, self._last_w_rung = b_rung, w_rung
        self._steps += 1
        self._m["steps"].inc()
        t_accept = time.perf_counter()
        _RING.complete("decode.verify", t_verify, t_accept, {"k1": K1})
        # 4. acceptance + rollback, per slot on the host
        finished = []
        for j, req in enumerate(reqs):
            n_known, nd = meta[j]
            drafts = chains[j][:nd]
            seq_len_old = len(seqs[j])
            if req.feeding and req.cache_len + n_known >= len(req.prompt):
                # the verify just consumed the last prompt-tail token:
                # the pages now hold the whole prompt
                req.feeding = False
                req.input_tail.clear()
                if self._prefix is not None:
                    self._prefix.insert(
                        req.prompt, req.pages[:len(req.prompt) // pt])
            emitted, a, i = [], 0, n_known - 1
            while True:
                if req.temperature > 0.0 and lognp is None:
                    lognp = np.asarray(logits)
                # Sample-then-compare verification: the committed token
                # at every position comes straight from the target —
                # greedy argmax, or the plain engine's per-(seed, pos)
                # sampler over the verify logits — and a draft is
                # accepted iff it guessed that token. A draft d is
                # accepted with probability p[d], exactly classic
                # rejection sampling's, but the OUTPUT never depends on
                # the draft chain: a speculative stream is draw-for-draw
                # the plain engine's across any k, batch composition, or
                # preempt/resume history.
                if req.temperature <= 0.0:
                    tok = int(amaxnp[j, i])
                else:
                    pos = len(req.prompt) + len(req.generated) \
                        + len(emitted)
                    tok = self._sample(lognp[j, i], req, pos=pos)
                accept = a < nd and tok == drafts[a]
                emitted.append(tok)
                if accept:
                    a += 1
                    i += 1
                hit_eos = req.eos_id is not None and tok == req.eos_id
                if (not accept) or hit_eos \
                        or len(req.generated) + len(emitted) >= req.max_new \
                        or req.cache_len + n_known + a >= cap:
                    break
            new_c = req.cache_len + n_known + a
            # rollback: keep pages covering the committed rows and the
            # still-valid draft rows, release the stranded tail
            dl_valid = min(req.draft_len, seq_len_old + a)
            req.draft_len = dl_valid
            keep = -(-max(new_c, dl_valid) // pt)
            if keep < len(req.pages):
                released = self._alloc.release_range(
                    req.pages, keep, owner=self._owner_for(req))
                del req.pages[keep:]
                if released:
                    self._m["page_rollback_released"].inc(released)
            req.cache_len = new_c
            req.last_tok = emitted[-1]
            # acceptance accounting + adaptive k
            req.drafted += nd
            req.accepted += a
            req.stream.spec_drafted = req.drafted
            req.stream.spec_accepted = req.accepted
            self._drafted_total += nd
            self._accepted_total += a
            if nd:
                self._m["spec_accepted"].inc(a)
                self._m["spec_rejected"].inc(nd - a)
                req.accept_ema = 0.5 * req.accept_ema + 0.5 * (a / nd)
                ki = self.k_ladder.index(req.spec_k)
                if req.accept_ema < 0.35 and ki > 0:
                    req.spec_k = self.k_ladder[ki - 1]
                elif req.accept_ema > 0.8 and ki < len(self.k_ladder) - 1:
                    req.spec_k = self.k_ladder[ki + 1]
            if self._drafted_total:
                self._m["spec_acceptance"].set(
                    self._accepted_total / self._drafted_total)
            # stream the newly committed tokens
            first = not req.generated
            try:
                chaos.maybe_fail("decode.stream", detail=req.id)
            except Exception as exc:
                req.stream._push_error(TypedServeError(
                    ERR_UNAVAILABLE, f"decode stream killed: {exc}"))
                self._m["evictions"].labels(reason="error").inc()
                self._release_pages(req)
                finished.append(req)
                continue
            req.generated.extend(emitted)
            self._tokens += len(emitted)
            self._m["tokens"].inc(len(emitted))
            self._note_token(req, len(emitted))
            req.stream._push_tokens(
                emitted,
                req.eos_id is not None and emitted[-1] == req.eos_id)
            _RING.instant("decode.emit", {"req": req.id, "n": len(emitted)})
            if first:
                self._m["ttft"].observe(time.monotonic() - req.t_submit)
            done_eos = req.eos_id is not None \
                and emitted[-1] == req.eos_id
            if done_eos or len(req.generated) >= req.max_new \
                    or req.cache_len >= cap:
                self._finish(req, "eos" if done_eos else "length")
                self._release_pages(req)
                finished.append(req)
        now = time.perf_counter()
        _RING.complete("decode.accept", t_accept, now, {"reqs": len(reqs)})
        _RING.complete("decode.step", t_tick, now,
                       {"batch": len(reqs), "k": tick_k})
        if finished:
            done = {r.id for r in finished}
            self._active = [r for r in reqs if r.id not in done]
            self._update_gauges()

    def stats(self) -> Dict:
        st = super().stats()
        drafted, accepted = self._drafted_total, self._accepted_total
        st["speculate"] = {
            "k_max": self.k_ladder[-1],
            "k_ladder": list(self.k_ladder),
            "drafted": drafted,
            "accepted": accepted,
            "acceptance_rate": round(accepted / drafted, 4)
            if drafted else 0.0,
        }
        return st


# ------------------------------------------------------------ artifact

def save_for_decode(model, prefix: str, quant: Optional[str] = None):
    """Persist a GPT for the decode daemon: config JSON + params npz
    (the jit.save one-shot artifact has no incremental entry points).

    `quant="int8"` applies `quant.ptq.quantize_params` before writing —
    int8 weights under their original keys plus fp32 `::scale` siblings
    — and records `"quant": "int8"` in the manifest. The default fp32
    artifact is byte-identical to pre-quantization versions (no extra
    manifest key, same npz keys), so old artifacts load unchanged."""
    from .. import framework
    meta = {"config": dataclasses.asdict(model.cfg),
            "eps": float(model.ln_f._epsilon),
            "format": "paddle_tpu.decode.v1"}
    params = {k: np.asarray(v)
              for k, v in framework.param_arrays(model).items()}
    if quant is not None:
        if quant != "int8":
            raise ValueError(f"quant={quant!r}: expected None or 'int8'")
        params = quantize_params(params)
        meta["quant"] = "int8"
    with open(prefix + ".decode.json", "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    np.savez(prefix + ".decode.npz", **params)


def _load_decode_artifact(prefix: str):
    with open(prefix + ".decode.json") as f:
        meta = json.load(f)
    if meta.get("format") != "paddle_tpu.decode.v1":
        raise ValueError(f"{prefix}.decode.json: not a decode artifact")
    cfg = GPTConfig(**meta["config"])
    with np.load(prefix + ".decode.npz") as z:
        params = {k: z[k] for k in z.files}
    return cfg, params, meta.get("eps")


def load_for_decode(prefix: str, draft_prefix: Optional[str] = None,
                    speculate_k: Optional[int] = None,
                    draft_quant: Optional[bool] = None,
                    **engine_kw) -> DecodeEngine:
    """Load a `save_for_decode` artifact into a ready DecodeEngine.

    With a draft artifact (`draft_prefix`, or
    PADDLE_TPU_DECODE_DRAFT_MODEL) and a speculation depth
    (`speculate_k`, or PADDLE_TPU_DECODE_SPECULATE >= 1) the result is
    a `SpecDecodeEngine`; otherwise the plain engine — speculation is
    strictly opt-in.

    `draft_quant` (or PADDLE_TPU_DECODE_DRAFT_QUANT) int8-quantizes the
    DRAFT weights at load when the draft artifact is still fp32 — draft
    numerics only move the acceptance rate, never the target stream, so
    this is the cheapest quantization on-ramp. Already-quantized
    artifacts (manifest `"quant": "int8"`) pass through untouched."""
    cfg, params, eps = _load_decode_artifact(prefix)
    if draft_prefix is None:
        draft_prefix = _flags.env_value(
            "PADDLE_TPU_DECODE_DRAFT_MODEL") or None
    if speculate_k is None:
        speculate_k = int(_flags.env_value("PADDLE_TPU_DECODE_SPECULATE"))
    if draft_quant is None:
        draft_quant = bool(
            _flags.env_value("PADDLE_TPU_DECODE_DRAFT_QUANT"))
    if draft_prefix and int(speculate_k) >= 1:
        dcfg, dparams, deps = _load_decode_artifact(draft_prefix)
        if draft_quant and not _params_quantized(dparams):
            dparams = quantize_params(dparams)
        return SpecDecodeEngine(cfg=cfg, params=params, eps=eps,
                                draft_cfg=dcfg, draft_params=dparams,
                                draft_eps=deps,
                                speculate_k=int(speculate_k), **engine_kw)
    return DecodeEngine(cfg=cfg, params=params, eps=eps, **engine_kw)
