"""Continuous-batching autoregressive decode engine (GPT KV-cache path).

The DynamicBatcher serves stateless one-shot requests; LLM traffic is
iterative — every request is a prefill followed by many single-token
steps, and requests arrive and finish mid-flight. This engine is the
token-level analog of the batcher's shape-bucket design:

  * the compute core is `models.gpt.gpt_decode_fns` — `prefill` builds a
    request's K/V panel in one pass, `decode_step` advances EVERY active
    request one token through a fixed-capacity cache updated with
    `lax.dynamic_update_slice`;
  * both run through an `AotCache`, one executable per
    (batch-rung x kv-capacity-rung) bucket, so after `warmup()` a
    steady-state token stream compiles nothing (`profiler`'s compile
    events make that checkable, as for the batcher);
  * a slot pool bounds concurrent sequences. The slot count defaults
    from `core.monitor.hbm_usage` — how many full-capacity KV panels fit
    in a fraction of free HBM — with a fixed CPU fallback where the
    stats read (0, 0);
  * between steps the scheduler admits queued requests into free slots
    and evicts finished ones (EOS / max-tokens / context full), then
    re-packs the pool onto the smallest rung pair that holds the
    survivors — a late request shares the running batch instead of
    waiting behind it;
  * sampling is host-side numpy (greedy, or temperature with optional
    top-k), so the device graph stays deterministic per shape.

Streams: `submit()` returns a `DecodeStream`; tokens are pushed as they
are sampled (serve.py forwards them as incremental PDI2 frames), and a
failed request gets a typed UNAVAILABLE while its batch-mates keep
streaming — the same error-isolation contract as batched one-shot
serving. Chaos site `decode.stream` fires per token delivery for drills.
"""
from __future__ import annotations

import dataclasses
import json
import math
import queue
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

import jax
import jax.numpy as jnp

from .. import profiler
from ..core import flags as _flags
from ..core import monitor
from ..jit.compile_cache import AotCache
from ..models.gpt import GPTConfig, gpt_decode_fns
from ..observability import counter, gauge, histogram
from ..observability.spans import SpanRecorder, next_request_id
from ..testing import chaos
from .batching import _WARMUP_SIG_CAP, bucket_ladder, next_bucket
from .errors import (ERR_INVALID_ARGUMENT, ERR_RESOURCE_EXHAUSTED,
                     ERR_UNAVAILABLE, TypedServeError)

DEFAULT_MAX_SLOTS = 8          # CPU fallback when HBM stats are absent
DEFAULT_MAX_NEW_TOKENS = 64
_KV_LADDER_FLOOR = 16          # smallest kv-capacity rung worth compiling

_METRICS = None


def _decode_metrics():
    """Register (idempotently) and return the paddle_tpu_decode_* family."""
    global _METRICS
    if _METRICS is None:
        _METRICS = {
            "tokens": counter(
                "paddle_tpu_decode_tokens_total",
                "Tokens sampled by the decode engine (prefill + steps)"),
            "steps": counter(
                "paddle_tpu_decode_steps_total",
                "Batched decode steps executed (one per token column)"),
            "prefills": counter(
                "paddle_tpu_decode_prefills_total",
                "Requests admitted through the prefill phase"),
            "evictions": counter(
                "paddle_tpu_decode_cache_evictions_total",
                "KV-cache slot evictions by reason",
                labelnames=("reason",)),
            "occupancy": gauge(
                "paddle_tpu_decode_slot_occupancy",
                "Active sequences / slot-pool capacity (0..1)"),
            "active": gauge(
                "paddle_tpu_decode_active_requests",
                "Sequences currently holding a KV slot"),
            "prefill_latency": histogram(
                "paddle_tpu_decode_prefill_latency_seconds",
                "Prefill execution latency per admitted request"),
            "step_latency": histogram(
                "paddle_tpu_decode_step_latency_seconds",
                "Batched decode-step execution latency"),
            "ttft": histogram(
                "paddle_tpu_decode_ttft_seconds",
                "Submit-to-first-token latency per request"),
        }
    return _METRICS


def kv_slot_bytes(cfg: GPTConfig, capacity: Optional[int] = None) -> int:
    """HBM bytes one sequence's full K+V panel occupies at `capacity`."""
    cap = capacity or cfg.max_seq_len
    return cfg.layers * 2 * cap * cfg.heads * cfg.head_dim * 4


def default_slot_count(cfg: GPTConfig, hbm_fraction: float = 0.5,
                       fallback: int = DEFAULT_MAX_SLOTS) -> int:
    """Size the slot pool from live HBM stats: how many full-capacity KV
    panels fit in `hbm_fraction` of the free bytes. CPU (stats (0, 0))
    gets the fixed fallback so tests and benches behave identically."""
    used, limit = monitor.hbm_usage()
    if limit <= 0:
        return fallback
    free = max(limit - used, 0) * hbm_fraction
    return max(1, min(int(free // kv_slot_bytes(cfg)), 256))


def kv_capacity_ladder(max_seq_len: int) -> List[int]:
    """Powers of two from the floor up to (and including) max_seq_len."""
    if max_seq_len <= _KV_LADDER_FLOOR:
        return [int(max_seq_len)]
    vals, v = [], _KV_LADDER_FLOOR
    while v < max_seq_len:
        vals.append(v)
        v *= 2
    vals.append(int(max_seq_len))
    return sorted(set(vals))


class DecodeStream:
    """Consumer handle for one request's token stream.

    Events arrive in order: zero or more ``("token", tok, eos)`` then
    exactly one ``("done", tokens)`` — or a `TypedServeError` raised out
    of `next_event` / `result` if the stream died (engine stop, chaos,
    per-request failure)."""

    def __init__(self, req_id: int, prompt: List[int]):
        self.request_id = req_id
        self.prompt = list(prompt)
        self.tokens: List[int] = []      # generated so far (mirror)
        self._q: queue.Queue = queue.Queue()
        self._closed = False             # producer-side latch

    # -- producer (engine thread) ------------------------------------
    def _push_token(self, tok: int, eos: bool):
        if not self._closed:
            self.tokens.append(int(tok))
            self._q.put(("token", int(tok), bool(eos)))

    def _push_done(self):
        if not self._closed:
            self._closed = True
            self._q.put(("done", list(self.tokens)))

    def _push_error(self, err: TypedServeError):
        if not self._closed:
            self._closed = True
            self._q.put(("error", err))

    # -- consumer ----------------------------------------------------
    def next_event(self, timeout: Optional[float] = None):
        try:
            ev = self._q.get(timeout=timeout)
        except queue.Empty:
            raise TypedServeError(
                ERR_UNAVAILABLE,
                f"decode stream {self.request_id}: no event within "
                f"{timeout}s") from None
        if ev[0] == "error":
            raise ev[1]
        return ev

    def events(self, timeout: Optional[float] = None):
        """Yield ("token", tok, eos) events until done; raises on error."""
        while True:
            ev = self.next_event(timeout=timeout)
            if ev[0] == "done":
                return
            yield ev

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream completes; returns generated tokens."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            left = None if deadline is None \
                else max(deadline - time.monotonic(), 0.0)
            ev = self.next_event(timeout=left)
            if ev[0] == "done":
                return ev[1]


class _Req:
    __slots__ = ("id", "prompt", "max_new", "temperature", "top_k",
                 "eos_id", "stream", "cache_len", "last_tok", "generated",
                 "row", "t_submit", "t_admit", "prefill_s", "_knp", "_vnp")

    def __init__(self, prompt, max_new, temperature, top_k, eos_id):
        self.id = next_request_id()
        self.prompt = prompt
        self.max_new = max_new
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.stream = DecodeStream(self.id, prompt)
        self.cache_len = 0
        self.last_tok = 0
        self.generated: List[int] = []
        self.row = -1
        self.t_submit = time.monotonic()
        self.t_admit = 0.0
        self.prefill_s = 0.0
        self._knp = None      # prefill K/V awaiting pool insertion
        self._vnp = None


class DecodeEngine:
    """Slot-pool continuous batcher over the incremental GPT forward."""

    def __init__(self, model=None, *, cfg: Optional[GPTConfig] = None,
                 params: Optional[Dict] = None, eps: Optional[float] = None,
                 max_slots: Optional[int] = None,
                 max_new_tokens: int = DEFAULT_MAX_NEW_TOKENS,
                 eos_id: Optional[int] = None,
                 hbm_fraction: float = 0.5, seed: int = 0,
                 max_pending: Optional[int] = None):
        if model is not None:
            from .. import framework
            cfg = model.cfg
            params = framework.param_arrays(model)
            eps = model.ln_f._epsilon if eps is None else eps
        if cfg is None or params is None:
            raise ValueError("DecodeEngine needs a model or (cfg, params)")
        self.cfg = cfg
        self.eps = 1e-5 if eps is None else float(eps)
        self.params = {k: jnp.asarray(v) for k, v in params.items()}
        self.max_new_tokens = int(max_new_tokens)
        self.eos_id = eos_id
        self.max_slots = int(max_slots) if max_slots \
            else default_slot_count(cfg, hbm_fraction)
        self.max_pending = int(max_pending) if max_pending is not None \
            else 4 * self.max_slots
        self.batch_ladder = bucket_ladder(
            self.max_slots, env=_flags.env_value("PADDLE_TPU_DECODE_BUCKETS"))
        self.kv_ladder = kv_capacity_ladder(cfg.max_seq_len)

        prefill_fn, step_fn = gpt_decode_fns(cfg, eps=self.eps)
        self._prefill_aot = AotCache(jax.jit(prefill_fn), "decode.prefill")
        self._step_aot = AotCache(jax.jit(step_fn), "decode.step")

        self._m = _decode_metrics()
        self._spans = SpanRecorder(
            component="decode", metric="paddle_tpu_decode_span_seconds",
            help="Decode request stage latency (queue/prefill/decode)")
        self._rng = np.random.default_rng(seed)

        self._pending: deque = deque()
        self._active: List[_Req] = []
        self._kdev = None            # [L, B_rung, kv_rung, nh, D]
        self._vdev = None
        self._need_rebuild = False
        self._steps = 0
        self._tokens = 0
        self._stop = False
        self._cond = threading.Condition()
        self._thread = threading.Thread(
            target=self._loop, name="decode-scheduler", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ API

    def submit(self, prompt: Sequence[int], max_new_tokens=None,
               temperature: float = 0.0, top_k: int = 0,
               eos_id=None) -> DecodeStream:
        toks = [int(t) for t in np.asarray(prompt, dtype=np.int64).reshape(-1)]
        if not toks:
            raise TypedServeError(ERR_INVALID_ARGUMENT, "empty prompt")
        if any(t < 0 or t >= self.cfg.vocab_size for t in toks):
            raise TypedServeError(
                ERR_INVALID_ARGUMENT,
                f"prompt token out of range [0, {self.cfg.vocab_size})")
        if len(toks) >= self.cfg.max_seq_len:
            raise TypedServeError(
                ERR_INVALID_ARGUMENT,
                f"prompt length {len(toks)} leaves no room to generate "
                f"(max_seq_len={self.cfg.max_seq_len})")
        req = _Req(toks,
                   int(max_new_tokens or self.max_new_tokens),
                   float(temperature), int(top_k),
                   self.eos_id if eos_id is None else int(eos_id))
        with self._cond:
            if self._stop:
                raise TypedServeError(ERR_UNAVAILABLE,
                                      "decode engine stopped")
            if len(self._pending) >= self.max_pending:
                raise TypedServeError(
                    ERR_RESOURCE_EXHAUSTED,
                    f"decode queue full ({self.max_pending} pending)")
            self._pending.append(req)
            self._cond.notify_all()
        return req.stream

    def warmup(self, verbose: bool = False) -> int:
        """AOT-compile the prefill prompt rungs and the decode
        (batch-rung x kv-rung) cross product (capped, largest rungs
        first dropped last). Returns the number of fresh compiles."""
        before = len(profiler.compile_events())
        L, nh, D = self.cfg.layers, self.cfg.heads, self.cfg.head_dim
        i32, f32 = jnp.int32, jnp.float32
        for r in self.kv_ladder:
            self._prefill_aot.get_or_compile(
                self.params,
                jax.ShapeDtypeStruct((1, r), i32),
                jax.ShapeDtypeStruct((1,), i32),
                key=("prefill", 1, r))
        sigs = [(b, r) for b in self.batch_ladder for r in self.kv_ladder]
        if len(sigs) > _WARMUP_SIG_CAP:
            sigs = sigs[:_WARMUP_SIG_CAP]
        for b, r in sigs:
            self._step_aot.get_or_compile(
                self.params,
                jax.ShapeDtypeStruct((L, b, r, nh, D), f32),
                jax.ShapeDtypeStruct((L, b, r, nh, D), f32),
                jax.ShapeDtypeStruct((b,), i32),
                jax.ShapeDtypeStruct((b,), i32),
                key=("step", b, r))
        n = len(profiler.compile_events()) - before
        if verbose:
            print(f"DECODE WARMUP compiles={n} "
                  f"prefill_rungs={self.kv_ladder} "
                  f"step_sigs={len(sigs)}", flush=True)
        return n

    def stats(self) -> Dict:
        return {
            "active": len(self._active),
            "pending": len(self._pending),
            "max_slots": self.max_slots,
            "steps": self._steps,
            "tokens": self._tokens,
            "batch_rung": 0 if self._kdev is None
            else int(self._kdev.shape[1]),
            "kv_rung": 0 if self._kdev is None
            else int(self._kdev.shape[2]),
            "batch_ladder": list(self.batch_ladder),
            "kv_ladder": list(self.kv_ladder),
        }

    def stop(self):
        """Stop the scheduler; open streams get typed UNAVAILABLE."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=30)
        leftovers = list(self._active) + list(self._pending)
        self._active, self._pending = [], deque()
        for req in leftovers:
            req.stream._push_error(TypedServeError(
                ERR_UNAVAILABLE, "decode engine stopped"))
        self._m["active"].set(0)
        self._m["occupancy"].set(0.0)
        self._spans.close()

    # ------------------------------------------------------- scheduler

    def _loop(self):
        while True:
            newly = []
            with self._cond:
                while (not self._stop and not self._pending
                       and not self._active):
                    self._cond.wait(timeout=0.1)
                if self._stop:
                    return
                free = self.max_slots - len(self._active)
                while self._pending and free > 0:
                    newly.append(self._pending.popleft())
                    free -= 1
            try:
                # the next step writes K/V at row cache_len: grow to the
                # next kv rung BEFORE dynamic_update_slice would clamp
                # the write into the last row and corrupt the cache
                if self._active and self._kdev is not None and \
                        max(r.cache_len + 1 for r in self._active) \
                        > int(self._kdev.shape[2]):
                    self._need_rebuild = True
                if newly or self._need_rebuild:
                    admitted = [r for r in newly if self._admit(r)]
                    self._rebuild(admitted)
                if self._active:
                    self._step_once()
            except Exception as exc:  # engine-level failure: fail the
                # batch (typed), drop the pool, keep serving newcomers
                err = exc if isinstance(exc, TypedServeError) else \
                    TypedServeError(ERR_UNAVAILABLE,
                                    f"decode scheduler failure: {exc}")
                for req in self._active:
                    req.stream._push_error(err)
                    self._m["evictions"].labels(reason="error").inc()
                self._active = []
                self._kdev = self._vdev = None
                self._need_rebuild = False
                self._update_gauges()

    def _admit(self, req: _Req) -> bool:
        """Prefill one request (B=1 at its prompt rung) and deliver the
        first sampled token. True if it still needs a decode slot."""
        plen = len(req.prompt)
        rung = next_bucket(plen, self.kv_ladder)
        toks = np.zeros((1, rung), np.int32)
        toks[0, :plen] = req.prompt
        exe = self._prefill_aot.get_or_compile(
            self.params,
            jax.ShapeDtypeStruct((1, rung), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            key=("prefill", 1, rung))
        t0 = time.perf_counter()
        logits, k, v = exe(self.params, jnp.asarray(toks),
                           jnp.asarray([plen], np.int32))
        row = np.asarray(logits)[0]
        req.prefill_s = time.perf_counter() - t0
        req.t_admit = time.monotonic()
        self._m["prefills"].inc()
        self._m["prefill_latency"].observe(req.prefill_s)
        self._m["ttft"].observe(time.monotonic() - req.t_submit)
        try:
            chaos.maybe_fail("decode.stream", detail=req.id)
            tok = self._sample(row, req)
        except Exception as exc:
            req.stream._push_error(TypedServeError(
                ERR_UNAVAILABLE, f"decode stream killed: {exc}"))
            self._m["evictions"].labels(reason="error").inc()
            return False
        req.cache_len = plen
        req.last_tok = tok
        req.generated.append(tok)
        self._tokens += 1
        self._m["tokens"].inc()
        eos = req.eos_id is not None and tok == req.eos_id
        req.stream._push_token(tok, eos)
        if eos or len(req.generated) >= req.max_new \
                or req.cache_len >= self.cfg.max_seq_len:
            self._finish(req, "eos" if eos else "length")
            return False
        # keep only the real prompt columns; rung padding beyond plen is
        # garbage K/V the pool must never inherit
        req._knp = np.asarray(k)[:, 0, :plen]
        req._vnp = np.asarray(v)[:, 0, :plen]
        return True

    def _rebuild(self, admitted: List[_Req]):
        """Re-pack survivors + admissions onto the smallest rung pair."""
        survivors = list(self._active)
        k_old = None if self._kdev is None else np.asarray(self._kdev)
        v_old = None if self._vdev is None else np.asarray(self._vdev)
        actives = survivors + admitted
        self._need_rebuild = False
        if not actives:
            self._active = []
            self._kdev = self._vdev = None
            self._update_gauges()
            return
        L, nh, D = self.cfg.layers, self.cfg.heads, self.cfg.head_dim
        b_rung = next_bucket(len(actives), self.batch_ladder)
        need = max(r.cache_len + 1 for r in actives)
        kv_rung = next_bucket(need, self.kv_ladder)
        knp = np.zeros((L, b_rung, kv_rung, nh, D), np.float32)
        vnp = np.zeros_like(knp)
        for j, req in enumerate(actives):
            n = req.cache_len
            if req._knp is not None:               # fresh admission
                knp[:, j, :n] = req._knp
                vnp[:, j, :n] = req._vnp
                req._knp = req._vnp = None
            else:                                  # survivor: old row
                knp[:, j, :n] = k_old[:, req.row, :n]
                vnp[:, j, :n] = v_old[:, req.row, :n]
            req.row = j
        self._active = actives
        self._kdev = jnp.asarray(knp)
        self._vdev = jnp.asarray(vnp)
        self._update_gauges()

    def _step_once(self):
        reqs = self._active
        L, b_rung, kv_rung = (self._kdev.shape[0], self._kdev.shape[1],
                              self._kdev.shape[2])
        ltok = np.zeros(b_rung, np.int32)
        clen = np.zeros(b_rung, np.int32)
        for req in reqs:
            ltok[req.row] = req.last_tok
            clen[req.row] = req.cache_len
        if int(clen.max()) + 1 > kv_rung:
            raise RuntimeError(
                f"decode step would overflow kv capacity {kv_rung} "
                f"(cache_len {int(clen.max())}) — rebuild missed")
        exe = self._step_aot.get_or_compile(
            self.params, self._kdev, self._vdev,
            jax.ShapeDtypeStruct((b_rung,), jnp.int32),
            jax.ShapeDtypeStruct((b_rung,), jnp.int32),
            key=("step", b_rung, kv_rung))
        t0 = time.perf_counter()
        logits, self._kdev, self._vdev = exe(
            self.params, self._kdev, self._vdev,
            jnp.asarray(ltok), jnp.asarray(clen))
        lognp = np.asarray(logits)
        self._m["step_latency"].observe(time.perf_counter() - t0)
        self._steps += 1
        self._m["steps"].inc()
        finished = []
        for req in reqs:
            req.cache_len += 1
            try:
                chaos.maybe_fail("decode.stream", detail=req.id)
                tok = self._sample(lognp[req.row], req)
            except Exception as exc:
                req.stream._push_error(TypedServeError(
                    ERR_UNAVAILABLE, f"decode stream killed: {exc}"))
                self._m["evictions"].labels(reason="error").inc()
                finished.append(req)
                continue
            req.generated.append(tok)
            req.last_tok = tok
            self._tokens += 1
            self._m["tokens"].inc()
            eos = req.eos_id is not None and tok == req.eos_id
            req.stream._push_token(tok, eos)
            if eos or len(req.generated) >= req.max_new \
                    or req.cache_len >= self.cfg.max_seq_len:
                self._finish(req, "eos" if eos else "length")
                finished.append(req)
        if finished:
            self._active = [r for r in reqs if r not in finished]
            self._need_rebuild = True
            self._update_gauges()

    def _finish(self, req: _Req, reason: str):
        req.stream._push_done()
        self._m["evictions"].labels(reason=reason).inc()
        now = time.monotonic()
        self._spans.record(req.id, {
            "queue": req.t_admit - req.t_submit,
            "prefill": req.prefill_s,
            "decode": now - req.t_admit,
        }, extra={"tokens": len(req.generated),
                  "prompt_len": len(req.prompt)})

    def _sample(self, row: np.ndarray, req: _Req) -> int:
        if req.temperature <= 0.0:
            return int(np.argmax(row))
        logits = row.astype(np.float64) / max(req.temperature, 1e-6)
        if 0 < req.top_k < logits.shape[0]:
            kth = np.partition(logits, -req.top_k)[-req.top_k]
            logits = np.where(logits >= kth, logits, -np.inf)
        logits -= logits.max()
        p = np.exp(logits)
        p /= p.sum()
        return int(self._rng.choice(logits.shape[0], p=p))

    def _update_gauges(self):
        n = len(self._active)
        self._m["active"].set(n)
        self._m["occupancy"].set(n / max(self.max_slots, 1))


# ------------------------------------------------------------ artifact

def save_for_decode(model, prefix: str):
    """Persist a GPT for the decode daemon: config JSON + params npz
    (the jit.save one-shot artifact has no incremental entry points)."""
    from .. import framework
    meta = {"config": dataclasses.asdict(model.cfg),
            "eps": float(model.ln_f._epsilon),
            "format": "paddle_tpu.decode.v1"}
    with open(prefix + ".decode.json", "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    params = {k: np.asarray(v)
              for k, v in framework.param_arrays(model).items()}
    np.savez(prefix + ".decode.npz", **params)


def load_for_decode(prefix: str, **engine_kw) -> DecodeEngine:
    """Load a `save_for_decode` artifact into a ready DecodeEngine."""
    with open(prefix + ".decode.json") as f:
        meta = json.load(f)
    if meta.get("format") != "paddle_tpu.decode.v1":
        raise ValueError(f"{prefix}.decode.json: not a decode artifact")
    cfg = GPTConfig(**meta["config"])
    with np.load(prefix + ".decode.npz") as z:
        params = {k: z[k] for k in z.files}
    return DecodeEngine(cfg=cfg, params=params, eps=meta.get("eps"),
                        **engine_kw)
