"""Dynamic cross-request batching for the inference daemon.

The serve daemon's historical shape — thread-per-connection, one
predictor call per request under a global lock — bounds throughput by
per-request dispatch overhead and lets every novel input shape trigger a
recompile. This module is the Clipper/Orca-style fix: reader threads
enqueue decoded tensors, ONE dispatcher thread forms batches under a
deadline, each formed batch is padded to a shape bucket from a bounded
ladder and executed through the predictor's per-bucket AOT cache
(`jit.compile_cache.AotCache`), and the results are sliced back
per-request into futures. The compiled-shape set is therefore finite and
warmable: after `DynamicBatcher.warmup()` a mixed-shape request stream
compiles nothing.

Shape buckets
    The ladder defaults to powers of two up to ``max_batch_size`` and is
    overridable via ``PADDLE_TPU_SERVE_BUCKETS`` (comma/space separated
    ints, e.g. ``"1,2,4,8,16,32"``); a custom ladder whose top rung is
    below ``max_batch_size`` is extended by powers of two so warmup
    covers every batch shape formation can produce. The batch (leading)
    dim of a formed batch is padded UP to the next rung; trailing
    *dynamic* dims (the export's symbolic axes, e.g. a ``"seqlen"``
    spec) are bucketed with the same ladder — requests whose trailing
    dims land in the same rung batch together and are zero-padded to it.
    Values beyond the top rung grow by powers of two (one compile each,
    still bounded).

Correctness contract
    Batch-dim padding assumes row-independent outputs (true of any
    batch-polymorphic export whose leading symbol is the batch); the
    engine checks each output's leading *symbol* is the batch symbol
    (falling back to a runtime leading-dim check when output avals are
    unavailable) and runs per-request otherwise. Trailing zero-padding
    additionally requires padding-invariance per row, so it is governed
    by ``trailing`` / ``PADDLE_TPU_SERVE_TRAILING``: ``"auto"`` (the
    default) PROVES invariance at startup by comparing a padded against
    an unpadded probe run and disables trailing bucketing on mismatch
    (softmax/attention/mean over the padded axis); ``"on"`` forces it,
    ``"off"`` restricts batching to the batch dim (requests merge only
    on exact trailing shapes). Un-padding of results is keyed by the
    SYMBOL an output axis carries, never by its size — a static output
    dim that happens to equal a rung is left alone, and two axes padded
    to the same rung from different originals cannot collide.

Error isolation
    A failed batch is re-executed per request, so a poison request (bad
    static dim, NaN-triggering payload, ...) fails only its own future.

Robustness (docs/fault_tolerance.md "serving fleet")
    A dead dispatcher immediately fails every queued AND future request
    with a typed ``UNAVAILABLE`` frame (clients + the front router see
    the death now, not after the request deadline). A crashed pool
    worker fails its in-flight batch the same way, then respawns in
    place with bounded backoff (``paddle_tpu_serve_worker_restarts_total``
    counts respawns; an exhausted budget leaves the slot dead and
    /healthz red). ``max_queue`` (``PADDLE_TPU_SERVE_MAX_QUEUE``) is the
    admission watermark: past it, ``submit`` sheds instantly with
    ``RESOURCE_EXHAUSTED`` instead of queueing unboundedly. ``quiesce``
    blocks until all accepted work has been answered — the drain step of
    a SIGTERM'd daemon. Chaos sites ``batcher.dispatch`` /
    ``batcher.worker`` let tests kill or wedge either thread
    deterministically.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from concurrent.futures import Future, InvalidStateError
from itertools import product
from queue import Queue
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core import flags as _flags
from ..testing import chaos
from .errors import (ERR_RESOURCE_EXHAUSTED, ERR_UNAVAILABLE,
                     TypedServeError)

__all__ = ["DynamicBatcher", "bucket_ladder", "next_bucket",
           "DEFAULT_MAX_BATCH", "DEFAULT_TIMEOUT_MS",
           "max_queue_default", "parse_tenant_map", "tenant_weights",
           "tenant_quotas"]

DEFAULT_MAX_BATCH = 8
DEFAULT_TIMEOUT_MS = 2.0
_WARMUP_SIG_CAP = 64          # cross-product guard for many dynamic dims


def parse_tenant_map(spec, default: float = 0.0):
    """Parse a ``tenant:value,tenant2:value`` spec into a dict. A ``*``
    entry sets the value for unlisted tenants (exposed under the ``"*"``
    key); malformed entries are skipped — a QoS config typo must not
    take the daemon down."""
    out = {"*": float(default)}
    for part in str(spec or "").replace(";", ",").split(","):
        part = part.strip()
        if not part or ":" not in part:
            continue
        name, _, val = part.rpartition(":")
        try:
            out[name.strip()] = float(val)
        except ValueError:
            continue
    return out


def tenant_weights(spec=None) -> dict:
    """Per-tenant fair-share weights (``PADDLE_TPU_TENANT_WEIGHTS``);
    unlisted tenants weigh 1.0, non-positive entries degrade to 1.0."""
    raw = _flags.env_value("PADDLE_TPU_TENANT_WEIGHTS") \
        if spec is None else spec
    out = parse_tenant_map(raw, default=1.0)
    return {t: (w if w > 0 else 1.0) for t, w in out.items()}


def tenant_quotas(spec=None) -> dict:
    """Per-tenant service-rate quotas (``PADDLE_TPU_TENANT_QUOTA``):
    tokens/second for the decode engine, rows/second for the dynamic
    batcher. 0 (the default) means unlimited."""
    raw = _flags.env_value("PADDLE_TPU_TENANT_QUOTA") \
        if spec is None else spec
    out = parse_tenant_map(raw, default=0.0)
    return {t: max(q, 0.0) for t, q in out.items()}


def max_queue_default() -> int:
    """Admission-control watermark (``PADDLE_TPU_SERVE_MAX_QUEUE``):
    queued requests past this are shed with ``RESOURCE_EXHAUSTED``
    instead of waiting out (and then blowing) the request deadline.
    0 disables shedding."""
    return int(_flags.env_value("PADDLE_TPU_SERVE_MAX_QUEUE"))


def bucket_ladder(max_batch: int, env: Optional[str] = None) -> List[int]:
    """The padded-shape ladder: ``PADDLE_TPU_SERVE_BUCKETS`` if set, else
    powers of two up to (and including) ``max_batch``."""
    spec = _flags.env_value("PADDLE_TPU_SERVE_BUCKETS") \
        if env is None else env
    if spec.strip():
        vals = sorted({int(t) for t in spec.replace(",", " ").split()})
        if not vals or vals[0] <= 0:
            raise ValueError(
                f"PADDLE_TPU_SERVE_BUCKETS must be positive ints, "
                f"got {spec!r}")
        return vals
    vals, v = [], 1
    while v < max_batch:
        vals.append(v)
        v *= 2
    vals.append(int(max_batch))
    return sorted(set(vals))


def next_bucket(n: int, ladder: Sequence[int]) -> int:
    """Smallest rung >= n; beyond the top the ladder continues by powers
    of two so oversized requests still land on a bounded shape set."""
    for v in ladder:
        if v >= n:
            return v
    v = ladder[-1]
    while v < n:
        v *= 2
    return v


class _Request:
    __slots__ = ("arrays", "rows", "key", "pad_map", "future", "t_enq",
                 "solo", "req_id", "tenant", "deferred")

    def __init__(self, arrays, rows, key, solo=False, req_id=0,
                 tenant="default"):
        self.arrays = arrays
        self.rows = rows
        self.key = key
        self.pad_map = {}          # padded trailing dim -> original dim
        self.future = Future()
        self.t_enq = time.perf_counter()
        self.solo = solo
        self.req_id = req_id       # observability: spans + error frames
        self.tenant = tenant       # QoS: weighted-fair anchor + quotas
        self.deferred = False      # quota-deferred at least once (metric)


class DynamicBatcher:
    """Deadline-based cross-request batcher over one or more Predictors.

    ``submit(inputs) -> Future`` enqueues a decoded request (list of
    numpy arrays, shared leading batch dim). The dispatcher thread forms
    batches of up to ``max_batch_size`` rows, waiting at most
    ``batch_timeout_ms`` past the oldest request's enqueue before
    dispatching a partial batch. Formed batches are handed round-robin to
    one worker thread per predictor (a ``PredictorPool`` pinned to
    distinct devices overlaps batches across chips).
    """

    def __init__(self, predictors, max_batch_size: int = DEFAULT_MAX_BATCH,
                 batch_timeout_ms: float = DEFAULT_TIMEOUT_MS,
                 ladder: Optional[Sequence[int]] = None,
                 trailing: Optional[str] = None,
                 max_queue: Optional[int] = None,
                 worker_max_restarts: int = 5):
        preds = getattr(predictors, "predictors", None)
        if preds is None:
            preds = (list(predictors)
                     if isinstance(predictors, (list, tuple))
                     else [predictors])
        if not preds:
            raise ValueError("DynamicBatcher needs at least one predictor")
        self._preds = preds
        self._max_batch = int(max_batch_size)
        if self._max_batch < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._timeout_s = float(batch_timeout_ms) / 1e3
        self._ladder = list(ladder) if ladder is not None \
            else bucket_ladder(self._max_batch)
        # a custom PADDLE_TPU_SERVE_BUCKETS ladder may top out below the
        # row budget; extend it so warmup_signatures covers every batch
        # bucket next_bucket can hand a full batch (zero-compile contract)
        while self._ladder[-1] < self._max_batch:
            self._ladder.append(self._ladder[-1] * 2)
        self._specs = preds[0].input_specs()
        self._n_inputs = len(self._specs)
        self._dyn_axes = [
            {j for j in range(1, len(shape)) if not isinstance(shape[j], int)}
            for shape, _ in self._specs]
        self._can_batch = bool(self._specs) and all(
            shape and not isinstance(shape[0], int)
            for shape, _ in self._specs)
        self._batch_sym = self._specs[0][0][0] if self._can_batch else None
        try:
            self._out_syms = [tuple(shape)
                              for shape, _ in preds[0].output_specs()]
        except Exception:
            self._out_syms = None     # un-padding then needs no pad_map
        self._trailing_syms = {self._specs[i][0][j]
                               for i in range(self._n_inputs)
                               for j in self._dyn_axes[i]}
        self._trailing = self._resolve_trailing(trailing)
        self._rowwise_ok = True      # flipped off if outputs aren't rowwise
        self._warned_rowwise = False

        self._q: deque = deque()
        self._forming = 0            # requests popped into the batch being formed
        # request-scoped observability: stage histograms + sampled JSONL
        # traces (PADDLE_TPU_TRACE_SAMPLE), and the stall flight recorder
        # (PADDLE_TPU_STALL_DUMP) — a watchdog that dumps every thread's
        # stack when queued work stops dispatching
        from ..observability import FlightRecorder, SpanRecorder, counter
        from ..observability import tracez as _tracez
        self._spans = SpanRecorder(component="serve")
        self._ring = _tracez.RING
        self._max_queue = max_queue_default() if max_queue is None \
            else int(max_queue)
        self._worker_max_restarts = int(worker_max_restarts)
        self._worker_restarts = 0
        self._dispatcher_error: Optional[BaseException] = None
        self._inflight = 0           # accepted, not yet delivered
        self._inflight_lock = threading.Lock()
        self._worker_restarts_total = counter(
            "paddle_tpu_serve_worker_restarts_total",
            "Pool predictor worker threads respawned in place after an "
            "uncaught crash (bounded backoff; an exhausted budget leaves "
            "the slot dead and /healthz unhealthy).")
        self._shed_total = counter(
            "paddle_tpu_serve_shed_total",
            "Requests refused at admission because the queue was past "
            "the PADDLE_TPU_SERVE_MAX_QUEUE watermark (typed "
            "RESOURCE_EXHAUSTED error frame).")
        # multi-tenant QoS: weighted-fair anchor selection over the queue
        # plus per-tenant rows/sec quotas (PADDLE_TPU_TENANT_WEIGHTS /
        # PADDLE_TPU_TENANT_QUOTA); same registry families as DecodeEngine
        self._weights = tenant_weights()
        self._quota = tenant_quotas()
        self._vrows: Dict[str, float] = {}     # weighted rows served
        self._quota_rows: Dict[str, float] = {}  # token buckets (rows)
        self._quota_ts = time.perf_counter()
        self._tenant_shed_total = counter(
            "paddle_tpu_tenant_shed_total",
            "Requests refused at admission because the tenant was over "
            "its weighted share of the pending queue (typed "
            "RESOURCE_EXHAUSTED error frame).", labelnames=("tenant",))
        self._quota_deferred_total = counter(
            "paddle_tpu_tenant_quota_deferred_total",
            "Requests held in queue past their turn because the tenant's "
            "token-rate quota (PADDLE_TPU_TENANT_QUOTA) was exhausted; "
            "deferred, never dropped.", labelnames=("tenant",))
        self._busy_batches = 0       # formed batches inside _execute
        self._recorder = FlightRecorder(
            "serve_batcher",
            busy_fn=lambda: bool(self._q) or self._busy_batches > 0,
            context_fn=self._stall_context)
        self._cond = threading.Condition()
        self._stop = False
        self._workers = []
        self._wqueues: List[Queue] = []
        if len(self._preds) > 1:
            # multi-chip: one worker per predictor so formed batches
            # overlap across devices; the dispatcher only forms + routes
            for i, p in enumerate(self._preds):
                wq: Queue = Queue(maxsize=4)  # backpressure per predictor
                t = threading.Thread(target=self._worker_main,
                                     args=(i, p, wq), daemon=True,
                                     name=f"serve-worker-{i}")
                t.start()
                self._wqueues.append(wq)
                self._workers.append(t)
        self._rr = 0
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True,
                                            name="serve-dispatcher")
        self._dispatcher.start()

    # -- trailing-dim padding policy -------------------------------------

    @property
    def trailing_bucketing(self) -> bool:
        """Whether trailing dynamic dims are bucketed (padded) — False
        means requests merge only on exact trailing shapes."""
        return self._trailing

    def _trailing_unpaddable(self):
        """True when results padded along a trailing axis could not be
        un-padded by symbol: output avals are unavailable, or some
        output axis is a derived expression (e.g. ``2*seqlen``) rather
        than a plain input symbol."""
        if self._out_syms is None:
            return True
        known = set(self._trailing_syms)
        if self._batch_sym is not None:
            known.add(self._batch_sym)
        return any(not isinstance(d, int) and d not in known
                   for syms in self._out_syms for d in syms)

    def _resolve_trailing(self, trailing) -> bool:
        import warnings

        mode = (trailing if trailing is not None else
                _flags.env_value("PADDLE_TPU_SERVE_TRAILING"))
        mode = str(mode).lower()
        if mode not in ("auto", "on", "off"):
            raise ValueError(
                f"trailing must be 'auto', 'on' or 'off', got {mode!r}")
        if mode == "off" or not (self._can_batch and self._trailing_syms):
            return False
        if self._trailing_unpaddable():
            if mode == "on":
                warnings.warn(
                    "DynamicBatcher: trailing='on' requested but output "
                    "axes cannot be un-padded by symbol (output avals "
                    "unavailable or derived dims); trailing-dim "
                    "bucketing stays off", RuntimeWarning)
            return False
        if mode == "on":
            return True
        # auto: prove padding-invariance with a padded-vs-unpadded probe
        try:
            ok = self._probe_trailing_invariance()
        except Exception:
            ok = False
        if not ok:
            warnings.warn(
                "DynamicBatcher: model outputs change under trailing "
                "zero-padding (probe mismatch); batching on the batch "
                "dim only. Pass trailing='on' (or "
                "PADDLE_TPU_SERVE_TRAILING=on) to force bucketing for a "
                "model you know is padding-invariant", RuntimeWarning)
        return ok

    def _probe_trailing_invariance(self) -> bool:
        """Run the model once on exact trailing shapes and once on the
        same rows zero-padded to the next rung; trailing bucketing is
        safe only if the un-padded results agree."""
        pred = self._preds[0]
        tgt = max(next_bucket(2, self._ladder), 2)
        orig = tgt - 1
        rng = np.random.default_rng(0)
        exact, padded = [], []
        for i, (shape, dtype) in enumerate(self._specs):
            dims = tuple(orig if j in self._dyn_axes[i] else shape[j]
                         for j in range(1, len(shape)))
            if np.issubdtype(dtype, np.floating):
                a = rng.standard_normal((1,) + dims).astype(dtype)
            elif dtype == np.bool_:
                a = rng.integers(0, 2, (1,) + dims).astype(dtype)
            else:
                a = rng.integers(0, 4, (1,) + dims).astype(dtype)
            exact.append(a)
            pdims = tuple(tgt if j in self._dyn_axes[i] else shape[j]
                          for j in range(1, len(shape)))
            m = np.zeros((1,) + pdims, dtype)
            m[tuple(slice(0, d) for d in a.shape)] = a
            padded.append(m)
        ref = pred.run_batch(exact)
        got = pred.run_batch(padded)
        if len(ref) != len(got):
            return False
        pad_map = {sym: orig for sym in self._trailing_syms}
        for k, (r, g) in enumerate(zip(ref, got)):
            g = self._unpad(g, self._out_syms[k], pad_map)
            if r.shape != g.shape or \
                    not np.allclose(r, g, rtol=1e-4, atol=1e-5):
                return False
        return True

    @staticmethod
    def _unpad(arr, syms, pad_map):
        """Slice trailing axes of one output row-block back to the
        originals recorded in ``pad_map`` — keyed by the SYMBOL the axis
        carries, so static axes (whatever their size) are untouched."""
        sl, changed = [slice(None)] * arr.ndim, False
        for j in range(1, arr.ndim):
            sym = syms[j] if syms is not None and j < len(syms) else None
            orig = pad_map.get(sym) if isinstance(sym, str) else None
            if orig is not None and orig != arr.shape[j]:
                sl[j] = slice(0, orig)
                changed = True
        return arr[tuple(sl)] if changed else arr

    @staticmethod
    def _tag(exc, req_id):
        """Stamp an exception with the request id it failed, so the wire
        layer can return the id in the error frame (grep-able against a
        sampled span trace)."""
        try:
            exc.request_id = int(req_id)
        except Exception:
            pass
        return exc

    def _set(self, fut, value=None, exc=None):
        """Deliver into a future the caller may have abandoned (e.g. a
        server-side request deadline cancelled it) without letting
        InvalidStateError kill the dispatcher/worker thread. Every
        ACCEPTED request is delivered through here exactly once, so this
        is also where the in-flight count (quiesce/drain accounting)
        goes down."""
        try:
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(value)
        except InvalidStateError:
            return
        with self._inflight_lock:
            self._inflight -= 1

    # -- request intake --------------------------------------------------

    def submit(self, inputs, tenant=None) -> Future:
        """Enqueue one request; the returned Future resolves to the list
        of output arrays for exactly this request's rows (or raises the
        per-request error). The future carries the assigned request id
        as ``.request_id``; errors carry the same id so a failing
        request is traceable end to end. ``tenant`` tags the request for
        weighted-fair anchor selection, per-tenant quota, and a
        per-tenant share of the queue watermark."""
        from ..observability import next_request_id
        req_id = next_request_id()
        tenant = str(tenant).strip() if tenant else "default"
        try:
            # no ascontiguousarray here: assembly copies into the zeroed
            # bucket buffer anyway, and the solo path normalizes itself
            arrays = [np.asarray(a) for a in inputs]
            if len(arrays) != self._n_inputs:
                raise ValueError(
                    f"model takes {self._n_inputs} inputs, got "
                    f"{len(arrays)}")
            req = self._make_request(arrays, req_id)
            req.tenant = tenant
        except Exception as e:
            fut = Future()
            fut.request_id = req_id
            fut.set_exception(self._tag(e, req_id))
            return fut
        req.future.request_id = req_id
        with self._cond:
            if self._stop:
                # typed so a front router fails the request over to a
                # live backend instead of relaying a terminal error
                req.future.set_exception(self._tag(TypedServeError(
                    ERR_UNAVAILABLE, "DynamicBatcher is stopped"), req_id))
                return req.future
            if self._dispatcher_error is not None \
                    or not self._dispatcher.is_alive():
                # a dead dispatcher would never dequeue this request;
                # fail NOW, not after the request deadline
                req.future.set_exception(self._tag(TypedServeError(
                    ERR_UNAVAILABLE,
                    "serve dispatcher is dead "
                    f"({self._dispatcher_error!r}); restart the daemon"),
                    req_id))
                return req.future
            # admission control counts the batch being formed too: the
            # dispatcher pops requests out of _q while merging, and that
            # in-formation work is still queued latency-wise — without it
            # the watermark has a hole exactly as wide as the formation
            # window (tsan-lite caught the race)
            depth = len(self._q) + self._forming
            if self._max_queue:
                # per-tenant watermark share: with multiple tenants
                # queued, nobody may hold more than their weighted slice
                # of the watermark — a flood tenant sheds while the
                # well-behaved tenant's slice stays admissible (the
                # flood must not be able to shed everyone by filling the
                # global queue; 2x the watermark is the hard backstop).
                # A single tenant keeps the whole watermark (back-compat
                # with the pre-QoS global check).
                tset = {r.tenant for r in self._q} | {tenant}
                if len(tset) > 1:
                    mine = sum(1 for r in self._q if r.tenant == tenant)
                    wsum = sum(self._weight(t) for t in tset)
                    share = max(1, round(
                        self._max_queue * self._weight(tenant) / wsum))
                    if mine >= share or depth >= 2 * self._max_queue:
                        self._tenant_shed_total.labels(
                            tenant=tenant).inc()
                        req.future.set_exception(self._tag(
                            TypedServeError(
                                ERR_RESOURCE_EXHAUSTED,
                                f"serve queue past watermark for tenant "
                                f"{tenant!r} ({mine} of its {share}-slot "
                                "share queued; PADDLE_TPU_TENANT_WEIGHTS"
                                ")"), req_id))
                        return req.future
                elif depth >= self._max_queue:
                    # admission control: past the watermark the queue
                    # can only add deadline-bound latency — shed instead
                    self._shed_total.inc()
                    req.future.set_exception(self._tag(TypedServeError(
                        ERR_RESOURCE_EXHAUSTED,
                        f"serve queue past watermark ({depth} >= "
                        f"{self._max_queue} queued; "
                        "PADDLE_TPU_SERVE_MAX_QUEUE)"), req_id))
                    return req.future
            self._q.append(req)
            with self._inflight_lock:
                self._inflight += 1
            self._cond.notify_all()
        return req.future

    def _make_request(self, arrays, req_id=0) -> _Request:
        if not (self._can_batch and self._rowwise_ok):
            return _Request(arrays, rows=1, key=object(), solo=True,
                            req_id=req_id)
        rows = None
        for i, a in enumerate(arrays):
            shape, _ = self._specs[i]
            if a.ndim != len(shape):
                raise ValueError(
                    f"input {i}: expected ndim {len(shape)}, got {a.ndim}")
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise ValueError(
                    "inputs disagree on the leading batch dim "
                    f"({rows} vs {a.shape[0]})")
        key = []
        for i, a in enumerate(arrays):
            # trailing dynamic dims bucket to the ladder only when the
            # policy proved (or the caller forced) padding-invariance;
            # otherwise they stay exact and only same-shape requests merge
            trailing = tuple(
                next_bucket(a.shape[j], self._ladder)
                if self._trailing and j in self._dyn_axes[i] else a.shape[j]
                for j in range(1, a.ndim))
            key.append((str(a.dtype), trailing))
        return _Request(arrays, rows=int(rows), key=tuple(key),
                        req_id=req_id)

    # -- batch formation -------------------------------------------------

    def _form_batch(self):
        """Blocks for the next batch: the oldest request of the most
        underserved (weighted virtual-rows) quota-eligible tenant anchors
        the key and the deadline; same-key requests are merged until the
        row budget or the deadline is hit. Quota-blocked tenants keep
        their place in queue (deferred, never dropped); stop() drains the
        queue ignoring quotas."""
        with self._cond:
            while True:
                if not self._q:
                    if self._stop:
                        return None
                    self._cond.wait(0.25)
                    continue
                self._refill_quota()
                first = self._pick_anchor()
                if first is not None:
                    break
                if self._stop:
                    first = self._q.popleft()   # drain ignores quota
                    break
                self._cond.wait(0.05)           # wait for quota refill
            reqs, rows = [first], first.rows
            self._forming = 1
            try:
                if first.solo:
                    return reqs, first.key, rows
                deadline = first.t_enq + self._timeout_s
                while rows < self._max_batch:
                    taken = []
                    for r in self._q:
                        if r.solo or r.key != first.key:
                            continue
                        if rows + r.rows > self._max_batch:
                            continue
                        if r.tenant != first.tenant \
                                and not self._quota_room(r.tenant):
                            continue   # quota-blocked rows never ride along
                        taken.append(r)
                        rows += r.rows
                        if rows >= self._max_batch:
                            break
                    for r in taken:
                        self._q.remove(r)
                    reqs.extend(taken)
                    self._forming = len(reqs)
                    if rows >= self._max_batch or self._stop:
                        break
                    now = time.perf_counter()
                    if now >= deadline:
                        break
                    self._cond.wait(min(deadline - now, 0.05))
                return reqs, first.key, rows
            finally:
                for r in reqs:
                    self._note_rows(r)
                self._forming = 0

    # -- QoS scheduling ---------------------------------------------------

    def _weight(self, tenant) -> float:
        return self._weights.get(tenant, self._weights["*"])

    def _quota_rate(self, tenant) -> float:
        return self._quota.get(tenant, self._quota["*"])

    def _refill_quota(self):
        """Advance every tenant's rows/sec token bucket (capped at one
        burst = max(rate, 1.0) rows). Caller holds _cond."""
        now = time.perf_counter()
        dt, self._quota_ts = now - self._quota_ts, now
        if dt <= 0:
            return
        for t in list(self._quota_rows):
            rate = self._quota_rate(t)
            if rate <= 0:
                self._quota_rows.pop(t)   # quota removed at runtime
                continue
            burst = max(rate, 1.0)
            self._quota_rows[t] = min(burst,
                                      self._quota_rows[t] + rate * dt)

    def _quota_room(self, tenant) -> bool:
        """True when the tenant may dispatch rows right now. Buckets are
        lazily created at full burst; rate <= 0 means unmetered."""
        rate = self._quota_rate(tenant)
        if rate <= 0:
            return True
        if tenant not in self._quota_rows:
            self._quota_rows[tenant] = max(rate, 1.0)
        return self._quota_rows[tenant] > 0.0

    def _pick_anchor(self):
        """Pop and return the oldest request of the most underserved
        quota-eligible tenant (lowest weighted virtual rows), or None if
        every queued tenant is quota-blocked. Caller holds _cond."""
        heads = {}
        for r in self._q:
            if r.tenant not in heads:
                heads[r.tenant] = r
        best = None
        for t, r in heads.items():
            try:
                chaos.maybe_fail("batcher.quota")
                ok = self._quota_room(t)
            except Exception:
                ok = False   # drill: treat the tenant as quota-blocked
            if not ok:
                if not r.deferred:
                    r.deferred = True
                    self._quota_deferred_total.labels(tenant=t).inc()
                continue
            v = self._vrows.get(t, 0.0)
            if best is None or v < best[0]:
                best = (v, r)
        if best is None:
            return None
        self._q.remove(best[1])
        # idle-tenant catch-up floor: a tenant returning from idle starts
        # at the busiest peer's deficit, not at zero-for-all-history
        if self._vrows:
            floor = min(self._vrows.values())
            t = best[1].tenant
            self._vrows[t] = max(self._vrows.get(t, 0.0), floor)
        return best[1]

    def _note_rows(self, req):
        """Charge a dispatched request's rows to its tenant: advances the
        weighted-fair clock and drains the quota bucket (which may go
        negative — burst debt pays back over time). Caller holds _cond."""
        self._vrows[req.tenant] = (self._vrows.get(req.tenant, 0.0)
                                   + req.rows / self._weight(req.tenant))
        if req.tenant in self._quota_rows:
            self._quota_rows[req.tenant] -= req.rows

    def _dispatch_loop(self):
        formed = None
        try:
            while True:
                t_form = time.perf_counter()
                formed = self._form_batch()
                if formed is None:
                    return
                # form span covers dequeue + merge window (idle wait for
                # the FIRST request included: that's queue starvation,
                # worth seeing on the timeline)
                self._ring.complete("batch.form", t_form,
                                    time.perf_counter(),
                                    {"rows": formed[2],
                                     "reqs": len(formed[0])})
                chaos.maybe_fail("batcher.dispatch")
                if not self._wqueues:
                    # single predictor: execute inline — a queue handoff
                    # to a worker thread costs a context switch per batch
                    # for no overlap gain on one device
                    self._execute(self._preds[0], *formed)
                else:
                    wq = self._wqueues[self._rr % len(self._wqueues)]
                    self._rr += 1
                    wq.put(formed)
                formed = None
        except BaseException as e:   # noqa: BLE001 - the thread is dying
            self._on_dispatcher_death(e, formed)

    def _on_dispatcher_death(self, exc, formed):
        """The dispatcher thread is dying on an uncaught exception: every
        queued request (and the batch in hand) gets a typed UNAVAILABLE
        error frame NOW — connection threads must not sit out the full
        request deadline for work that can never run — and `submit`
        fails fast from here on."""
        import warnings
        with self._cond:
            self._dispatcher_error = exc
            pending = list(self._q)
            self._q.clear()
        if formed is not None:
            pending = list(formed[0]) + pending
        for r in pending:
            self._set(r.future, exc=self._tag(TypedServeError(
                ERR_UNAVAILABLE,
                f"serve dispatcher died mid-flight ({exc!r}); "
                "restart the daemon"), r.req_id))
        warnings.warn(
            f"DynamicBatcher dispatcher thread died ({exc!r}); "
            f"{len(pending)} queued request(s) failed with UNAVAILABLE "
            "and all future submits fail fast", RuntimeWarning)

    # -- execution -------------------------------------------------------

    def _assemble(self, reqs, key):
        """Pack same-key requests into one zero-initialized bucket-shaped
        buffer per input (single allocation: batch-dim and trailing-dim
        padding fall out of the zeros). Returns
        (stacked_inputs, bucket, real_elems, padded_elems)."""
        total_rows = sum(r.rows for r in reqs)
        bucket = next_bucket(total_rows, self._ladder)
        stacked, real, padded = [], 0, 0
        for i in range(self._n_inputs):
            target_trailing = tuple(key[i][1])
            mat = np.zeros((bucket,) + target_trailing,
                           reqs[0].arrays[i].dtype)
            off = 0
            for r in reqs:
                a = r.arrays[i]
                real += a.size
                if a.shape[1:] == target_trailing:
                    mat[off:off + r.rows] = a
                else:
                    mat[(slice(off, off + r.rows),)
                        + tuple(slice(0, d) for d in a.shape[1:])] = a
                    # bookkeeping is keyed by the axis SYMBOL, never the
                    # padded size: two axes sharing a rung cannot
                    # collide, and a static output dim that happens to
                    # equal the rung is never sliced
                    spec_shape = self._specs[i][0]
                    for j, tgt in enumerate(target_trailing, start=1):
                        if a.shape[j] != tgt:
                            r.pad_map[spec_shape[j]] = a.shape[j]
                off += r.rows
            padded += mat.size
            stacked.append(mat)
        return stacked, bucket, real, padded

    def _deliver(self, r, res, spans, bucket):
        """Deliver one request's result with its span breakdown: the
        spans are stamped on the future BEFORE the result lands so the
        wire layer (which wakes on delivery) can echo them in a traced
        reply without racing the recorder."""
        r.future.spans = dict(spans)
        self._spans.record(r.req_id, spans,
                           extra={"rows": r.rows, "bucket": bucket})
        self._set(r.future, res)

    def _slice_back(self, outs, reqs, bucket, times=None) -> bool:
        """Hand each request its row slice (and un-pad trailing dims it
        contributed padding to, by symbol). False when the outputs are
        not rowwise — or padded results could not be un-padded safely —
        and the caller must fall back to per-request execution.
        ``times=(t_formed, t_padded, t_executed)`` makes delivery record
        each request's span breakdown (and stamp it on the future)."""
        syms = self._out_syms
        if syms is not None and len(outs) != len(syms):
            syms = None
        if syms is not None:
            # symbol-verified rowwise: every output leads with the batch
            # symbol (a static leading dim that merely equals the bucket
            # is NOT rowwise and must not be sliced per request)
            if not all(s and s[0] == self._batch_sym for s in syms):
                return False
            if not all(o.ndim >= 1 and o.shape[0] == bucket for o in outs):
                return False
        else:
            if not all(o.ndim >= 1 and o.shape[0] == bucket for o in outs):
                return False
            if any(r.pad_map for r in reqs):
                # trailing padding happened but output symbols are
                # unknown: un-padding would be guesswork
                return False
        off = 0
        for r in reqs:
            res = []
            for k, o in enumerate(outs):
                s = o[off:off + r.rows]
                if r.pad_map and syms is not None:
                    s = self._unpad(s, syms[k], r.pad_map)
                res.append(s)            # views; the wire path copies
            if times is not None:
                t0, t1, t2 = times
                self._deliver(r, res,
                              {"queue_wait": t0 - r.t_enq, "pad": t1 - t0,
                               "execute": t2 - t1,
                               "unpad": time.perf_counter() - t2},
                              bucket)
            else:
                self._set(r.future, res)
            off += r.rows
        return True

    def _worker_main(self, idx: int, pred, wq: Queue):
        """Supervised worker: a crash fails the in-flight batch with a
        typed frame, then the loop re-enters after a bounded backoff —
        the device slot does NOT go silently idle. An exhausted restart
        budget lets the thread die, which flips ``workers_alive`` (and
        /healthz) so the outage is visible."""
        import warnings
        from ..utils.retry import backoff_delays
        delays = backoff_delays(self._worker_max_restarts,
                                base_delay=0.05, max_delay=2.0)
        while True:
            try:
                self._worker_loop(pred, wq)
                return
            except BaseException as e:   # noqa: BLE001 - supervise all
                if self._stop:
                    return
                self._worker_restarts += 1
                self._worker_restarts_total.inc()
                try:
                    delay = next(delays)
                except StopIteration:
                    warnings.warn(
                        f"serve worker {idx} died {self._worker_restarts} "
                        f"times (last: {e!r}); restart budget exhausted — "
                        "slot is dead, /healthz goes unhealthy",
                        RuntimeWarning)
                    return
                warnings.warn(
                    f"serve worker {idx} crashed ({e!r}); respawning in "
                    f"{delay:.2f}s", RuntimeWarning)
                time.sleep(delay)

    def _worker_loop(self, pred, wq: Queue):
        while True:
            item = wq.get()
            if item is None:
                return
            try:
                chaos.maybe_fail("batcher.worker")
                self._execute(pred, *item)
            except BaseException as e:
                # fail the batch in hand before the supervisor respawns
                # us: its futures would otherwise wait out the deadline
                for r in item[0]:
                    self._set(r.future, exc=self._tag(TypedServeError(
                        ERR_UNAVAILABLE,
                        f"serve worker crashed mid-batch ({e!r})"),
                        r.req_id))
                raise

    def _execute(self, pred, reqs, key, rows):
        # busy accounting + heartbeat bracket the real work so the stall
        # flight recorder can tell "no traffic" from "wedged mid-batch"
        self._busy_batches += 1
        try:
            self._execute_inner(pred, reqs, key, rows)
        finally:
            self._busy_batches -= 1
            self._recorder.beat()

    def _execute_inner(self, pred, reqs, key, rows):
        from .. import profiler

        qdepth = len(self._q)
        if not reqs[0].solo:
            try:
                t0 = time.perf_counter()
                stacked, bucket, real, padded = self._assemble(reqs, key)
                t1 = time.perf_counter()
                outs = pred.run_batch(stacked)
                t2 = time.perf_counter()
                self._ring.complete("batch.pad", t0, t1,
                                    {"bucket": bucket, "rows": rows})
                self._ring.complete("batch.execute", t1, t2,
                                    {"bucket": bucket})
                if self._slice_back(outs, reqs, bucket,
                                    times=(t0, t1, t2)):
                    now = time.perf_counter()
                    self._ring.complete("batch.unpad", t2, now)
                    profiler.record_serve_batch(rows, bucket, real, padded,
                                                qdepth)
                    profiler.record_serve_requests(
                        [now - r.t_enq for r in reqs])
                    return
                # outputs are not rowwise (batch-reducing model): stop
                # merging requests from here on — correctness first
                self._rowwise_ok = False
                if not self._warned_rowwise:
                    self._warned_rowwise = True
                    import warnings
                    warnings.warn(
                        "DynamicBatcher: model outputs are not rowwise "
                        "(leading dim != dispatched batch); falling back "
                        "to per-request execution", RuntimeWarning)
            except Exception:
                pass               # isolate below, request by request
        # per-request fallback: a poison request fails only itself
        for r in reqs:
            if r.future.done():
                continue
            try:
                t0 = time.perf_counter()
                if r.solo or not self._rowwise_ok:
                    outs = pred.run_batch(r.arrays)
                    t2 = time.perf_counter()
                    self._deliver(r, [np.asarray(o) for o in outs],
                                  {"queue_wait": t0 - r.t_enq, "pad": 0.0,
                                   "execute": t2 - t0,
                                   "unpad": time.perf_counter() - t2},
                                  r.rows)
                else:
                    r.pad_map.clear()
                    stacked, bucket, real, padded = self._assemble(
                        [r], r.key)
                    t1 = time.perf_counter()
                    outs = pred.run_batch(stacked)
                    t2 = time.perf_counter()
                    if not self._slice_back(outs, [r], bucket,
                                            times=(t0, t1, t2)):
                        outs = pred.run_batch(r.arrays)
                        t2 = time.perf_counter()
                        self._deliver(
                            r, [np.asarray(o) for o in outs],
                            {"queue_wait": t0 - r.t_enq, "pad": t1 - t0,
                             "execute": t2 - t1,
                             "unpad": time.perf_counter() - t2},
                            bucket)
                    profiler.record_serve_batch(r.rows, bucket, real,
                                                padded, qdepth)
                profiler.record_serve_request(
                    time.perf_counter() - r.t_enq)
            except Exception as e:
                profiler.record_serve_error()
                # a failed request still traces: same line schema with
                # the stages it never reached at zero, plus the error —
                # and the partial breakdown rides the error frame's ctx
                err_spans = {"queue_wait": t0 - r.t_enq, "pad": 0.0,
                             "execute": 0.0, "unpad": 0.0}
                try:
                    e.spans = err_spans
                except Exception:
                    pass
                self._spans.record(
                    r.req_id, err_spans,
                    extra={"rows": r.rows, "error": type(e).__name__})
                self._set(r.future, exc=self._tag(e, r.req_id))

    # -- warmup ----------------------------------------------------------

    def warmup_signatures(self) -> List[list]:
        """The bounded signature set steady-state traffic maps onto: the
        cross product of batch-ladder rungs and ladder rungs per distinct
        trailing dynamic symbol (shared symbols vary together), capped at
        _WARMUP_SIG_CAP signatures."""
        if not self._can_batch:
            return []
        # the ladder's top rung is >= max_batch (extended in __init__),
        # so every batch bucket formation can produce is covered — a full
        # batch on a sparse custom ladder may dispatch ABOVE max_batch
        batch_cap = next_bucket(self._max_batch, self._ladder)
        batch_rungs = [b for b in self._ladder if b <= batch_cap]
        syms: List[str] = []
        for i, (shape, _) in enumerate(self._specs):
            for j in self._dyn_axes[i]:
                s = shape[j]
                if s not in syms:
                    syms.append(s)
        # with trailing bucketing off, dynamic trailing shapes pass
        # through exactly — warming the ladder would compile shapes
        # traffic may never hit, so warm one representative rung only
        trail_rungs = self._ladder if self._trailing else [self._ladder[-1]]
        sigs = []
        for combo in product(batch_rungs, *[trail_rungs for _ in syms]):
            assign = dict(zip(syms, combo[1:]))
            sig = []
            for shape, dtype in self._specs:
                dims = [combo[0]]
                for j, d in enumerate(shape[1:], start=1):
                    dims.append(d if isinstance(d, int)
                                else assign.get(d, self._ladder[-1]))
                sig.append((tuple(dims), dtype))
            sigs.append(sig)
            if len(sigs) >= _WARMUP_SIG_CAP:
                break
        return sigs

    def warmup(self) -> int:
        """AOT-compile the whole bucket set on every pooled predictor;
        returns the number of compiles actually performed (0 when the
        persistent cache or a prior warmup already holds them all)."""
        from .. import profiler

        sigs = self.warmup_signatures()
        before = len(profiler.compile_events())
        for pred in self._preds:
            pred.warm(sigs)
        return len(profiler.compile_events()) - before

    # -- lifecycle -------------------------------------------------------

    @property
    def ladder(self) -> List[int]:
        return list(self._ladder)

    @property
    def queue_depth(self) -> int:
        return len(self._q)

    @property
    def forming(self) -> int:
        """Requests the dispatcher has popped into the batch it is still
        forming — counted by admission control alongside ``queue_depth``."""
        return self._forming

    @property
    def oldest_wait_s(self) -> float:
        """Seconds the oldest queued request has been waiting — 0.0 on
        an empty queue. The /healthz wedge check compares this against
        the request deadline."""
        try:
            head = self._q[0]
        except IndexError:
            return 0.0
        return max(0.0, time.perf_counter() - head.t_enq)

    @property
    def dispatcher_alive(self) -> bool:
        return self._dispatcher.is_alive() \
            and self._dispatcher_error is None

    @property
    def worker_restarts(self) -> int:
        """Times a crashed pool worker was respawned in place."""
        return self._worker_restarts

    @property
    def max_queue(self) -> int:
        """Admission watermark (0 = shedding off)."""
        return self._max_queue

    @property
    def inflight(self) -> int:
        """Accepted requests whose future has not been delivered yet."""
        with self._inflight_lock:
            return self._inflight

    def quiesce(self, timeout: float = 30.0) -> bool:
        """Block until every ACCEPTED request has been answered (result
        or error delivered into its future) — the drain step of a
        SIGTERM'd daemon: stop enqueueing first, then quiesce, then
        stop(). True on quiet, False on timeout."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            if self.inflight <= 0:
                return True
            time.sleep(0.01)
        return self.inflight <= 0

    @property
    def workers_alive(self) -> bool:
        """True while every pooled-predictor worker thread is alive
        (vacuously true in single-predictor inline mode)."""
        return all(t.is_alive() for t in self._workers)

    def _stall_context(self):
        """Flight-recorder context: what the queue looked like when the
        watchdog fired (bounded to the 32 oldest queued requests)."""
        got = self._cond.acquire(timeout=1.0)
        try:
            queued = [{"request_id": r.req_id, "rows": r.rows,
                       "age_s": round(time.perf_counter() - r.t_enq, 3),
                       "solo": r.solo}
                      for r in list(self._q)[:32]]
            depth = len(self._q)
        finally:
            if got:
                self._cond.release()
        return {"queue_depth": depth,
                "busy_batches": self._busy_batches,
                "oldest_wait_s": round(self.oldest_wait_s, 3),
                "dispatcher_alive": self.dispatcher_alive,
                "workers_alive": self.workers_alive,
                "cond_lock_acquired": got,
                "queued": queued}

    def stop(self):
        """Stop accepting work, drain the queue into errors, and join the
        dispatcher + workers."""
        with self._cond:
            self._stop = True
            pending = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for r in pending:
            # UNAVAILABLE, not a bare RuntimeError: a stopping backend is
            # the canonical failover case for a front router
            self._set(r.future, exc=self._tag(TypedServeError(
                ERR_UNAVAILABLE, "DynamicBatcher stopped"), r.req_id))
        self._dispatcher.join(timeout=5)
        for wq in self._wqueues:
            wq.put(None)
        for t in self._workers:
            t.join(timeout=5)
        self._recorder.stop()
        self._spans.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
