"""Dynamic cross-request batching for the inference daemon.

The serve daemon's historical shape — thread-per-connection, one
predictor call per request under a global lock — bounds throughput by
per-request dispatch overhead and lets every novel input shape trigger a
recompile. This module is the Clipper/Orca-style fix: reader threads
enqueue decoded tensors, ONE dispatcher thread forms batches under a
deadline, each formed batch is padded to a shape bucket from a bounded
ladder and executed through the predictor's per-bucket AOT cache
(`jit.compile_cache.AotCache`), and the results are sliced back
per-request into futures. The compiled-shape set is therefore finite and
warmable: after `DynamicBatcher.warmup()` a mixed-shape request stream
compiles nothing.

Shape buckets
    The ladder defaults to powers of two up to ``max_batch_size`` and is
    overridable via ``PADDLE_TPU_SERVE_BUCKETS`` (comma/space separated
    ints, e.g. ``"1,2,4,8,16,32"``). The batch (leading) dim of a formed
    batch is padded UP to the next rung; trailing *dynamic* dims (the
    export's symbolic axes, e.g. a ``"seqlen"`` spec) are bucketed with
    the same ladder — requests whose trailing dims land in the same rung
    batch together and are zero-padded to it. Values beyond the top rung
    grow by powers of two (one compile each, still bounded).

Correctness contract
    Batch-dim padding assumes row-independent outputs (true of any
    batch-polymorphic export whose leading symbol is the batch); the
    engine verifies each output's leading dim equals the dispatched
    bucket and falls back to per-request execution otherwise. Trailing
    zero-padding additionally assumes padding-invariance per row
    (elementwise/masked models); see docs/serving.md for the caveat.

Error isolation
    A failed batch is re-executed per request, so a poison request (bad
    static dim, NaN-triggering payload, ...) fails only its own future.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from concurrent.futures import Future
from itertools import product
from queue import Queue
from typing import List, Optional, Sequence

import numpy as np

__all__ = ["DynamicBatcher", "bucket_ladder", "next_bucket",
           "DEFAULT_MAX_BATCH", "DEFAULT_TIMEOUT_MS"]

DEFAULT_MAX_BATCH = 8
DEFAULT_TIMEOUT_MS = 2.0
_WARMUP_SIG_CAP = 64          # cross-product guard for many dynamic dims


def bucket_ladder(max_batch: int, env: Optional[str] = None) -> List[int]:
    """The padded-shape ladder: ``PADDLE_TPU_SERVE_BUCKETS`` if set, else
    powers of two up to (and including) ``max_batch``."""
    spec = os.environ.get("PADDLE_TPU_SERVE_BUCKETS", "") \
        if env is None else env
    if spec.strip():
        vals = sorted({int(t) for t in spec.replace(",", " ").split()})
        if not vals or vals[0] <= 0:
            raise ValueError(
                f"PADDLE_TPU_SERVE_BUCKETS must be positive ints, "
                f"got {spec!r}")
        return vals
    vals, v = [], 1
    while v < max_batch:
        vals.append(v)
        v *= 2
    vals.append(int(max_batch))
    return sorted(set(vals))


def next_bucket(n: int, ladder: Sequence[int]) -> int:
    """Smallest rung >= n; beyond the top the ladder continues by powers
    of two so oversized requests still land on a bounded shape set."""
    for v in ladder:
        if v >= n:
            return v
    v = ladder[-1]
    while v < n:
        v *= 2
    return v


class _Request:
    __slots__ = ("arrays", "rows", "key", "pad_map", "future", "t_enq",
                 "solo")

    def __init__(self, arrays, rows, key, solo=False):
        self.arrays = arrays
        self.rows = rows
        self.key = key
        self.pad_map = {}          # padded trailing dim -> original dim
        self.future = Future()
        self.t_enq = time.perf_counter()
        self.solo = solo


class DynamicBatcher:
    """Deadline-based cross-request batcher over one or more Predictors.

    ``submit(inputs) -> Future`` enqueues a decoded request (list of
    numpy arrays, shared leading batch dim). The dispatcher thread forms
    batches of up to ``max_batch_size`` rows, waiting at most
    ``batch_timeout_ms`` past the oldest request's enqueue before
    dispatching a partial batch. Formed batches are handed round-robin to
    one worker thread per predictor (a ``PredictorPool`` pinned to
    distinct devices overlaps batches across chips).
    """

    def __init__(self, predictors, max_batch_size: int = DEFAULT_MAX_BATCH,
                 batch_timeout_ms: float = DEFAULT_TIMEOUT_MS,
                 ladder: Optional[Sequence[int]] = None):
        preds = getattr(predictors, "predictors", None)
        if preds is None:
            preds = (list(predictors)
                     if isinstance(predictors, (list, tuple))
                     else [predictors])
        if not preds:
            raise ValueError("DynamicBatcher needs at least one predictor")
        self._preds = preds
        self._max_batch = int(max_batch_size)
        if self._max_batch < 1:
            raise ValueError("max_batch_size must be >= 1")
        self._timeout_s = float(batch_timeout_ms) / 1e3
        self._ladder = list(ladder) if ladder is not None \
            else bucket_ladder(self._max_batch)
        self._specs = preds[0].input_specs()
        self._n_inputs = len(self._specs)
        self._dyn_axes = [
            {j for j in range(1, len(shape)) if not isinstance(shape[j], int)}
            for shape, _ in self._specs]
        self._can_batch = bool(self._specs) and all(
            shape and not isinstance(shape[0], int)
            for shape, _ in self._specs)
        self._rowwise_ok = True      # flipped off if outputs aren't rowwise
        self._warned_rowwise = False

        self._q: deque = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._workers = []
        self._wqueues: List[Queue] = []
        if len(self._preds) > 1:
            # multi-chip: one worker per predictor so formed batches
            # overlap across devices; the dispatcher only forms + routes
            for i, p in enumerate(self._preds):
                wq: Queue = Queue(maxsize=4)  # backpressure per predictor
                t = threading.Thread(target=self._worker_loop,
                                     args=(p, wq), daemon=True,
                                     name=f"serve-worker-{i}")
                t.start()
                self._wqueues.append(wq)
                self._workers.append(t)
        self._rr = 0
        self._dispatcher = threading.Thread(target=self._dispatch_loop,
                                            daemon=True,
                                            name="serve-dispatcher")
        self._dispatcher.start()

    # -- request intake --------------------------------------------------

    def submit(self, inputs) -> Future:
        """Enqueue one request; the returned Future resolves to the list
        of output arrays for exactly this request's rows (or raises the
        per-request error)."""
        try:
            # no ascontiguousarray here: assembly copies into the zeroed
            # bucket buffer anyway, and the solo path normalizes itself
            arrays = [np.asarray(a) for a in inputs]
            if len(arrays) != self._n_inputs:
                raise ValueError(
                    f"model takes {self._n_inputs} inputs, got "
                    f"{len(arrays)}")
            req = self._make_request(arrays)
        except Exception as e:
            fut = Future()
            fut.set_exception(e)
            return fut
        with self._cond:
            if self._stop:
                req.future.set_exception(
                    RuntimeError("DynamicBatcher is stopped"))
                return req.future
            self._q.append(req)
            self._cond.notify_all()
        return req.future

    def _make_request(self, arrays) -> _Request:
        if not (self._can_batch and self._rowwise_ok):
            return _Request(arrays, rows=1, key=object(), solo=True)
        rows = None
        for i, a in enumerate(arrays):
            shape, _ = self._specs[i]
            if a.ndim != len(shape):
                raise ValueError(
                    f"input {i}: expected ndim {len(shape)}, got {a.ndim}")
            if rows is None:
                rows = a.shape[0]
            elif a.shape[0] != rows:
                raise ValueError(
                    "inputs disagree on the leading batch dim "
                    f"({rows} vs {a.shape[0]})")
        key = []
        for i, a in enumerate(arrays):
            trailing = tuple(
                next_bucket(a.shape[j], self._ladder)
                if j in self._dyn_axes[i] else a.shape[j]
                for j in range(1, a.ndim))
            key.append((str(a.dtype), trailing))
        return _Request(arrays, rows=int(rows), key=tuple(key))

    # -- batch formation -------------------------------------------------

    def _form_batch(self):
        """Blocks for the next batch: the oldest queued request anchors
        the key and the deadline; same-key requests are merged until the
        row budget or the deadline is hit."""
        with self._cond:
            while not self._q and not self._stop:
                self._cond.wait(0.25)
            if not self._q:
                return None
            first = self._q.popleft()
            reqs, rows = [first], first.rows
            if first.solo:
                return reqs, first.key, rows
            deadline = first.t_enq + self._timeout_s
            while rows < self._max_batch:
                taken = []
                for r in self._q:
                    if r.solo or r.key != first.key:
                        continue
                    if rows + r.rows > self._max_batch:
                        continue
                    taken.append(r)
                    rows += r.rows
                    if rows >= self._max_batch:
                        break
                for r in taken:
                    self._q.remove(r)
                reqs.extend(taken)
                if rows >= self._max_batch or self._stop:
                    break
                now = time.perf_counter()
                if now >= deadline:
                    break
                self._cond.wait(min(deadline - now, 0.05))
            return reqs, first.key, rows

    def _dispatch_loop(self):
        while True:
            formed = self._form_batch()
            if formed is None:
                return
            if not self._wqueues:
                # single predictor: execute inline — a queue handoff to a
                # worker thread costs a context switch per batch for no
                # overlap gain on one device
                self._execute(self._preds[0], *formed)
                continue
            wq = self._wqueues[self._rr % len(self._wqueues)]
            self._rr += 1
            wq.put(formed)

    # -- execution -------------------------------------------------------

    def _assemble(self, reqs, key):
        """Pack same-key requests into one zero-initialized bucket-shaped
        buffer per input (single allocation: batch-dim and trailing-dim
        padding fall out of the zeros). Returns
        (stacked_inputs, bucket, real_elems, padded_elems)."""
        total_rows = sum(r.rows for r in reqs)
        bucket = next_bucket(total_rows, self._ladder)
        stacked, real, padded = [], 0, 0
        for i in range(self._n_inputs):
            target_trailing = tuple(key[i][1])
            mat = np.zeros((bucket,) + target_trailing,
                           reqs[0].arrays[i].dtype)
            off = 0
            for r in reqs:
                a = r.arrays[i]
                real += a.size
                if a.shape[1:] == target_trailing:
                    mat[off:off + r.rows] = a
                else:
                    mat[(slice(off, off + r.rows),)
                        + tuple(slice(0, d) for d in a.shape[1:])] = a
                    for j, tgt in enumerate(target_trailing, start=1):
                        if a.shape[j] != tgt:
                            r.pad_map[tgt] = a.shape[j]
                off += r.rows
            padded += mat.size
            stacked.append(mat)
        return stacked, bucket, real, padded

    @staticmethod
    def _slice_back(outs, reqs, bucket) -> bool:
        """Hand each request its row slice (and un-pad trailing dims it
        contributed padding to). False when the outputs are not rowwise —
        the caller must fall back to per-request execution."""
        if not all(o.ndim >= 1 and o.shape[0] == bucket for o in outs):
            return False
        off = 0
        for r in reqs:
            res = []
            for o in outs:
                s = o[off:off + r.rows]
                if r.pad_map:
                    sl, changed = [slice(None)] * s.ndim, False
                    for j in range(1, s.ndim):
                        orig = r.pad_map.get(s.shape[j])
                        if orig is not None and orig != s.shape[j]:
                            sl[j] = slice(0, orig)
                            changed = True
                    if changed:
                        s = s[tuple(sl)]
                res.append(s)            # views; the wire path copies
            r.future.set_result(res)
            off += r.rows
        return True

    def _worker_loop(self, pred, wq: Queue):
        while True:
            item = wq.get()
            if item is None:
                return
            self._execute(pred, *item)

    def _execute(self, pred, reqs, key, rows):
        from .. import profiler

        qdepth = len(self._q)
        if not reqs[0].solo:
            try:
                stacked, bucket, real, padded = self._assemble(reqs, key)
                outs = pred.run_batch(stacked)
                if self._slice_back(outs, reqs, bucket):
                    now = time.perf_counter()
                    profiler.record_serve_batch(rows, bucket, real, padded,
                                                qdepth)
                    profiler.record_serve_requests(
                        [now - r.t_enq for r in reqs])
                    return
                # outputs are not rowwise (batch-reducing model): stop
                # merging requests from here on — correctness first
                self._rowwise_ok = False
                if not self._warned_rowwise:
                    self._warned_rowwise = True
                    import warnings
                    warnings.warn(
                        "DynamicBatcher: model outputs are not rowwise "
                        "(leading dim != dispatched batch); falling back "
                        "to per-request execution", RuntimeWarning)
            except Exception:
                pass               # isolate below, request by request
        # per-request fallback: a poison request fails only itself
        for r in reqs:
            if r.future.done():
                continue
            try:
                if r.solo or not self._rowwise_ok:
                    outs = pred.run_batch(r.arrays)
                    r.future.set_result([np.asarray(o) for o in outs])
                else:
                    r.pad_map.clear()
                    stacked, bucket, real, padded = self._assemble(
                        [r], r.key)
                    outs = pred.run_batch(stacked)
                    if not self._slice_back(outs, [r], bucket):
                        outs = pred.run_batch(r.arrays)
                        r.future.set_result([np.asarray(o) for o in outs])
                    profiler.record_serve_batch(r.rows, bucket, real,
                                                padded, qdepth)
                profiler.record_serve_request(
                    time.perf_counter() - r.t_enq)
            except Exception as e:
                profiler.record_serve_error()
                r.future.set_exception(e)

    # -- warmup ----------------------------------------------------------

    def warmup_signatures(self) -> List[list]:
        """The bounded signature set steady-state traffic maps onto: the
        cross product of batch-ladder rungs and ladder rungs per distinct
        trailing dynamic symbol (shared symbols vary together), capped at
        _WARMUP_SIG_CAP signatures."""
        if not self._can_batch:
            return []
        batch_rungs = [b for b in self._ladder if b <= self._max_batch] \
            or [self._max_batch]
        syms: List[str] = []
        for i, (shape, _) in enumerate(self._specs):
            for j in self._dyn_axes[i]:
                s = shape[j]
                if s not in syms:
                    syms.append(s)
        sigs = []
        for combo in product(batch_rungs, *[self._ladder for _ in syms]):
            assign = dict(zip(syms, combo[1:]))
            sig = []
            for shape, dtype in self._specs:
                dims = [combo[0]]
                for j, d in enumerate(shape[1:], start=1):
                    dims.append(d if isinstance(d, int)
                                else assign.get(d, self._ladder[-1]))
                sig.append((tuple(dims), dtype))
            sigs.append(sig)
            if len(sigs) >= _WARMUP_SIG_CAP:
                break
        return sigs

    def warmup(self) -> int:
        """AOT-compile the whole bucket set on every pooled predictor;
        returns the number of compiles actually performed (0 when the
        persistent cache or a prior warmup already holds them all)."""
        from .. import profiler

        sigs = self.warmup_signatures()
        before = len(profiler.compile_events())
        for pred in self._preds:
            pred.warm(sigs)
        return len(profiler.compile_events()) - before

    # -- lifecycle -------------------------------------------------------

    @property
    def ladder(self) -> List[int]:
        return list(self._ladder)

    @property
    def queue_depth(self) -> int:
        return len(self._q)

    def stop(self):
        """Stop accepting work, drain the queue into errors, and join the
        dispatcher + workers."""
        with self._cond:
            self._stop = True
            pending = list(self._q)
            self._q.clear()
            self._cond.notify_all()
        for r in pending:
            r.future.set_exception(RuntimeError("DynamicBatcher stopped"))
        self._dispatcher.join(timeout=5)
        for wq in self._wqueues:
            wq.put(None)
        for t in self._workers:
            t.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
