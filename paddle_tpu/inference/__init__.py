"""paddle.inference — the deployment predictor surface (reference L7:
AnalysisPredictor analysis_predictor.cc:145 Init, :354 Run, config
analysis_config.cc).

TPU-native: the "analysis + pass pipeline + NaiveExecutor" stack collapses
to (deserialize StableHLO, bind params, jit.call) — XLA is the optimizer
pass pipeline. The Config/Predictor API keeps the reference's shape so
serving code ports over; the engine is paddle_tpu.jit.load.
"""
from __future__ import annotations

import numpy as np

from .. import jit as jit_mod

__all__ = ["Config", "Predictor", "create_predictor",
           "PredictorPool", "get_version", "get_num_bytes_of_data_type"]


class Config:
    """AnalysisConfig parity: points at the saved program + params.
    Accepts either the artifact prefix (Config(prefix)) or the two file
    paths (Config(model_file, params_file))."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            self._prefix = prog_file[:-len(".pdmodel")]
        else:
            self._prefix = prog_file
        self._params_file = params_file
        self._enable_memory_optim = True

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or (self._prefix or "") + ".pdiparams"

    # parity toggles — XLA owns these decisions on TPU
    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def switch_ir_optim(self, flag=True):
        pass

    def enable_use_gpu(self, *a, **k):  # pragma: no cover - parity no-op
        pass

    def disable_glog_info(self):  # pragma: no cover - parity no-op
        pass


class _InputHandle:
    def __init__(self, predictor, idx):
        self._p = predictor
        self._idx = idx

    def copy_from_cpu(self, array):
        self._p._inputs[self._idx] = np.asarray(array)

    def reshape(self, shape):  # data arrives via copy_from_cpu; no-op
        pass


class _OutputHandle:
    def __init__(self, predictor, idx):
        self._p = predictor
        self._idx = idx

    def copy_to_cpu(self):
        return np.asarray(self._p._outputs[self._idx])


class Predictor:
    """AnalysisPredictor::Run parity: copy inputs -> run program -> fetch."""

    def __init__(self, config: Config):
        self._layer = jit_mod.load(config.prog_file(),
                                   params_path=config.params_file())
        n_in = len(self._layer.in_avals) - len(self._layer._params)
        self._n_inputs = max(n_in, 1)
        self._inputs = [None] * self._n_inputs
        self._outputs = []

    def get_input_names(self):
        return [f"x{i}" for i in range(self._n_inputs)]

    def get_input_handle(self, name):
        names = self.get_input_names()
        if name not in names:
            raise KeyError(f"unknown input {name!r}; exported inputs are "
                           f"positional: {names}")
        return _InputHandle(self, names.index(name))

    def run(self, inputs=None):
        if inputs is not None:
            self._inputs = [np.asarray(a) for a in inputs]
        if any(a is None for a in self._inputs):
            raise ValueError("inputs not set; use copy_from_cpu or run([..])")
        out = self._layer(*self._inputs)
        leaves = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = [np.asarray(t._data if hasattr(t, "_data") else t)
                         for t in leaves]
        return self._outputs

    def get_output_names(self):
        return [f"out{i}" for i in range(max(len(self._outputs), 1))]

    def get_output_handle(self, name):
        names = self.get_output_names()
        if name not in names:
            raise KeyError(f"unknown output {name!r}; exported outputs are "
                           f"positional: {names}")
        return _OutputHandle(self, names.index(name))


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


import enum as _enum


class DataType(_enum.Enum):
    """reference paddle/inference DataType (paddle_infer enums)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType(_enum.Enum):
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    TPU = 3


class PrecisionType(_enum.Enum):
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


from ..core.tensor import Tensor  # noqa: F401,E402  (handle type parity)


def get_version() -> str:
    """Inference-library version string (reference paddle_infer
    get_version — the AnalysisPredictor build tag); here the framework
    version."""
    from .. import __version__
    return __version__


def get_num_bytes_of_data_type(dtype) -> int:
    """Byte width of a paddle_infer DataType (reference
    get_num_bytes_of_data_type)."""
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2}
    if dtype not in sizes:
        raise ValueError(f"unknown inference DataType: {dtype!r}")
    return sizes[dtype]


class PredictorPool:
    """A pool of Predictors over one Config (reference PredictorPool:
    thread-per-predictor serving). Each retrieve(i) slot holds its own
    Predictor instance — independent input/output bindings — while the
    deserialized program weights are shared through jit.load's arrays."""

    def __init__(self, config: Config, size: int = 1):
        if size < 1:
            raise ValueError("PredictorPool size must be >= 1")
        self._preds = [Predictor(config) for _ in range(int(size))]

    def retrieve(self, idx: int) -> Predictor:
        if not 0 <= idx < len(self._preds):
            raise IndexError(
                f"PredictorPool.retrieve: idx {idx} out of range "
                f"[0, {len(self._preds)}) — the reference pool rejects "
                "out-of-range handles the same way")
        return self._preds[idx]

    def __len__(self):
        return len(self._preds)
