"""paddle.inference — the deployment predictor surface (reference L7:
AnalysisPredictor analysis_predictor.cc:145 Init, :354 Run, config
analysis_config.cc).

TPU-native: the "analysis + pass pipeline + NaiveExecutor" stack collapses
to (deserialize StableHLO, bind params, jit.call) — XLA is the optimizer
pass pipeline. The Config/Predictor API keeps the reference's shape so
serving code ports over; the engine is paddle_tpu.jit.load.
"""
from __future__ import annotations

import numpy as np

from .. import jit as jit_mod

__all__ = ["Config", "Predictor", "create_predictor",
           "PredictorPool", "get_version", "get_num_bytes_of_data_type"]


class Config:
    """AnalysisConfig parity: points at the saved program + params.
    Accepts either the artifact prefix (Config(prefix)) or the two file
    paths (Config(model_file, params_file))."""

    def __init__(self, prog_file=None, params_file=None):
        if prog_file is not None and prog_file.endswith(".pdmodel"):
            self._prefix = prog_file[:-len(".pdmodel")]
        else:
            self._prefix = prog_file
        self._params_file = params_file
        self._enable_memory_optim = True

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_file or (self._prefix or "") + ".pdiparams"

    # parity toggles — XLA owns these decisions on TPU
    def enable_memory_optim(self, flag=True):
        self._enable_memory_optim = flag

    def switch_ir_optim(self, flag=True):
        pass

    def enable_use_gpu(self, *a, **k):  # pragma: no cover - parity no-op
        pass

    def disable_glog_info(self):  # pragma: no cover - parity no-op
        pass


class _InputHandle:
    def __init__(self, predictor, idx):
        self._p = predictor
        self._idx = idx

    def copy_from_cpu(self, array):
        self._p._inputs[self._idx] = np.asarray(array)

    def reshape(self, shape):  # data arrives via copy_from_cpu; no-op
        pass


class _OutputHandle:
    def __init__(self, predictor, idx):
        self._p = predictor
        self._idx = idx

    def copy_to_cpu(self):
        return np.asarray(self._p._outputs[self._idx])


class Predictor:
    """AnalysisPredictor::Run parity: copy inputs -> run program -> fetch.

    ``device`` pins this predictor's parameters AND its AOT-compiled
    bucket executables to one chip — the unit the serving
    ``PredictorPool`` round-robins batches across. ``run_batch`` is the
    compile-bounded entry the serving engine uses: one executable per
    exact input-shape signature, cached in a ``jit.compile_cache.AotCache``
    so steady-state traffic over a warmed bucket ladder never compiles
    (``run`` keeps the jit dispatch path and re-specializes per novel
    shape)."""

    def __init__(self, config: Config, device=None):
        self._layer = jit_mod.load(config.prog_file(),
                                   params_path=config.params_file())
        self._device = device
        if device is not None:
            import jax
            self._layer._params = jax.device_put(self._layer._params,
                                                 device)
        n_in = len(self._layer.in_avals) - len(self._layer._params)
        self._n_inputs = max(n_in, 1)
        self._inputs = [None] * self._n_inputs
        self._outputs = []
        from ..jit.compile_cache import AotCache
        self._aot = AotCache(self._layer._call, label="serve")

    def get_input_names(self):
        return [f"x{i}" for i in range(self._n_inputs)]

    def get_input_handle(self, name):
        names = self.get_input_names()
        if name not in names:
            raise KeyError(f"unknown input {name!r}; exported inputs are "
                           f"positional: {names}")
        return _InputHandle(self, names.index(name))

    def run(self, inputs=None):
        if inputs is not None:
            self._inputs = [np.asarray(a) for a in inputs]
        if any(a is None for a in self._inputs):
            raise ValueError("inputs not set; use copy_from_cpu or run([..])")
        out = self._layer(*self._inputs)
        leaves = out if isinstance(out, (list, tuple)) else [out]
        self._outputs = [np.asarray(t._data if hasattr(t, "_data") else t)
                         for t in leaves]
        return self._outputs

    # -- compile-bounded serving path -----------------------------------

    def input_specs(self):
        """Per-input (shape, dtype) with symbolic dims as their symbol
        name string (``"batch"``, ``"seqlen"``, ...) — the batcher pads
        exactly those axes. Static dims are plain ints."""
        specs = []
        for a in self._layer.input_avals:
            shape = tuple(d if isinstance(d, int) else str(d)
                          for d in a.shape)
            specs.append((shape, np.dtype(a.dtype)))
        return specs

    def output_specs(self):
        """Per-output (shape, dtype) with symbolic dims as their symbol
        name string — the same scope as ``input_specs``, so an output
        axis named ``"seqlen"`` is exactly the input axis the batcher
        padded. Static dims are plain ints."""
        specs = []
        for a in self._layer.out_avals:
            shape = tuple(d if isinstance(d, int) else str(d)
                          for d in a.shape)
            specs.append((shape, np.dtype(a.dtype)))
        return specs

    @staticmethod
    def _sig_key(sig):
        return tuple((tuple(shape), str(np.dtype(dtype)))
                     for shape, dtype in sig)

    def _input_avals_for(self, sig):
        import jax
        avals = []
        for shape, dtype in sig:
            kw = {}
            if self._device is not None:
                try:
                    kw["sharding"] = jax.sharding.SingleDeviceSharding(
                        self._device)
                except Exception:
                    pass
            avals.append(jax.ShapeDtypeStruct(tuple(shape),
                                              np.dtype(dtype), **kw))
        return avals

    def warm(self, signatures):
        """AOT-compile one executable per signature, where a signature is
        ``[(shape, dtype), ...]`` over the positional inputs. Idempotent:
        already-cached signatures are dict hits and record no compile."""
        for sig in signatures:
            key = self._sig_key(sig)
            if self._aot.get(key) is None:
                self._aot.get_or_compile(self._layer._params,
                                         *self._input_avals_for(sig),
                                         key=key)

    def run_batch(self, inputs):
        """Run one already-formed batch through the per-bucket AOT cache.
        Inputs must hit an exact compiled signature or one compile is
        paid (and recorded) for the novel shape. Returns numpy leaves."""
        import jax
        arrays = [np.ascontiguousarray(a) for a in inputs]
        if self._device is not None:
            arrays = [jax.device_put(a, self._device) for a in arrays]
        key = tuple((tuple(a.shape), str(a.dtype)) for a in arrays)
        exe = self._aot.get_or_compile(self._layer._params, *arrays,
                                       key=key)
        out = exe(self._layer._params, *arrays)
        leaves = jax.tree_util.tree_leaves(out)
        return [np.asarray(t) for t in leaves]

    @property
    def aot_cache_size(self):
        return len(self._aot)

    def get_output_names(self):
        n = len(self._outputs)
        if not n:
            # before the first run the arity comes from the export's
            # out_avals, not a hardcoded 1 (a 3-output model must report
            # out0..out2 so get_output_handle works pre-run)
            try:
                n = len(self._layer.out_avals)
            except Exception:
                n = 1
        return [f"out{i}" for i in range(max(n, 1))]

    def get_output_handle(self, name):
        names = self.get_output_names()
        if name not in names:
            raise KeyError(f"unknown output {name!r}; exported outputs are "
                           f"positional: {names}")
        return _OutputHandle(self, names.index(name))


def create_predictor(config: Config) -> Predictor:
    return Predictor(config)


import enum as _enum


class DataType(_enum.Enum):
    """reference paddle/inference DataType (paddle_infer enums)."""
    FLOAT32 = 0
    INT64 = 1
    INT32 = 2
    UINT8 = 3
    INT8 = 4
    FLOAT16 = 5
    BFLOAT16 = 6


class PlaceType(_enum.Enum):
    UNK = -1
    CPU = 0
    GPU = 1
    XPU = 2
    TPU = 3


class PrecisionType(_enum.Enum):
    Float32 = 0
    Half = 1
    Int8 = 2
    Bfloat16 = 3


from ..core.tensor import Tensor  # noqa: F401,E402  (handle type parity)


def get_version() -> str:
    """Inference-library version string (reference paddle_infer
    get_version — the AnalysisPredictor build tag); here the framework
    version."""
    from .. import __version__
    return __version__


def get_num_bytes_of_data_type(dtype) -> int:
    """Byte width of a paddle_infer DataType (reference
    get_num_bytes_of_data_type)."""
    sizes = {DataType.FLOAT32: 4, DataType.INT64: 8, DataType.INT32: 4,
             DataType.UINT8: 1, DataType.INT8: 1, DataType.FLOAT16: 2,
             DataType.BFLOAT16: 2}
    if dtype not in sizes:
        raise ValueError(f"unknown inference DataType: {dtype!r}")
    return sizes[dtype]


class PredictorPool:
    """A pool of Predictors over one Config (reference PredictorPool:
    thread-per-predictor serving). Each retrieve(i) slot holds its own
    Predictor instance — independent input/output bindings — while the
    deserialized program weights are shared through jit.load's arrays.

    ``devices="auto"`` pins slot i to ``jax.devices()[i]`` when enough
    devices exist (each slot gets its own parameter copy + executables on
    its chip) — the multi-chip serving shape the DynamicBatcher
    round-robins formed batches across. An explicit device list pins
    slots positionally; ``None`` keeps the legacy unpinned pool."""

    def __init__(self, config: Config, size: int = 1, devices=None):
        if size < 1:
            raise ValueError("PredictorPool size must be >= 1")
        size = int(size)
        if devices == "auto":
            try:
                import jax
                devs = jax.devices()
                devices = devs[:size] if len(devs) >= size else None
            except Exception:
                devices = None
        if devices is not None and len(devices) < size:
            raise ValueError(f"PredictorPool: {size} slots but only "
                             f"{len(devices)} devices given")
        self._preds = [
            Predictor(config,
                      device=(devices[i] if devices is not None else None))
            for i in range(size)]

    @property
    def predictors(self):
        return list(self._preds)

    def retrieve(self, idx: int) -> Predictor:
        if not 0 <= idx < len(self._preds):
            raise IndexError(
                f"PredictorPool.retrieve: idx {idx} out of range "
                f"[0, {len(self._preds)}) — the reference pool rejects "
                "out-of-range handles the same way")
        return self._preds[idx]

    def __len__(self):
        return len(self._preds)
