"""Health-aware front router: one wire-protocol address over a serve fleet.

A single ``InferenceServer`` is one process on one host — restart it and
every client sees connection errors until it is back. The router is the
resilience layer on top: it speaks the same wire protocol as a backend
(``serve.py`` frames in, frames out), so C/Go clients point at the
router unchanged, and behind it N backend daemons come, go, drain and
crash without a client ever losing a request silently.

What the router does per request:

* **Health-weighted routing** — a poll thread hits each backend's admin
  plane (``/healthz`` for liveness + draining, ``/statusz`` for
  ``queue_depth`` / ``oldest_wait_s``) every ``poll_interval`` seconds;
  requests go to the routable backend with the lowest load score
  (router-side in-flight + reported queue depth + wedge penalty).
  Backends without an admin port degrade to a TCP dial probe.
* **Circuit breaking** — a :class:`~paddle_tpu.utils.retry.CircuitBreaker`
  per backend trips OPEN after consecutive wire failures, so a dead
  backend costs one connect timeout, not one per request; after
  ``reset_timeout`` one half-open probe request re-tests it.
* **Bounded failover** — inference requests are idempotent, so a wire
  failure (or a typed ``UNAVAILABLE`` frame from a dying backend) is
  retried on the next-best backend — but every failover spends from a
  shared :class:`~paddle_tpu.utils.retry.RetryBudget`, so fleet-wide
  outage cannot amplify into a retry storm: when the budget is empty
  the client gets a fast typed ``UNAVAILABLE`` frame instead.
* **Load shedding** — when every routable backend is past the
  ``shed_watermark`` queue depth (or the router's own per-backend
  in-flight cap), the request is refused immediately with a typed
  ``RESOURCE_EXHAUSTED`` frame. Deterministic model errors
  (``INVALID_ARGUMENT``, ``INTERNAL``, ``DEADLINE_EXCEEDED``) are
  relayed verbatim, never failed over.
* **Drain awareness** — a backend whose /healthz says "draining"
  (SIGTERM was delivered; it is finishing in-flight work) is routed
  around within one poll interval; the router itself drains the same
  way (``drain()`` / SIGTERM in ``main_router``).
* **Stream-aware decode proxy** — a PDI2 request whose context carries
  a ``decode`` field leaves the one-reply fast path: the router relays
  the backend's seq-numbered token frames while recording every emitted
  token, and a backend dying mid-stream is *resumed* on another backend
  as ``prompt + tokens_emitted_so_far`` — greedy decode is
  deterministic and sampled decode carries a per-stream seed, so the
  client sees one gapless, duplicate-free, token-identical stream
  (``_handle_stream``; chaos site ``router.stream_relay``). PDI2
  decode requests must carry the ``decode`` context field (the
  ``decode_request`` helper always does); a bare PDI2 decode frame
  would be mis-relayed as a one-reply exchange.
* **Dynamic membership** — ``watch_membership`` follows a
  ``distributed/store`` registry (TCPStore in production, FileStore in
  tests): backends publish TTL'd heartbeat keys at startup and a
  "left" record at drain, and the watcher calls ``add_backend`` /
  ``remove_backend`` live — fleet joins/leaves need no supervisor
  edits and no router restart.

``BackendSupervisor`` optionally owns the fleet: ``--fleet N`` spawns N
``serve.py`` daemons from the model prefix, restarts dead ones with
bounded backoff (sharing one ``PADDLE_TPU_COMPILE_CACHE`` directory so a
restarted backend warms from the persistent compile cache), and swaps
them into the routing table live.

Chaos site ``router.forward`` fires once per backend attempt, so tests
inject wire failures between router and backend deterministically
(see tests/test_serve_chaos.py and docs/fault_tolerance.md).

    python -m paddle_tpu.inference.serve /path/prefix --router --fleet 3 \
        --port 9000 --warmup

All ``paddle_tpu_router_*`` metric families land in the shared registry
and are served from the router's own admin plane (``--metrics-port``),
which also mounts ``/varz`` (windowed time-series history) and
``/alertz`` (SLO burn-rate verdicts). Observability feeds back into
routing: the poll thread reads each backend's ``/alertz`` and a backend
whose SLOs are firing is demoted in the load score before it ever goes
unhealthy. Requests carrying a PDI2 trace context (or sampled by
``PADDLE_TPU_TRACE_SAMPLE``) are forwarded with the context to
trace-capable backends and assembled into one JSONL line per request:
router stages (pick / forward / reply) plus the backend's relayed
queue_wait / pad / execute / unpad breakdown (docs/observability.md).
"""
from __future__ import annotations

import collections
import json
import os
import socket
import struct
import subprocess
import sys
import threading
import time
from http.client import HTTPConnection

import numpy as np

from ..core import flags as _flags
from ..observability import (FlightRecorder, SLOEngine, SpanRecorder,
                             TimeSeriesStore, next_request_id,
                             request_id_base, router_objectives)
from ..observability import tracez as _tracez
from ..testing import chaos
from ..utils.retry import CircuitBreaker, RetryBudget, backoff_delays
from .errors import (ERR_INVALID_ARGUMENT, ERR_RESOURCE_EXHAUSTED,
                     ERR_UNAVAILABLE, RETRYABLE_CODES, TypedServeError,
                     error_code)
from .serve import (read_reply_ctx, read_request, write_error,
                    write_tensors)

__all__ = ["Backend", "ServeRouter", "BackendSupervisor", "parse_backend",
           "main_router"]

_BREAKER_STATE_CODE = {CircuitBreaker.CLOSED: 0,
                       CircuitBreaker.HALF_OPEN: 1,
                       CircuitBreaker.OPEN: 2}


class _RerouteShed(Exception):
    """Internal: a backend answered RESOURCE_EXHAUSTED before any token
    was relayed — unwind the stream attempt and reroute to a sibling
    without counting a breaker failure (the backend answered; it is
    saturated, not broken)."""


class _HandoffFailed(Exception):
    """Internal: a prefill->decode KV handoff could not complete (typed
    refusal, malformed reply, wire failure). Never fatal — the stream
    degrades to a plain re-prefill on its decode worker, which is
    token-identical (docs/serving.md)."""


def _router_metrics():
    """Register (idempotently) and return the paddle_tpu_router_* metric
    families. Catalogued in docs/observability.md."""
    from ..observability import counter, gauge, histogram
    return {
        "requests": counter(
            "paddle_tpu_router_requests_total",
            "Requests answered by the router, by outcome (ok, "
            "relayed_error, shed, unavailable, malformed)", ("outcome",)),
        "failovers": counter(
            "paddle_tpu_router_failovers_total",
            "Requests retried on another backend after a wire failure "
            "or typed UNAVAILABLE frame"),
        "budget_denied": counter(
            "paddle_tpu_router_retry_budget_denied_total",
            "Failovers refused because the shared retry budget was "
            "empty (the anti-retry-storm valve)"),
        "shed": counter(
            "paddle_tpu_router_shed_total",
            "Requests refused with RESOURCE_EXHAUSTED because every "
            "routable backend was past the shed watermark"),
        "backend_up": gauge(
            "paddle_tpu_router_backend_up",
            "1 while the backend's last health poll was healthy",
            ("backend",)),
        "breaker_state": gauge(
            "paddle_tpu_router_breaker_state",
            "Per-backend circuit breaker state "
            "(0 closed, 1 half-open, 2 open)", ("backend",)),
        "backend_queue": gauge(
            "paddle_tpu_router_backend_queue_depth",
            "Backend queue depth from its last /statusz poll",
            ("backend",)),
        "inflight": gauge(
            "paddle_tpu_router_inflight",
            "Requests currently being routed (read off a client and "
            "not yet answered)"),
        "latency": histogram(
            "paddle_tpu_router_request_latency_seconds",
            "Router-side request latency (client read to reply write)",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0, 30.0), sample_cap=2048),
        "failover_latency": histogram(
            "paddle_tpu_router_failover_latency_seconds",
            "Extra latency a failed-over request paid: first backend "
            "failure to final reply",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0), sample_cap=2048),
        "backend_restarts": counter(
            "paddle_tpu_router_backend_restarts_total",
            "Dead fleet backends respawned by the supervisor"),
        "backend_requests": counter(
            "paddle_tpu_router_backend_requests_total",
            "Forward attempts per backend (failovers count once per "
            "backend tried)", ("backend",)),
        "poll_latency": histogram(
            "paddle_tpu_router_poll_latency_seconds",
            "Health-poll round-trip per backend (healthz + statusz + "
            "alertz, or the TCP dial fallback)",
            buckets=(0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                     0.1, 0.25, 0.5, 1.0, 2.5), sample_cap=1024),
        "poll_failures": counter(
            "paddle_tpu_router_poll_failures_total",
            "Health polls that failed outright (dial refused, admin "
            "unreachable, poll raised), per backend", ("backend",)),
        "stream_active": gauge(
            "paddle_tpu_router_stream_active",
            "Decode streams currently being relayed through the router"),
        "stream_failovers": counter(
            "paddle_tpu_router_stream_failovers_total",
            "Decode streams re-issued to another backend after a "
            "mid-stream wire failure or typed UNAVAILABLE frame"),
        "stream_resumed_tokens": counter(
            "paddle_tpu_router_stream_resumed_tokens_total",
            "Tokens already emitted that were carried into a resume "
            "re-issue (prompt + tokens so far) across stream failovers"),
        "stream_lost": counter(
            "paddle_tpu_router_stream_lost_total",
            "Decode streams the router could not complete or resume "
            "(client got a typed UNAVAILABLE instead of a done frame)"),
        "membership_backends": gauge(
            "paddle_tpu_router_membership_backends",
            "Live members in the membership registry at the last "
            "watcher poll"),
        "membership_events": counter(
            "paddle_tpu_router_membership_events_total",
            "Routing-table updates driven by the membership watcher, "
            "by event (join, leave)", ("event",)),
        "reroutes": counter(
            "paddle_tpu_router_reroutes_total",
            "Requests rerouted to a sibling after one backend answered "
            "RESOURCE_EXHAUSTED at its own admission watermark "
            "(one-shot, spends from the shared retry budget; shed is "
            "terminal only when every backend is saturated)"),
        "tenant_shed": counter(
            "paddle_tpu_router_tenant_shed_total",
            "Requests refused at the router because the tenant was at "
            "its PADDLE_TPU_ROUTER_TENANT_MAX_INFLIGHT cap; never "
            "counted against fleet availability", ("tenant",)),
        "tenant_inflight": gauge(
            "paddle_tpu_router_tenant_inflight",
            "Requests currently being routed, per tenant", ("tenant",)),
        "role_backends": gauge(
            "paddle_tpu_router_role_backends",
            "Routable backends by advertised serving-topology role "
            "(unified, prefill, decode; docs/serving.md)", ("role",)),
        "handoffs": counter(
            "paddle_tpu_router_handoffs_total",
            "Prefill->decode KV handoffs orchestrated for routed "
            "streams, by outcome: 'ok' landed the pages on the decode "
            "worker, 'fallback' degraded to a plain re-prefill there "
            "(compat refusal, wire failure, or chaos)", ("outcome",)),
        "handoff_latency": histogram(
            "paddle_tpu_router_handoff_seconds",
            "Wall time of one orchestrated KV handoff: prefill-worker "
            "export round-trip plus shipping the pages to the decode "
            "worker and its ack",
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0, 10.0), sample_cap=2048),
    }


class Backend:
    """One backend daemon in the routing table: its address, the last
    health-poll verdict, a circuit breaker, and router-side in-flight
    accounting. Health fields are written by the poll thread and read by
    the routing path; all mutation goes through ``update_health`` /
    ``begin``/``end`` under the backend's lock."""

    def __init__(self, host: str, port: int, admin_port: int = None,
                 breaker: CircuitBreaker = None):
        self.host = host
        self.port = int(port)
        self.admin_port = int(admin_port) if admin_port is not None \
            else None
        self.key = f"{host}:{self.port}"
        self.breaker = breaker or CircuitBreaker(failure_threshold=3,
                                                 reset_timeout=2.0)
        self._lock = threading.Lock()
        # optimistic until the first poll: a just-added backend must be
        # routable immediately (the poll loop demotes it within one tick)
        self.healthy = True
        self.health_reasons = []
        self.draining = False
        self.queue_depth = 0
        self.oldest_wait_s = 0.0
        self.last_poll_s = None
        self.polls_failed = 0
        self.inflight = 0
        # does the backend speak the PDI2 trace-context frames? learned
        # from /statusz ("trace_wire": true); False until proven, so a
        # mixed fleet of old and new backends interops (old backends
        # simply never see a trace context)
        self.trace_wire = False
        # the backend's own /alertz verdict ("ok" / "warning" /
        # "firing"); a burning backend is demoted in score() so traffic
        # shifts away BEFORE it goes fully unhealthy
        self.alert_state = "ok"
        # serving-topology role + KV-compat facts from the membership
        # meta (docs/serving.md): "unified" until advertised otherwise,
        # so a meta-less fleet keeps today's routing byte-identical
        self.role = "unified"
        self.page_tokens = None
        self.kv_dtype = None
        self.fingerprint = None

    def set_meta(self, meta: dict):
        """Apply a membership meta dict (role + KV-compat facts)."""
        meta = meta or {}
        with self._lock:
            role = str(meta.get("role") or "unified").lower()
            self.role = role if role in ("unified", "prefill",
                                         "decode") else "unified"
            self.page_tokens = meta.get("page_tokens")
            self.kv_dtype = meta.get("kv_dtype")
            self.fingerprint = meta.get("fingerprint")

    def kv_compat(self) -> dict:
        with self._lock:
            return {"page_tokens": self.page_tokens,
                    "kv_dtype": self.kv_dtype,
                    "fingerprint": self.fingerprint}

    # score() demotion per /alertz state: warning nudges traffic away,
    # firing is worth ~50 queued requests — routed around unless every
    # other backend is worse
    _ALERT_PENALTY = {"ok": 0.0, "warning": 5.0, "firing": 50.0}

    def update_health(self, healthy: bool, reasons=(), draining=False,
                      queue_depth: int = None, oldest_wait_s: float = None,
                      trace_wire: bool = None, alert_state: str = None):
        with self._lock:
            self.healthy = bool(healthy)
            self.health_reasons = list(reasons)
            self.draining = bool(draining)
            if queue_depth is not None:
                self.queue_depth = int(queue_depth)
            if oldest_wait_s is not None:
                self.oldest_wait_s = float(oldest_wait_s)
            if trace_wire is not None:
                self.trace_wire = bool(trace_wire)
            if alert_state in self._ALERT_PENALTY:
                self.alert_state = alert_state
            self.last_poll_s = time.monotonic()
            self.polls_failed = 0 if healthy else self.polls_failed + 1

    def begin(self):
        with self._lock:
            self.inflight += 1

    def end(self):
        with self._lock:
            self.inflight -= 1

    def score(self) -> float:
        """Load score for least-loaded routing: cheap requests go where
        the combined router-side in-flight + backend queue is smallest;
        a wedging queue (old oldest_wait_s) is penalized hard, and a
        backend whose own SLOs are burning is demoted (warning +5,
        firing +50) so the alert feeds back into routing."""
        with self._lock:
            return (self.inflight + self.queue_depth
                    + 10.0 * self.oldest_wait_s
                    + self._ALERT_PENALTY.get(self.alert_state, 0.0))

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "key": self.key,
                "admin_port": self.admin_port,
                "healthy": self.healthy,
                "reasons": list(self.health_reasons),
                "draining": self.draining,
                "queue_depth": self.queue_depth,
                "oldest_wait_s": round(self.oldest_wait_s, 3),
                "inflight": self.inflight,
                "breaker": self.breaker.state,
                "trace_wire": self.trace_wire,
                "alert_state": self.alert_state,
                "polls_failed": self.polls_failed,
                "role": self.role,
                "kv_compat": {"page_tokens": self.page_tokens,
                              "kv_dtype": self.kv_dtype,
                              "fingerprint": self.fingerprint},
            }


def parse_backend(spec: str) -> Backend:
    """``HOST:PORT`` or ``HOST:PORT:ADMIN_PORT`` -> :class:`Backend`."""
    parts = spec.rsplit(":", 2)
    try:
        if len(parts) == 3 and parts[0]:
            # HOST:PORT:ADMIN — but HOST:PORT alone also splits in two;
            # disambiguate by whether the first part parses as a port
            try:
                host, port, admin = parts[0], int(parts[1]), int(parts[2])
                return Backend(host, port, admin)
            except ValueError:
                pass
        host, port = spec.rsplit(":", 1)
        return Backend(host, int(port))
    except (ValueError, IndexError):
        raise ValueError(
            f"backend spec {spec!r}: want HOST:PORT[:ADMIN_PORT]")


class ServeRouter:
    """Wire-protocol front router over a set of :class:`Backend`\\ s.

    Accepts client connections exactly like ``InferenceServer`` (same
    framing, same keep-alive loop), but instead of running a model it
    picks a backend, relays the request, and relays the reply — with
    health-weighted selection, circuit-breaker failover, retry
    budgeting, load shedding and drain support (class docstring above,
    and docs/fault_tolerance.md for the full state machine).
    """

    def __init__(self, backends, port: int = 0, host: str = "127.0.0.1",
                 poll_interval: float = 0.5, shed_watermark: int = 64,
                 failover_retries: int = 2, forward_timeout: float = 130.0,
                 connect_timeout: float = 2.0, idle_timeout: float = None,
                 metrics_port: int = None, retry_budget: RetryBudget = None,
                 max_inflight_per_backend: int = 256,
                 stream_retries: int = None):
        self._backends = list(backends)
        self._block = threading.Lock()          # routing-table lock
        self._poll_interval = float(poll_interval)
        self._watermark = int(shed_watermark)
        self._failover_retries = max(int(failover_retries), 0)
        self._stream_retries = max(int(
            _flags.env_value("PADDLE_TPU_ROUTER_STREAM_RETRIES")
            if stream_retries is None else stream_retries), 0)
        self._forward_timeout = forward_timeout
        self._connect_timeout = float(connect_timeout)
        self._idle_timeout = float(idle_timeout) if idle_timeout else None
        self._budget = retry_budget or RetryBudget()
        self._max_inflight = max(int(max_inflight_per_backend), 1)
        # multi-tenant isolation: a per-tenant in-flight cap (0 = off)
        # and per-tenant retry budgets so one tenant's failure storm
        # cannot drain the shared budget or trip fleet-wide alerts
        self._tenant_max_inflight = max(int(_flags.env_value(
            "PADDLE_TPU_ROUTER_TENANT_MAX_INFLIGHT") or 0), 0)
        self._tenant_inflight = {}              # tenant -> in-flight
        self._tenant_budgets = {}               # tenant -> RetryBudget
        self._local = threading.local()         # per-thread conn cache
        # every thread's cache dict, so remove_backend can purge a dead
        # backend's sockets fleet-wide, not just the calling thread's
        self._conn_caches = {}                  # thread -> cache dict
        self._conn_caches_lock = threading.Lock()
        # dynamic membership (watch_membership): watcher + bookkeeping
        self._membership = None
        self._membership_thread = None
        self._membership_interval = None
        self._member_keys = set()
        self._rr = 0                            # tie-break rotation
        self._m = _router_metrics()
        self._inflight = 0
        self._inflight_lock = threading.Lock()
        self._t0 = time.monotonic()
        self._ring = _tracez.RING
        # router-side trace assembly: its own stage histogram family
        # (pick / forward / reply + the backend_* breakdown relayed over
        # the wire), same JSONL sink and sampling gate as the backends
        self._spans = SpanRecorder(
            component="router",
            metric="paddle_tpu_router_span_seconds",
            help="Router-side per-request span breakdown by stage "
                 "(pick, forward, reply, plus relayed backend_* "
                 "stages), seconds.")
        # stall watchdog: busy while a client request is in flight; the
        # forward loop beats after every answered request
        self._recorder = FlightRecorder(
            "serve_router",
            busy_fn=lambda: self.inflight_requests > 0,
            context_fn=self._stall_context)

        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(128)
        self.port = self._srv.getsockname()[1]
        self.host = host
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True,
                                               name="router-accept")
        self._accept_thread.start()
        self._poll_thread = threading.Thread(target=self._poll_loop,
                                             daemon=True,
                                             name="router-health-poll")
        self._poll_thread.start()

        self._admin = None
        self.metrics_port = None
        self._varz = None
        self._slo = None
        if metrics_port is not None and int(metrics_port) >= 0:
            from ..observability import (AdminServer,
                                         install_default_collectors)
            install_default_collectors()
            self._varz = TimeSeriesStore()
            self._varz.start()
            self._slo = SLOEngine(self._varz, router_objectives())
            self._admin = AdminServer(port=int(metrics_port), host=host,
                                      health_fn=self._health,
                                      status_fn=self._status,
                                      varz_fn=self._varz.varz,
                                      alertz_fn=self._slo.alertz,
                                      tracez_fn=self._fleet_tracez,
                                      memz_fn=self._fleet_memz)
            self.metrics_port = self._admin.port

    # -- routing table ---------------------------------------------------

    def backends(self):
        with self._block:
            return list(self._backends)

    def add_backend(self, backend: Backend) -> Backend:
        with self._block:
            self._backends.append(backend)
        return backend

    def remove_backend(self, key: str):
        with self._block:
            self._backends = [b for b in self._backends if b.key != key]
        # purge the removed backend's cached keep-alive sockets in EVERY
        # thread, not just this one — a backend re-added on the same
        # host:port must never inherit a half-dead socket from a thread
        # that had no request in between. dict.pop is atomic under the
        # GIL; the owning thread sees a miss and dials fresh, and a
        # socket closed mid-request surfaces as a wire failure the
        # failover loop already handles.
        dead = []
        with self._conn_caches_lock:
            for t in [t for t in self._conn_caches if not t.is_alive()]:
                dead.extend(self._conn_caches.pop(t).values())
            caches = list(self._conn_caches.values())
        for cache in caches:
            dead.append(cache.pop(key, None))
        for s in dead:
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass
        # drop the dead backend's per-backend samples so /metrics does
        # not advertise an address that no longer exists
        for fam in ("backend_up", "breaker_state", "backend_queue",
                    "poll_failures", "backend_requests"):
            self._m[fam].remove(backend=key)

    # -- dynamic membership ----------------------------------------------

    def watch_membership(self, store, group: str = "serve", ttl=None,
                         interval: float = None):
        """Follow a ``distributed/store`` membership registry: backends
        publishing into ``group`` (see ``membership.MembershipPublisher``)
        are added to the routing table on join and removed on clean
        leave or heartbeat expiry — no supervisor edits, no router
        restart. ``store`` is a :class:`Store` instance or an endpoint
        string (``HOST:PORT`` for TCPStore, else a FileStore path).
        Statically configured backends are never membership-removed."""
        from ..distributed.store.membership import MembershipWatcher
        from ..distributed.store.membership import connect as _store_connect
        if isinstance(store, str):
            store = _store_connect(store)
        ttl = float(_flags.env_value("PADDLE_TPU_MEMBERSHIP_TTL")
                    if ttl is None else ttl)
        self._membership = MembershipWatcher(store, group=group, ttl=ttl)
        self._membership_interval = float(interval or self._poll_interval)
        self._membership_thread = threading.Thread(
            target=self._membership_loop, daemon=True,
            name="router-membership")
        self._membership_thread.start()
        return self._membership

    def _membership_loop(self):
        while not self._stop.is_set():
            try:
                live = self._membership.poll()
            except Exception:
                live = None      # store unreachable: keep current table
            if live is not None:
                current = {b.key for b in self.backends()}
                for key, rec in live.items():
                    if key in current:
                        continue
                    host, port = key.rsplit(":", 1)
                    b = Backend(host, int(port), rec.get("admin_port"))
                    if rec.get("meta"):
                        # role + KV-compat facts ride the slot record
                        # (docs/serving.md): a prefill worker is pulled
                        # out of general rotation the moment it joins
                        b.set_meta(rec["meta"])
                    self.add_backend(b)
                    self._member_keys.add(key)
                    self._m["membership_events"].labels(event="join").inc()
                for key in list(self._member_keys):
                    if key not in live:
                        self.remove_backend(key)
                        self._member_keys.discard(key)
                        self._m["membership_events"].labels(
                            event="leave").inc()
                self._m["membership_backends"].set(len(live))
            self._stop.wait(self._membership_interval)

    # -- health polling --------------------------------------------------

    def _poll_loop(self):
        while not self._stop.is_set():
            for b in self.backends():
                t0 = time.perf_counter()
                try:
                    self._poll_backend(b)
                except Exception as e:   # a poll bug must not kill polls
                    b.update_health(False, [f"poll raised: {e!r}"])
                    self._m["poll_failures"].labels(backend=b.key).inc()
                self._m["poll_latency"].observe(time.perf_counter() - t0)
                self._m["backend_up"].labels(backend=b.key).set(
                    1 if b.healthy else 0)
                self._m["breaker_state"].labels(backend=b.key).set(
                    _BREAKER_STATE_CODE[b.breaker.state])
                self._m["backend_queue"].labels(backend=b.key).set(
                    b.queue_depth)
            counts = {"unified": 0, "prefill": 0, "decode": 0}
            for b in self.backends():
                counts[b.role] = counts.get(b.role, 0) + 1
            for role, n in counts.items():
                self._m["role_backends"].labels(role=role).set(n)
            self._stop.wait(self._poll_interval)

    def _poll_backend(self, b: Backend):
        if b.admin_port is None:
            # no admin plane: degrade to a TCP liveness dial
            try:
                socket.create_connection(
                    (b.host, b.port),
                    timeout=max(self._poll_interval, 0.5)).close()
                b.update_health(True)
            except OSError as e:
                b.update_health(False, [f"dial failed: {e}"])
                self._m["poll_failures"].labels(backend=b.key).inc()
            return
        conn = HTTPConnection(b.host, b.admin_port,
                              timeout=max(self._poll_interval, 0.5))
        try:
            conn.request("GET", "/healthz")
            r = conn.getresponse()
            hbody = json.loads(r.read().decode("utf-8", "replace") or "{}")
            healthy = r.status == 200
            reasons = hbody.get("reasons", [])
            draining = any("draining" in str(x) for x in reasons)
            queue_depth = oldest = None
            conn.request("GET", "/statusz")
            s = conn.getresponse()
            sbody = json.loads(s.read().decode("utf-8", "replace") or "{}")
            draining = bool(sbody.get("draining", draining))
            trace_wire = bool(sbody.get("trace_wire", False))
            batcher = sbody.get("batcher") or {}
            if "queue_depth" in batcher:
                queue_depth = batcher["queue_depth"]
            if "oldest_wait_s" in batcher:
                oldest = batcher["oldest_wait_s"]
            # the backend's own SLO verdict closes the loop into
            # routing: /alertz 404s on an old backend -> stays "ok"
            alert_state = None
            try:
                conn.request("GET", "/alertz")
                a = conn.getresponse()
                abody = json.loads(
                    a.read().decode("utf-8", "replace") or "{}")
                if a.status == 200:
                    alert_state = abody.get("state")
            except (OSError, ValueError):
                pass
            b.update_health(healthy, reasons, draining=draining,
                            queue_depth=queue_depth, oldest_wait_s=oldest,
                            trace_wire=trace_wire,
                            alert_state=alert_state)
        except (OSError, ValueError) as e:
            b.update_health(False, [f"admin poll failed: {e}"])
            self._m["poll_failures"].labels(backend=b.key).inc()
        finally:
            conn.close()

    # -- backend selection -----------------------------------------------

    def _routable(self, exclude=()):
        """Backends eligible for new traffic: last poll healthy, not
        draining, breaker not OPEN (HALF_OPEN stays in — its allow()
        gate hands one probe through)."""
        out = []
        for b in self.backends():
            if b.key in exclude or b.draining or not b.healthy:
                continue
            if b.breaker.state == CircuitBreaker.OPEN:
                continue
            if b.role == "prefill":
                # prefill workers take KV-export traffic from the
                # handoff orchestrator, never direct client requests
                continue
            out.append(b)
        return out

    def _choose_prefill(self, exclude=()):
        """Least-loaded routable prefill worker for a KV export, or
        ``None`` when the fleet has no usable prefill pool (the stream
        then just prefills on its decode worker — today's path). Compat
        is deliberately NOT pre-filtered here: the decode worker is the
        authority (typed FAILED_PRECONDITION refusal, docs/serving.md),
        so a misconfigured pairing is caught loudly on the wire instead
        of silently shadowed by the router."""
        cands = []
        for b in self.backends():
            if b.key in exclude or b.draining or not b.healthy:
                continue
            if b.role != "prefill":
                continue
            if b.breaker.state == CircuitBreaker.OPEN:
                continue
            cands.append(b)
        cands.sort(key=lambda b: b.score())
        for b in cands:
            if b.breaker.allow():
                return b
        return None

    def _choose(self, exclude=()):
        """Least-loaded routable backend, or ``None`` when nothing is
        routable. Raises RESOURCE_EXHAUSTED when backends ARE routable
        but every one is past the shed watermark / in-flight cap —
        queueing behind an overloaded fleet only converts overload into
        timeouts, so the router refuses fast instead."""
        cands = self._routable(exclude)
        if not cands:
            return None
        open_for_traffic = []
        for b in cands:
            if self._watermark > 0 and b.queue_depth >= self._watermark:
                continue
            if b.inflight >= self._max_inflight:
                continue
            open_for_traffic.append(b)
        if not open_for_traffic:
            self._m["shed"].inc()
            raise TypedServeError(
                ERR_RESOURCE_EXHAUSTED,
                f"all {len(cands)} routable backends past the shed "
                f"watermark (queue >= {self._watermark}); back off and "
                f"retry later")
        scored = [(b.score(), b) for b in open_for_traffic]
        scored.sort(key=lambda p: p[0])
        # equal-score leaders rotate round-robin — a stable sort alone
        # would pile every idle-fleet request onto the first backend
        leaders = [b for s, b in scored if s <= scored[0][0]]
        self._rr += 1
        rot = self._rr % len(leaders)
        ordered = leaders[rot:] + leaders[:rot] \
            + [b for _, b in scored if b not in leaders]
        for b in ordered:
            if b.breaker.allow():    # claims the half-open probe slot
                return b
        return None

    # -- forwarding ------------------------------------------------------

    def _conn_cache(self) -> dict:
        cache = getattr(self._local, "conns", None)
        if cache is None:
            cache = self._local.conns = {}
            with self._conn_caches_lock:
                self._conn_caches[threading.current_thread()] = cache
        return cache

    def _backend_conn(self, b: Backend) -> socket.socket:
        cache = self._conn_cache()
        s = cache.get(b.key)
        if s is None:
            s = socket.create_connection((b.host, b.port),
                                         timeout=self._connect_timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(self._forward_timeout)
            cache[b.key] = s
        return s

    def _drop_conn(self, b: Backend):
        s = self._conn_cache().pop(b.key, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _forward(self, b: Backend, arrays, ctx=None):
        """One attempt against one backend: write the request, read the
        reply. Returns ``(outputs, None, reply_ctx)`` or ``(None,
        error_message, reply_ctx)``; ``reply_ctx`` is the backend's
        trace context (span breakdown) or ``None``. The context is only
        put on the wire when the backend advertised ``trace_wire`` in
        its /statusz, so an old backend never sees a PDI2 frame. A
        stale keep-alive socket (backend restarted between requests)
        gets exactly one fresh-socket retry; every other wire failure
        propagates to the failover loop."""
        send_ctx = ctx if (ctx is not None and b.trace_wire) else None
        reused = b.key in self._conn_cache()
        b.begin()
        self._m["backend_requests"].labels(backend=b.key).inc()
        try:
            try:
                s = self._backend_conn(b)
                write_tensors(s, arrays, ctx=send_ctx)
                return read_reply_ctx(s)
            except ConnectionError:
                self._drop_conn(b)
                if not reused:
                    raise
            except (TimeoutError, OSError, struct.error):
                self._drop_conn(b)
                raise
            s = self._backend_conn(b)
            try:
                write_tensors(s, arrays, ctx=send_ctx)
                return read_reply_ctx(s)
            except (ConnectionError, TimeoutError, OSError, struct.error):
                self._drop_conn(b)
                raise
        finally:
            b.end()

    # -- multi-tenant isolation -------------------------------------------

    @staticmethod
    def _tenant_of(cctx) -> str:
        """Tenant identity off the wire ctx: the decode ctx field for
        streams, the top-level field for one-shot requests."""
        if not isinstance(cctx, dict):
            return "default"
        d = cctx.get("decode")
        t = d.get("tenant") if isinstance(d, dict) else None
        t = t or cctx.get("tenant")
        return str(t).strip() if t else "default"

    def _budget_for(self, tenant) -> RetryBudget:
        """Non-default tenants spend failover retries from their own
        budget: a flood tenant burning retries cannot starve everyone
        else's failovers."""
        if tenant == "default":
            return self._budget
        b = self._tenant_budgets.get(tenant)
        if b is None:
            b = self._tenant_budgets.setdefault(tenant, RetryBudget())
        return b

    def _tenant_admit(self, tenant) -> bool:
        """Claim an in-flight slot for the tenant; False when it is at
        its PADDLE_TPU_ROUTER_TENANT_MAX_INFLIGHT cap (0 disables)."""
        if self._tenant_max_inflight <= 0:
            return True
        with self._inflight_lock:
            n = self._tenant_inflight.get(tenant, 0)
            if n >= self._tenant_max_inflight:
                return False
            self._tenant_inflight[tenant] = n + 1
        self._m["tenant_inflight"].labels(tenant=tenant).inc()
        return True

    def _tenant_release(self, tenant):
        if self._tenant_max_inflight <= 0:
            return
        with self._inflight_lock:
            n = self._tenant_inflight.get(tenant, 1) - 1
            if n <= 0:
                self._tenant_inflight.pop(tenant, None)
            else:
                self._tenant_inflight[tenant] = n
        self._m["tenant_inflight"].labels(tenant=tenant).dec()

    def _handle(self, arrays, ctx=None, info=None, tenant="default"):
        """Route one decoded request. Returns ``("ok", outputs)`` or
        ``(outcome, error_message)`` with outcome one of
        ``relayed_error`` / ``shed`` / ``unavailable``. ``ctx`` is the
        trace context forwarded to trace-capable backends; ``info``
        (when given) is filled in-place with the trace assembly:
        ``pick_s`` / ``forward_s`` accumulated across attempts,
        ``backend`` (the answering backend's key), ``backend_ctx`` (its
        reply trace context) and ``attempts``."""
        info = info if info is not None else {}
        info.update(pick_s=0.0, forward_s=0.0, backend=None,
                    backend_ctx=None, attempts=0)
        budget = self._budget_for(tenant)
        budget.record_request()
        tried = set()
        attempts = 0
        first_failure_t = None
        last_err = None
        rerouted = False         # one-shot RESOURCE_EXHAUSTED reroute
        pending_reroute = False  # next attempt is the reroute, not a failover
        last_shed = None         # the shed errmsg, relayed if terminal
        max_attempts = 1 + self._failover_retries
        while attempts < max_attempts:
            t_pick = time.perf_counter()
            try:
                b = self._choose(exclude=tried)
            except TypedServeError as e:     # shed: every backend busy
                now = time.perf_counter()
                info["pick_s"] += now - t_pick
                self._ring.complete("router.pick", t_pick, now,
                                    {"outcome": "shed"})
                return ("shed", last_shed or str(e))
            now = time.perf_counter()
            info["pick_s"] += now - t_pick
            self._ring.complete("router.pick", t_pick, now,
                                {"backend": b.key if b else None})
            if b is None:
                break
            if attempts > 0:
                if not budget.try_spend():
                    self._m["budget_denied"].inc()
                    if last_shed is not None:
                        # the reroute could not be funded: the shed is
                        # terminal — relay it so the client backs off
                        return ("shed", last_shed)
                    return ("unavailable",
                            f"{ERR_UNAVAILABLE}: retry budget exhausted "
                            f"after backend failure ({last_err}); "
                            f"failing fast instead of retry-storming")
                if pending_reroute:
                    pending_reroute = False
                    self._m["reroutes"].inc()
                else:
                    self._m["failovers"].inc()
            attempts += 1
            info["attempts"] = attempts
            tried.add(b.key)
            t_fwd = time.perf_counter()
            try:
                chaos.maybe_fail("router.forward", b.key)
                outputs, errmsg, rctx = self._forward(b, arrays, ctx=ctx)
            except (ConnectionError, TimeoutError, OSError,
                    struct.error, ValueError, IndexError) as e:
                # wire failure or unparseable reply: the backend is
                # misbehaving — count it against the breaker, fail over
                now = time.perf_counter()
                info["forward_s"] += now - t_fwd
                self._ring.complete("router.forward", t_fwd, now,
                                    {"backend": b.key, "error":
                                     type(e).__name__})
                b.breaker.record_failure()
                self._drop_conn(b)
                last_err = f"{b.key}: {type(e).__name__}: {e}"
                last_shed = None   # freshest failure is no longer a shed
                if first_failure_t is None:
                    first_failure_t = time.monotonic()
                continue
            now = time.perf_counter()
            info["forward_s"] += now - t_fwd
            self._ring.complete("router.forward", t_fwd, now,
                                {"backend": b.key})
            if errmsg is not None:
                code = error_code(errmsg)
                if code in RETRYABLE_CODES:
                    # the backend itself says UNAVAILABLE (dispatcher
                    # died, worker crashed): failover-safe
                    b.breaker.record_failure()
                    last_err = f"{b.key}: {errmsg}"
                    last_shed = None
                    if first_failure_t is None:
                        first_failure_t = time.monotonic()
                    continue
                if code == ERR_RESOURCE_EXHAUSTED and not rerouted:
                    # this backend shed at its own admission watermark;
                    # a sibling may have free slots — one-shot reroute
                    # to the least-loaded non-shedding backend (spends
                    # from the shared retry budget at the top of the
                    # loop). Shed stays terminal only when every
                    # backend is saturated.
                    b.breaker.record_success()   # it answered; healthy
                    rerouted = pending_reroute = True
                    last_shed = errmsg
                    last_err = f"{b.key}: {errmsg}"
                    max_attempts += 1   # don't eat a failover retry
                    continue
                if code == ERR_RESOURCE_EXHAUSTED:
                    # the reroute target shed too: the fleet really is
                    # saturated — terminal shed (counts against the shed
                    # outcome, not as a relayed model error)
                    b.breaker.record_success()
                    info["backend"], info["backend_ctx"] = b.key, rctx
                    return ("shed", errmsg)
                # deterministic / non-retryable error: relay verbatim —
                # the backend answered, so its breaker heals
                b.breaker.record_success()
                info["backend"], info["backend_ctx"] = b.key, rctx
                return ("relayed_error", errmsg)
            b.breaker.record_success()
            if first_failure_t is not None:
                self._m["failover_latency"].observe(
                    time.monotonic() - first_failure_t)
            info["backend"], info["backend_ctx"] = b.key, rctx
            return ("ok", outputs)
        if last_shed is not None:
            # the only failure seen was a backend shed and no sibling
            # could take the reroute: terminal shed, not UNAVAILABLE
            return ("shed", last_shed)
        detail = last_err or ("no routable backend (all unhealthy, "
                              "draining, or circuit-broken)")
        return ("unavailable",
                f"{ERR_UNAVAILABLE}: no backend answered after "
                f"{attempts} attempt(s): {detail}")

    # -- decode stream relay ---------------------------------------------

    def _stream_conn(self, b: Backend) -> socket.socket:
        """A dedicated socket for one stream attempt — never the shared
        keep-alive cache: a stream holds its connection for seconds, and
        a failed one is poisoned mid-frame by definition."""
        s = socket.create_connection((b.host, b.port),
                                     timeout=self._connect_timeout)
        s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        s.settimeout(self._forward_timeout)
        return s

    def _stream_ctx(self, rid, trace_id, stream_fields):
        return {"trace_id": trace_id, "request_id": rid,
                "stream": stream_fields}

    def _finish_stream(self, conn, rid, trace_id, emitted) -> bool:
        """Write the client's done frame from the router's own record
        (used both after a relayed done frame and when the backend died
        with nothing left to generate). False when the client is gone."""
        try:
            write_tensors(conn, [np.asarray(emitted, np.int32)],
                          ctx=self._stream_ctx(
                              rid, trace_id,
                              {"done": True, "n_tokens": len(emitted)}))
            return True
        except (ConnectionError, TimeoutError, OSError):
            return False

    def _export_kv_from(self, pre: Backend, prompt, rid, trace_id):
        """One kv_export round-trip to a prefill worker on a dedicated
        socket: prompt in, (page leaf arrays, export metadata) out."""
        pre.begin()
        self._m["backend_requests"].labels(backend=pre.key).inc()
        s = None
        try:
            s = self._stream_conn(pre)
            write_tensors(s, [np.asarray(prompt, np.int32)],
                          ctx={"trace_id": trace_id, "request_id": rid,
                               "kv_export": {}})
            arrays, errmsg, rctx = read_reply_ctx(s)
            if errmsg is not None:
                pre.breaker.record_success()   # it answered; not broken
                raise _HandoffFailed(f"{pre.key}: {errmsg}")
            meta = (rctx or {}).get("kv_export")
            if not isinstance(meta, dict):
                raise _HandoffFailed(
                    f"{pre.key}: kv_export reply carries no metadata")
            pre.breaker.record_success()
            return arrays, meta
        except (ConnectionError, TimeoutError, OSError, struct.error,
                ValueError, IndexError) as e:
            pre.breaker.record_failure()
            raise _HandoffFailed(
                f"{pre.key}: {type(e).__name__}: {e}") from e
        finally:
            pre.end()
            if s is not None:
                try:
                    s.close()
                except OSError:
                    pass

    def _maybe_handoff(self, b: Backend, s, prompt, rid,
                       trace_id) -> bool:
        """Orchestrate one prefill->decode KV handoff for a fresh stream
        routed to decode worker ``b`` (docs/serving.md "Disaggregated
        prefill/decode"): export the prompt's full KV pages from a
        prefill worker, ship them to ``b`` on the stream's own socket
        ``s``, and wait for the ack — the ordering that makes the landed
        pages visible to the stream request sent next on ``s``. Returns
        True when pages landed. ANY failure degrades to False — the
        stream simply prefills on ``b`` (token-identical, the same
        contract as a failed tier refetch); a failure that poisoned
        ``s`` mid-frame surfaces at the stream request write and rides
        the normal failover path."""
        pre = self._choose_prefill()
        if pre is None:
            return False
        t0 = time.monotonic()
        try:
            chaos.maybe_fail("handoff.send", detail=b.key)
            arrays, meta = self._export_kv_from(pre, prompt, rid,
                                                trace_id)
            write_tensors(s, arrays,
                          ctx={"trace_id": trace_id, "request_id": rid,
                               "kv_handoff": meta})
            _, errmsg, _ = read_reply_ctx(s)
            if errmsg is not None:
                # typed refusal (compat / checksum / exhausted): the
                # frame was fully consumed, the socket stays clean
                raise _HandoffFailed(f"{b.key}: {errmsg}")
        except (_HandoffFailed, ConnectionError, TimeoutError, OSError,
                struct.error, ValueError, IndexError) as e:
            self._m["handoffs"].labels(outcome="fallback").inc()
            _tracez.RING.instant("router.handoff_fallback",
                                 {"backend": b.key, "err": str(e)[:200]})
            return False
        self._m["handoffs"].labels(outcome="ok").inc()
        self._m["handoff_latency"].observe(time.monotonic() - t0)
        return True

    def _handle_stream(self, conn, arrays, cctx, rid, trace_id):
        """Proxy one decode stream with mid-stream failover.

        The state machine (docs/fault_tolerance.md "Streaming
        failover"): relay the backend's seq-numbered token frames to the
        client while recording every emitted token; on a wire failure or
        typed ``UNAVAILABLE``, re-issue the request to another routable
        backend as ``prompt + tokens_emitted_so_far`` (a resume is just
        a longer prefill; greedy decode is argmax-deterministic and
        sampled decode carries a per-stream seed, so the continuation is
        token-identical). Backend seq restarts at 0 per attempt, so
        client seq = tokens-already-relayed + backend seq; frames that
        would rewind it are dropped — the client sees one gapless,
        duplicate-free stream. Each failover spends from the shared
        retry budget and from the per-stream ``stream_retries`` cap.
        The half-open breaker probe resolves at the FIRST relayed frame
        (stream established), not stream completion, so a minutes-long
        stream cannot pin a breaker in HALF_OPEN.

        Returns ``(outcome, conn_alive)``.
        """
        opts = dict(cctx.get("decode") or {})
        prompt = [int(t) for t in np.asarray(arrays[0]).reshape(-1)]
        max_new = opts.get("max_new_tokens")
        max_new = None if max_new is None else int(max_new)
        temperature = float(opts.get("temperature") or 0.0)
        if temperature > 0.0 and opts.get("seed") is None:
            # sampled decode only resumes token-identically with a
            # per-stream seed; mint one so every attempt samples the
            # same continuation
            opts["seed"] = int.from_bytes(os.urandom(4), "little")
        budget = self._budget_for(self._tenant_of(cctx))
        budget.record_request()
        emitted = []             # tokens relayed to the client, in order
        eos_seen = False
        tried = set()
        attempts = 0
        first_failure_t = None
        last_err = None
        rerouted = False         # one-shot RESOURCE_EXHAUSTED reroute
        last_shed = None         # the shed errmsg, relayed if terminal
        max_attempts = 1 + self._stream_retries
        while attempts < max_attempts:
            if emitted and (eos_seen or
                            (max_new is not None
                             and len(emitted) >= max_new)):
                # the backend died between its last token and the done
                # frame: nothing is left to generate — synthesize the
                # done frame from the router's record
                return (("ok", True)
                        if self._finish_stream(conn, rid, trace_id,
                                               emitted)
                        else ("ok", False))
            try:
                b = self._choose(exclude=tried)
            except TypedServeError as e:         # shed: every backend busy
                if not emitted:
                    try:
                        write_error(conn, last_shed or str(e),
                                    ctx=self._stream_ctx(
                            rid, trace_id, {"done": True, "error": True,
                                            "seq": 0}))
                    except OSError:
                        return ("shed", False)
                    return ("shed", True)
                # mid-stream shed is a lost stream, same as no backend
                break
            if b is None:
                break
            if attempts > 0:
                if not budget.try_spend():
                    self._m["budget_denied"].inc()
                    last_err = (f"retry budget exhausted after "
                                f"{last_err}")
                    break
                self._m["stream_failovers"].inc()
                if emitted:
                    self._m["stream_resumed_tokens"].inc(len(emitted))
            attempts += 1
            tried.add(b.key)
            seq_base = len(emitted)
            send_opts = dict(opts)
            if max_new is not None:
                send_opts["max_new_tokens"] = max_new - seq_base
            # the resume form: every emitted token becomes prompt (the
            # paged prefix cache makes the re-prefill cheap)
            req_toks = np.asarray(prompt + emitted, np.int32)
            send_ctx = {"trace_id": trace_id, "request_id": rid,
                        "decode": send_opts}
            b.begin()
            self._m["backend_requests"].labels(backend=b.key).inc()
            s = None
            established = False
            try:
                chaos.maybe_fail("router.stream_relay", b.key)
                s = self._stream_conn(b)
                if not emitted and b.role == "decode":
                    # disaggregated topology: land the prompt's KV
                    # pages from a prefill worker before the stream
                    # request, so admission sees a prefix-cache hit;
                    # failure degrades to a plain prefill on b
                    self._maybe_handoff(b, s, prompt, rid, trace_id)
                write_tensors(s, [req_toks], ctx=send_ctx)
                while True:
                    outputs, errmsg, rctx = read_reply_ctx(s)
                    stream = (rctx or {}).get("stream") or {}
                    if errmsg is not None:
                        code = error_code(errmsg)
                        if code in RETRYABLE_CODES:
                            raise TypedServeError(code, errmsg)
                        if (code == ERR_RESOURCE_EXHAUSTED
                                and not rerouted and not emitted):
                            # shed at decode admission before any token:
                            # one-shot reroute to a sibling with free
                            # slots (terminal only when all saturated)
                            rerouted = True
                            last_shed = errmsg
                            max_attempts += 1
                            raise _RerouteShed(errmsg)
                        # deterministic error: relay verbatim; the
                        # backend answered, so its breaker heals
                        b.breaker.record_success()
                        try:
                            write_error(conn, errmsg,
                                        ctx=self._stream_ctx(
                                            rid, trace_id,
                                            {"done": True, "error": True,
                                             "seq": len(emitted)}))
                        except OSError:
                            return ("relayed_error", False)
                        return ("relayed_error", True)
                    if not established:
                        # stream established: the half-open probe (and a
                        # failover's recovery clock) resolves NOW, not
                        # at stream completion
                        established = True
                        b.breaker.record_success()
                        if first_failure_t is not None:
                            self._m["failover_latency"].observe(
                                time.monotonic() - first_failure_t)
                            first_failure_t = None
                    if stream.get("done"):
                        # reconcile: the done payload is this attempt's
                        # authoritative token list — relay any trailing
                        # tokens the per-token frames missed
                        done_toks = ([int(t) for t in
                                      np.asarray(outputs[0]).reshape(-1)]
                                     if outputs else [])
                        full = emitted[:seq_base] + done_toks
                        for i in range(len(emitted), len(full)):
                            try:
                                write_tensors(
                                    conn,
                                    [np.asarray([full[i]], np.int32)],
                                    ctx=self._stream_ctx(
                                        rid, trace_id,
                                        {"seq": i, "eos": False,
                                         "done": False}))
                            except (ConnectionError, TimeoutError,
                                    OSError):
                                return ("client_gone", False)
                        emitted = full
                        return (("ok", True)
                                if self._finish_stream(conn, rid,
                                                       trace_id, emitted)
                                else ("ok", False))
                    gseq = seq_base + int(stream.get("seq", 0))
                    if gseq < len(emitted):
                        continue     # duplicate of an already-relayed seq
                    tok = int(np.asarray(outputs[0]).reshape(-1)[0])
                    emitted.append(tok)
                    eos_seen = bool(stream.get("eos")) or eos_seen
                    try:
                        write_tensors(
                            conn, [np.asarray([tok], np.int32)],
                            ctx=self._stream_ctx(
                                rid, trace_id,
                                {"seq": gseq,
                                 "eos": bool(stream.get("eos")),
                                 "done": False}))
                    except (ConnectionError, TimeoutError, OSError):
                        return ("client_gone", False)
            except _RerouteShed as e:
                # the backend answered (saturated, not broken): heal its
                # breaker and reroute without a failure mark
                b.breaker.record_success()
                self._m["reroutes"].inc()
                last_err = f"{b.key}: {e}"
                continue
            except (TypedServeError, ConnectionError, TimeoutError,
                    OSError, struct.error, ValueError, IndexError) as e:
                # mid-stream backend failure: count it, resume elsewhere
                b.breaker.record_failure()
                last_err = f"{b.key}: {type(e).__name__}: {e}"
                last_shed = None   # freshest failure is no longer a shed
                if first_failure_t is None:
                    first_failure_t = time.monotonic()
                continue
            finally:
                b.end()
                if s is not None:
                    try:
                        s.close()
                    except OSError:
                        pass
        if last_shed is not None and not emitted:
            # the only failure seen was an admission shed and no sibling
            # could take the reroute: relay it terminally — the client
            # backs off instead of treating the fleet as down
            try:
                write_error(conn, last_shed, ctx=self._stream_ctx(
                    rid, trace_id, {"done": True, "error": True,
                                    "seq": 0}))
            except OSError:
                return ("shed", False)
            return ("shed", True)
        # out of backends or budget: the stream is lost
        self._m["stream_lost"].inc()
        detail = last_err or ("no routable backend (all unhealthy, "
                              "draining, or circuit-broken)")
        msg = (f"{ERR_UNAVAILABLE}: decode stream lost after "
               f"{attempts} attempt(s), {len(emitted)} token(s) "
               f"relayed: {detail}")
        try:
            write_error(conn, msg, ctx=self._stream_ctx(
                rid, trace_id, {"done": True, "error": True,
                                "seq": len(emitted)}))
        except OSError:
            return ("unavailable", False)
        return ("unavailable", True)

    def _serve_stream(self, conn, arrays, cctx, rid, trace_id) -> bool:
        """Accounting shell around :meth:`_handle_stream`: in-flight and
        stream gauges, latency + outcome metrics, the event ring, and
        the stall-watchdog beat. Returns whether the client connection
        is still usable."""
        with self._inflight_lock:
            self._inflight += 1
        self._m["inflight"].inc()
        self._m["stream_active"].inc()
        t0 = time.monotonic()
        t_ring = time.perf_counter()
        try:
            outcome, alive = self._handle_stream(conn, arrays, cctx,
                                                 rid, trace_id)
        finally:
            with self._inflight_lock:
                self._inflight -= 1
            self._m["inflight"].dec()
            self._m["stream_active"].dec()
        wall = time.monotonic() - t0
        self._m["latency"].observe(wall)
        self._m["requests"].labels(outcome=outcome).inc()
        self._ring.complete("router.stream", t_ring, time.perf_counter(),
                            {"outcome": outcome, "rid": rid})
        self._recorder.beat()
        return alive

    # -- client plane ----------------------------------------------------

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                break
            threading.Thread(target=self._serve_client, args=(conn,),
                             daemon=True).start()

    def _serve_client(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        if self._idle_timeout and self._idle_timeout > 0:
            conn.settimeout(self._idle_timeout)
        try:
            while True:
                try:
                    arrays, cctx = read_request(conn)
                except (ConnectionError, TimeoutError, struct.error,
                        OSError):
                    return
                except (ValueError, IndexError) as e:
                    self._m["requests"].labels(outcome="malformed").inc()
                    try:
                        write_error(conn,
                                    f"{ERR_INVALID_ARGUMENT}: malformed "
                                    f"request: {e}")
                    except OSError:
                        pass
                    return
                # one router-minted id per request (globally unique via
                # the process prefix); the trace id is the client's if
                # it sent a context, else the router id — either way it
                # names the whole client->router->backend trace
                rid = next_request_id()
                trace_id = (cctx or {}).get("trace_id") or rid
                tenant = self._tenant_of(cctx)
                is_stream = (cctx is not None
                             and isinstance(cctx.get("decode"), dict))
                if not self._tenant_admit(tenant):
                    # router-side per-tenant cap: refuse THIS tenant
                    # without touching a backend; the dedicated outcome
                    # keeps one tenant's flood out of the fleet-wide
                    # availability objective
                    self._m["tenant_shed"].labels(tenant=tenant).inc()
                    self._m["requests"].labels(
                        outcome="tenant_shed").inc()
                    msg = (f"{ERR_RESOURCE_EXHAUSTED}: tenant "
                           f"{tenant!r} is at its router in-flight "
                           f"cap ({self._tenant_max_inflight}; "
                           "PADDLE_TPU_ROUTER_TENANT_MAX_INFLIGHT)")
                    ectx = (self._stream_ctx(
                        rid, trace_id,
                        {"done": True, "error": True, "seq": 0})
                        if is_stream else None)
                    try:
                        write_error(conn, msg, ctx=ectx)
                    except (ConnectionError, TimeoutError, OSError):
                        return
                    continue
                if is_stream:
                    # decode stream: leave the one-reply fast path for
                    # the seq-relaying proxy with mid-stream failover
                    try:
                        alive = self._serve_stream(conn, arrays, cctx,
                                                   rid, trace_id)
                    finally:
                        self._tenant_release(tenant)
                    if not alive or self._draining.is_set():
                        return
                    continue
                traced = cctx is not None or self._spans.sampled(rid)
                fwd_ctx = {"trace_id": trace_id, "request_id": rid} \
                    if traced else None
                with self._inflight_lock:
                    self._inflight += 1
                self._m["inflight"].inc()
                t0 = time.monotonic()
                info = {}
                try:
                    outcome, payload = self._handle(arrays, ctx=fwd_ctx,
                                                    info=info,
                                                    tenant=tenant)
                finally:
                    self._tenant_release(tenant)
                    with self._inflight_lock:
                        self._inflight -= 1
                    self._m["inflight"].dec()
                wall = time.monotonic() - t0
                self._m["latency"].observe(wall)
                self._m["requests"].labels(outcome=outcome).inc()
                reply_ctx = self._client_reply_ctx(cctx, rid, trace_id,
                                                   info)
                t_reply = time.perf_counter()
                try:
                    if outcome == "ok":
                        write_tensors(conn, payload, ctx=reply_ctx)
                    else:
                        write_error(conn, payload, ctx=reply_ctx)
                except (ConnectionError, TimeoutError, OSError):
                    return
                now = time.perf_counter()
                if traced:
                    # trace line first: the client already has its reply,
                    # and a test (or tail -f) watching the JSONL sink
                    # should see the line as soon as possible
                    self._record_trace(rid, trace_id, cctx is not None,
                                       wall, info, outcome)
                self._ring.complete("router.reply", t_reply, now,
                                    {"outcome": outcome})
                self._ring.complete("router.request", now - wall, now,
                                    {"outcome": outcome, "rid": rid})
                self._recorder.beat()
                if self._draining.is_set():
                    return
        finally:
            conn.close()

    # -- trace assembly --------------------------------------------------

    @staticmethod
    def _backend_spans(info) -> dict:
        """The answering backend's span breakdown (stage -> seconds,
        no ``_s`` suffix) out of its reply trace context, or ``{}``."""
        bctx = info.get("backend_ctx") or {}
        out = {}
        for k, v in (bctx.get("spans") or {}).items():
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            out[k[:-2] if k.endswith("_s") else k] = v
        return out

    def _client_reply_ctx(self, cctx, rid, trace_id, info):
        """Trace context echoed to a PDI2 client: the router's ids plus
        the relayed backend breakdown. ``None`` for a PDI1 client (the
        reply frame must mirror the request's dialect)."""
        if cctx is None:
            return None
        ctx = {"trace_id": trace_id, "request_id": rid}
        spans = {"pick_s": round(info.get("pick_s", 0.0), 6),
                 "forward_s": round(info.get("forward_s", 0.0), 6)}
        for k, v in self._backend_spans(info).items():
            spans[f"backend_{k}_s"] = round(v, 6)
        ctx["spans"] = spans
        if info.get("backend"):
            ctx["backend"] = info["backend"]
        bctx = info.get("backend_ctx") or {}
        if bctx.get("request_id") is not None:
            ctx["backend_request_id"] = bctx["request_id"]
        return ctx

    def _record_trace(self, rid, trace_id, client_traced, wall, info,
                      outcome):
        """One assembled JSONL line per traced request: the router's
        own stages (pick / forward / reply, summing to the observed
        latency) plus the backend's relayed breakdown as ``backend_*``
        extras — kept out of ``total_s`` because the backend's time is
        inside ``forward_s`` already (double counting would make the
        epsilon check total_s - backend_total_s meaningless)."""
        pick = info.get("pick_s", 0.0)
        fwd = info.get("forward_s", 0.0)
        spans = {"pick": pick, "forward": fwd,
                 "reply": max(wall - pick - fwd, 0.0)}
        extra = {"trace_id": trace_id, "outcome": outcome,
                 "attempts": info.get("attempts", 0),
                 "client_traced": bool(client_traced)}
        if info.get("backend"):
            extra["backend"] = info["backend"]
        bctx = info.get("backend_ctx") or {}
        if bctx.get("request_id") is not None:
            extra["backend_request_id"] = bctx["request_id"]
        bspans = self._backend_spans(info)
        if bspans:
            for k, v in bspans.items():
                self._spans.observe_stage(f"backend_{k}", v)
                extra[f"backend_{k}_s"] = round(v, 6)
            extra["backend_total_s"] = round(sum(bspans.values()), 6)
        self._spans.record(rid, spans, extra=extra, force=True)

    # -- admin surface ---------------------------------------------------

    def _fleet_tracez(self) -> dict:
        """Router /tracez: the fleet's merged execution timeline — the
        router's own event ring plus every admin-reachable backend's
        /tracez, skew-corrected by each ring's wall-clock anchor
        (best-effort: an unreachable backend is simply absent)."""
        traces = [self._ring.chrome_trace()]
        for b in self.backends():
            if b.admin_port is None:
                continue
            try:
                traces.append(_tracez.fetch_trace(
                    f"http://{b.host}:{b.admin_port}/tracez",
                    timeout=2.0))
            except Exception:
                continue
        return _tracez.merge_traces(traces)

    def _fleet_memz(self, oom: bool = False) -> dict:
        """Router /memz: the fleet's merged memory plane — every
        admin-reachable backend's /memz body (owner rollups, ghost
        audits; with ``oom=1`` the retained OOM forensic dumps) summed
        into one view, each full body kept under ``backends``. Same
        best-effort contract as the tracez merge."""
        from ..observability import memz as _memz
        snaps, keys = [], []
        for b in self.backends():
            if b.admin_port is None:
                continue
            url = f"http://{b.host}:{b.admin_port}/memz" \
                  + ("?oom=1" if oom else "")
            try:
                snaps.append(_memz.fetch_memz(url, timeout=2.0))
                keys.append(b.key)
            except Exception:
                continue
        return _memz.merge_memz(snaps, keys=keys)

    def _health(self):
        """Router /healthz: healthy while >= 1 backend is routable."""
        reasons = []
        if self._stop.is_set():
            reasons.append("router stopped")
        elif self._draining.is_set():
            reasons.append("draining")
        routable = self._routable()
        if not routable:
            per = [f"{s['key']}: "
                   + ("draining" if s["draining"]
                      else f"breaker {s['breaker']}"
                      if s["breaker"] == CircuitBreaker.OPEN
                      else "; ".join(s["reasons"]) or "unhealthy")
                   for s in (b.snapshot() for b in self.backends())]
            reasons.append("no routable backend ("
                           + ("; ".join(per) or "no backends") + ")")
        return not reasons, reasons

    def _stall_context(self) -> dict:
        """Flight-recorder dump context: what the router was doing when
        it wedged (which backends looked routable, what was in flight)."""
        return {
            "inflight_requests": self.inflight_requests,
            "draining": self._draining.is_set(),
            "backends": [b.snapshot() for b in self.backends()],
        }

    def _status(self) -> dict:
        poll_lat = self._m["poll_latency"]
        return {
            "role": "router",
            "port": self.port,
            "metrics_port": self.metrics_port,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "draining": self._draining.is_set(),
            "inflight_requests": self.inflight_requests,
            "shed_watermark": self._watermark,
            "poll_interval_s": self._poll_interval,
            "trace_wire": True,
            "request_id_base": request_id_base(),
            "poll": {
                "interval_s": self._poll_interval,
                "polls": poll_lat.count,
                "latency_p50_s": round(poll_lat.percentile(0.50), 6),
                "latency_p99_s": round(poll_lat.percentile(0.99), 6),
                "failures": {
                    b.key: b.polls_failed for b in self.backends()},
            },
            "retry_budget": {
                "tokens": round(self._budget.tokens, 2),
                "spent": self._budget.spent,
                "denied": self._budget.denied,
            },
            "streams": {
                "retries": self._stream_retries,
            },
            "membership": None if self._membership is None else {
                "ttl_s": self._membership.ttl,
                "interval_s": self._membership_interval,
                "members": sorted(self._member_keys),
                # topology view (docs/serving.md): role + KV-compat
                # facts each member advertised in its slot meta
                "roles": {
                    b.key: dict(role=b.role, **b.kv_compat())
                    for b in self.backends()
                    if b.key in self._member_keys},
            },
            "topology": {
                "roles": {
                    role: sum(1 for b in self.backends()
                              if b.role == role)
                    for role in ("unified", "prefill", "decode")},
            },
            "backends": [b.snapshot() for b in self.backends()],
        }

    # -- lifecycle -------------------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def inflight_requests(self) -> int:
        with self._inflight_lock:
            return self._inflight

    def drain(self, timeout: float = 30.0) -> bool:
        """Stop accepting, answer everything in flight, then stop."""
        self._draining.set()
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._srv.close()
        deadline = time.monotonic() + float(timeout)
        drained = False
        while time.monotonic() < deadline:
            if self.inflight_requests <= 0:
                drained = True
                break
            time.sleep(0.01)
        self.stop()
        return drained

    def stop(self):
        self._stop.set()
        if self._membership_thread is not None:
            self._membership_thread.join(timeout=2)
        if self._varz is not None:
            self._varz.stop()
        self._recorder.stop()
        self._spans.close()
        if self._admin is not None:
            self._admin.stop()
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._srv.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class _ProcIO:
    """Stdout reader for one spawned backend: drains the pipe forever
    (a full pipe would wedge the child), remembers the announced ports,
    and keeps a tail of lines for crash diagnostics."""

    def __init__(self, proc: subprocess.Popen):
        self.proc = proc
        self.lines = collections.deque(maxlen=64)
        self.serve_port = None
        self.metrics_port = None
        self._serving = threading.Event()
        self._thread = threading.Thread(target=self._read, daemon=True,
                                        name=f"backend-io-{proc.pid}")
        self._thread.start()

    def _read(self):
        try:
            for line in self.proc.stdout:
                line = line.rstrip("\n")
                self.lines.append(line)
                if line.startswith("METRICS "):
                    try:
                        self.metrics_port = int(line.split()[1])
                    except (IndexError, ValueError):
                        pass
                elif line.startswith("SERVING "):
                    try:
                        self.serve_port = int(line.split()[1])
                    except (IndexError, ValueError):
                        pass
                    self._serving.set()
        except (OSError, ValueError):
            pass
        finally:
            self._serving.set()     # EOF: unblock any waiter

    def wait_serving(self, timeout: float):
        if not self._serving.wait(timeout) or self.serve_port is None:
            tail = "\n".join(self.lines)
            raise RuntimeError(
                f"backend pid {self.proc.pid} did not announce SERVING "
                f"within {timeout:g}s; last output:\n{tail}")
        return self.serve_port, self.metrics_port


class BackendSupervisor:
    """Owns a fleet of ``serve.py`` daemons for a router.

    Spawns ``count`` backends from one model prefix (each on an
    ephemeral data + admin port, announced on stdout), registers them
    with the router, and watches them: a backend that dies is removed
    from the routing table and respawned with bounded exponential
    backoff — up to ``max_restarts`` times per slot, after which the
    slot is abandoned (the router simply keeps routing around it). All
    backends share one ``PADDLE_TPU_COMPILE_CACHE`` directory, so a
    respawned backend warms its bucket ladder from the persistent
    compile cache instead of recompiling from scratch.

    ``terminate(key)`` SIGTERMs one backend (it drains via serve.py's
    handler) — the rolling-restart primitive: the watcher respawns it
    once it exits, one slot at a time.
    """

    def __init__(self, model_prefix: str, count: int, router: ServeRouter,
                 host: str = "127.0.0.1", serve_args=None, env=None,
                 max_restarts: int = 5, start_timeout: float = 180.0):
        self.model_prefix = model_prefix
        self.count = int(count)
        self.router = router
        self.host = host
        self.serve_args = list(serve_args or [])
        self.max_restarts = int(max_restarts)
        self.start_timeout = float(start_timeout)
        self._env = dict(env if env is not None else os.environ)
        if "PADDLE_TPU_COMPILE_CACHE" not in self._env:
            import tempfile
            self._cache_dir = tempfile.mkdtemp(prefix="paddle_tpu_fleet_")
            self._env["PADDLE_TPU_COMPILE_CACHE"] = self._cache_dir
        self._m = _router_metrics()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # slot -> {"io": _ProcIO, "backend": Backend, "restarts": int}
        self._slots = {}
        self._watch_thread = None

    def _spawn(self) -> _ProcIO:
        cmd = [sys.executable, "-m", "paddle_tpu.inference.serve",
               self.model_prefix, "--port", "0", "--metrics-port", "0",
               "--stats-interval", "0"] + self.serve_args
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                env=self._env)
        return _ProcIO(proc)

    def start(self):
        for slot in range(self.count):
            io = self._spawn()
            port, admin = io.wait_serving(self.start_timeout)
            backend = Backend(self.host, port, admin)
            self.router.add_backend(backend)
            with self._lock:
                self._slots[slot] = {"io": io, "backend": backend,
                                     "restarts": 0, "delays": None}
        self._watch_thread = threading.Thread(target=self._watch_loop,
                                              daemon=True,
                                              name="fleet-supervisor")
        self._watch_thread.start()
        return self

    def backends(self):
        with self._lock:
            return {slot: s["backend"] for slot, s in self._slots.items()}

    def terminate(self, key: str) -> bool:
        """SIGTERM the backend with this key (graceful drain); the
        watcher respawns the slot after it exits."""
        import signal as _signal
        with self._lock:
            for s in self._slots.values():
                if s["backend"] is not None and s["backend"].key == key:
                    s["io"].proc.send_signal(_signal.SIGTERM)
                    return True
        return False

    def _watch_loop(self):
        while not self._stop.wait(0.25):
            with self._lock:
                slots = list(self._slots.items())
            for slot, s in slots:
                io = s["io"]
                if io is None or io.proc.poll() is None:
                    continue
                if self._stop.is_set():
                    return
                self._restart_slot(slot, s)

    def _restart_slot(self, slot: int, s: dict):
        dead = s["backend"]
        if dead is not None:
            self.router.remove_backend(dead.key)
        tail = "\n".join(list(s["io"].lines)[-5:])
        if s["restarts"] >= self.max_restarts:
            # slot abandoned: the router routes around it for good
            print(f"FLEET slot {slot} exceeded {self.max_restarts} "
                  f"restarts; abandoning. last output:\n{tail}",
                  flush=True)
            with self._lock:
                s["io"], s["backend"] = None, None
            return
        if s["delays"] is None:
            s["delays"] = backoff_delays(self.max_restarts,
                                         base_delay=0.2, max_delay=5.0)
        try:
            delay = next(s["delays"])
        except StopIteration:
            delay = 5.0
        print(f"FLEET slot {slot} ({dead.key if dead else '?'}) exited "
              f"rc={s['io'].proc.returncode}; respawning in {delay:.2f}s",
              flush=True)
        if self._stop.wait(delay):
            return
        s["restarts"] += 1
        self._m["backend_restarts"].inc()
        try:
            io = self._spawn()
        except OSError as e:
            print(f"FLEET slot {slot} respawn failed: {e}", flush=True)
            return                       # old dead io stays; retry next tick
        try:
            port, admin = io.wait_serving(self.start_timeout)
        except RuntimeError as e:
            print(f"FLEET slot {slot} respawn failed: {e}", flush=True)
            with self._lock:
                s["io"], s["backend"] = io, None
            return                       # watcher sees it dead, retries
        backend = Backend(self.host, port, admin)
        with self._lock:
            s["io"], s["backend"] = io, backend
        self.router.add_backend(backend)
        print(f"FLEET slot {slot} back as {backend.key} "
              f"(restart {s['restarts']})", flush=True)

    def stop(self, drain_timeout: float = 15.0):
        """SIGTERM every live backend (graceful drain), then reap; a
        backend that ignores SIGTERM past the timeout is killed."""
        import signal as _signal
        self._stop.set()
        if self._watch_thread is not None:
            self._watch_thread.join(timeout=2)
        with self._lock:
            ios = [s["io"] for s in self._slots.values()
                   if s["io"] is not None]
        for io in ios:
            if io.proc.poll() is None:
                try:
                    io.proc.send_signal(_signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + float(drain_timeout)
        for io in ios:
            left = max(deadline - time.monotonic(), 0.1)
            try:
                io.proc.wait(timeout=left)
            except subprocess.TimeoutExpired:
                io.proc.kill()
                io.proc.wait(timeout=5)


def main_router(args) -> int:
    """Entry point for ``python -m paddle_tpu.inference.serve --router``
    (serve.py delegates here after argparse)."""
    import signal as _signal

    backends = [parse_backend(s) for s in args.backend]
    if not backends and not args.fleet:
        print("router needs --backend HOST:PORT[:ADMIN] and/or --fleet N",
              flush=True)
        return 2
    if args.fleet and not args.model:
        print("--fleet needs the model prefix argument", flush=True)
        return 2

    # forward timeout: a shade over the backend request deadline, so the
    # backend's own typed DEADLINE_EXCEEDED frame wins the race against
    # the router's socket timeout
    req_t = args.request_timeout
    if req_t is None:
        from .serve import _request_timeout_default
        req_t = _request_timeout_default()
    forward_timeout = (req_t + 10.0) if req_t and req_t > 0 else None

    router = ServeRouter(
        backends, port=args.port, host=args.host,
        poll_interval=args.poll_interval,
        shed_watermark=args.shed_watermark,
        forward_timeout=forward_timeout,
        idle_timeout=args.idle_timeout,
        metrics_port=args.metrics_port)

    membership_store = args.membership_store \
        or _flags.env_value("PADDLE_TPU_MEMBERSHIP_STORE")
    if membership_store:
        router.watch_membership(membership_store,
                                group=args.membership_group,
                                ttl=args.membership_ttl)
        print(f"MEMBERSHIP store={membership_store} "
              f"group={args.membership_group}", flush=True)

    sup = None
    if args.fleet:
        serve_args = ["--max-batch", str(args.max_batch),
                      "--pool", str(args.pool),
                      "--batch-timeout-ms", str(args.batch_timeout_ms),
                      "--drain-timeout", str(args.drain_timeout)]
        if args.warmup:
            serve_args.append("--warmup")
        if args.trailing:
            serve_args += ["--trailing", args.trailing]
        if args.request_timeout is not None:
            serve_args += ["--request-timeout", str(args.request_timeout)]
        if args.max_queue is not None:
            serve_args += ["--max-queue", str(args.max_queue)]
        sup = BackendSupervisor(args.model, args.fleet, router,
                                host=args.host, serve_args=serve_args)
        try:
            sup.start()
        except RuntimeError as e:
            print(f"FLEET start failed: {e}", flush=True)
            router.stop()
            sup.stop(drain_timeout=2.0)
            return 1

    keys = [b.key for b in router.backends()]
    print(f"ROUTER backends={','.join(keys)}", flush=True)
    if router.metrics_port is not None:
        print(f"METRICS {router.metrics_port}", flush=True)
    print(f"SERVING {router.port}", flush=True)

    term = threading.Event()
    try:
        _signal.signal(_signal.SIGTERM, lambda *a: term.set())
    except ValueError:                   # non-main thread (tests)
        pass
    try:
        term.wait()
        print("DRAINING", flush=True)
        ok = router.drain(timeout=args.drain_timeout)
        if sup is not None:
            sup.stop(drain_timeout=args.drain_timeout)
        print(f"DRAINED ok={ok}", flush=True)
    except KeyboardInterrupt:
        router.stop()
        if sup is not None:
            sup.stop(drain_timeout=2.0)
    return 0


if __name__ == "__main__":
    from .serve import main
    main(sys.argv[1:] + ["--router"])
