"""Inference serve daemon: a TCP front-end over Predictor, the transport
behind the C/Go client APIs.

Reference: the C API (/root/reference/paddle/fluid/inference/capi/) and Go
bindings (go/paddle/) link AnalysisPredictor into the client process. A
TPU predictor cannot be linked into a C program (the runtime is
XLA/PJRT + Python), so the native-client capability is delivered as a
daemon + thin C client (inference/capi/paddle_c_api.{h,c}): same
capability boundary, process-separated — the deployment shape TPU serving
uses in practice.

Wire protocol (little endian), one request per round trip:
  request : u32 magic 'PDI1' | u32 n_tensors | tensors
  tensor  : u8 dtype | u8 ndim | i64 shape[ndim] | raw data
  reply   : u32 magic | u32 n_tensors | tensors     (or n=0xFFFFFFFF +
            u32 len + utf8 error message)
dtype codes match utils/cpp_extension: 0 f32, 1 f64, 2 i32, 3 i64, 4 u8,
5 bool.

Trace-context extension (optional, backward compatible): a frame whose
magic is 'PDI2' carries a JSON *trace context* between the header and
the payload —
  request : u32 'PDI2' | u32 n_tensors | u32 ctx_len | ctx JSON | tensors
  reply   : u32 'PDI2' | u32 n_tensors | u32 ctx_len | ctx JSON | tensors
  error   : u32 'PDI2' | u32 0xFFFFFFFF | u32 ctx_len | ctx JSON |
            u32 len | utf8 message
The server replies 'PDI2' ONLY to a 'PDI2' request, echoing the trace id
and attaching its span breakdown, so a legacy client ('PDI1', including
the C client) never sees a frame it cannot parse; a new client talking
to a legacy server simply does not send a context (the router gates on
the backend's /statusz ``trace_wire`` capability flag). Contexts are
capped at 64 KiB and an unparseable context degrades to "no context" —
tracing must never fail a request.

Engine: with ``max_batch_size > 1`` (the CLI default) the daemon is a
batched, compile-bounded pipeline — reader threads enqueue decoded
tensors into a DynamicBatcher (inference/batching.py), a dispatcher
forms deadline-bounded batches padded to a shape-bucket ladder, and one
AOT-compiled executable per bucket answers them; ``--warmup``
pre-compiles the whole bucket set so steady-state traffic never
compiles. Trailing dynamic dims are only zero-padded when a startup
probe proves the model padding-invariant (``--trailing``), and every
batched request carries a server-side deadline (``--request-timeout``).
``max_batch_size in (0, 1)`` keeps the legacy one-request-at-a-time
lock. See docs/serving.md.

    python -m paddle_tpu.inference.serve /path/prefix --port 9000 --warmup
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import struct
import threading
import time
from concurrent.futures import TimeoutError as FuturesTimeout

import numpy as np

from ..core import flags as _flags
from ..observability.tracez import RING as _RING
from ..testing import chaos
from .errors import (ERR_DEADLINE_EXCEEDED, ERR_FAILED_PRECONDITION,
                     ERR_INTERNAL, ERR_INVALID_ARGUMENT, TypedServeError)

MAGIC = 0x31494450          # 'PDI1'
MAGIC_TRACE = 0x32494450    # 'PDI2': header is followed by a trace ctx
ERR = 0xFFFFFFFF
_DTYPES = [np.float32, np.float64, np.int32, np.int64, np.uint8, np.bool_]
_MAX_TENSORS = 256          # a request claiming more is malformed
_MAX_NDIM = 32
_MAX_CTX_BYTES = 1 << 16    # trace-context JSON cap
_SEND_COPY_MAX = 1 << 16    # payloads above this go out via memoryview


def _recv_exact(sock, n):
    from ..utils.net import recv_exact
    return recv_exact(sock, n, what="client")


_TENANT_METRICS = None


def _tenant_serve_metrics():
    """Per-tenant request/error counters — the key families the
    per-tenant SLO objectives (observability/slo.py) burn against."""
    global _TENANT_METRICS
    if _TENANT_METRICS is None:
        from ..observability import counter
        _TENANT_METRICS = {
            "requests": counter(
                "paddle_tpu_tenant_requests_total",
                "Decode requests served per tenant",
                labelnames=("tenant",)),
            "errors": counter(
                "paddle_tpu_tenant_errors_total",
                "Decode requests that ended in a typed error frame, "
                "per tenant", labelnames=("tenant",)),
        }
    return _TENANT_METRICS


def max_request_bytes() -> int:
    """Per-request payload budget (``PADDLE_TPU_MAX_REQUEST_BYTES``)."""
    return int(_flags.env_value("PADDLE_TPU_MAX_REQUEST_BYTES"))


def _encode_ctx(ctx: dict) -> bytes:
    raw = json.dumps(ctx, separators=(",", ":")).encode("utf-8")
    if len(raw) > _MAX_CTX_BYTES:
        # oversize context degrades to the trace id alone rather than
        # failing the frame
        raw = json.dumps({"trace_id": ctx.get("trace_id")},
                         separators=(",", ":")).encode("utf-8")
    return struct.pack("<I", len(raw)) + raw


def _read_ctx(sock) -> dict:
    (clen,) = struct.unpack("<I", _recv_exact(sock, 4))
    if clen > _MAX_CTX_BYTES:
        raise ValueError(f"trace context claims {clen} bytes "
                         f"(cap {_MAX_CTX_BYTES})")
    raw = _recv_exact(sock, clen)
    try:
        ctx = json.loads(raw.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return {}               # garbage context must not fail the frame
    return ctx if isinstance(ctx, dict) else {}


def _read_tensor_list(sock, n, max_bytes, what):
    """The shared per-tensor loop: validates every size field BEFORE
    allocating or recv-ing — dtype code and ndim in range, no negative
    dims, and the total payload capped by PADDLE_TPU_MAX_REQUEST_BYTES,
    so a hostile header can never drive ``count * itemsize`` into a huge
    (or, via int64 overflow, negative) recv."""
    out, total = [], 0
    for _ in range(n):
        dt, nd = struct.unpack("<BB", _recv_exact(sock, 2))
        if dt >= len(_DTYPES):
            raise IndexError(f"bad dtype code {dt}")
        if nd > _MAX_NDIM:
            raise ValueError(f"tensor ndim {nd} exceeds cap {_MAX_NDIM}")
        shape = struct.unpack(f"<{nd}q", _recv_exact(sock, 8 * nd)) \
            if nd else ()
        if any(d < 0 for d in shape):
            raise ValueError(f"negative dim in shape {shape}")
        dtype = np.dtype(_DTYPES[dt])
        count = 1
        for d in shape:          # python ints: no int64 overflow
            count *= d
        nbytes = count * dtype.itemsize
        total += nbytes
        if total > max_bytes:
            raise ValueError(
                f"{what} exceeds PADDLE_TPU_MAX_REQUEST_BYTES="
                f"{max_bytes} ({total} bytes claimed)")
        data = _recv_exact(sock, nbytes)
        out.append(np.frombuffer(data, dtype, count).reshape(shape).copy())
    return out


def read_request(sock, max_bytes=None):
    """Decode one request frame -> ``(arrays, ctx)``. ``ctx`` is the
    trace-context dict for a 'PDI2' frame, ``None`` for a legacy 'PDI1'
    frame (every pre-trace client, including the C client)."""
    if max_bytes is None:
        max_bytes = max_request_bytes()
    magic, n = struct.unpack("<II", _recv_exact(sock, 8))
    if magic not in (MAGIC, MAGIC_TRACE):
        raise ValueError("bad magic")
    ctx = _read_ctx(sock) if magic == MAGIC_TRACE else None
    if n > _MAX_TENSORS:
        raise ValueError(f"request claims {n} tensors "
                         f"(cap {_MAX_TENSORS})")
    return _read_tensor_list(sock, n, max_bytes, "request"), ctx


def read_tensors(sock, max_bytes=None):
    """Decode one request frame (tensors only — the historical API; any
    trace context on the frame is read and discarded)."""
    arrays, _ = read_request(sock, max_bytes)
    return arrays


def write_tensors(sock, arrays, ctx=None):
    """Encode one reply frame. Small tensors are coalesced into one
    buffered send; large payloads go out as per-part ``sendall`` on a
    ``memoryview`` of the array — no ``tobytes()`` + ``b"".join`` double
    copy of multi-megabyte results. A ``ctx`` dict upgrades the frame to
    'PDI2' with the JSON trace context after the header — only send one
    to a peer known to speak it."""
    if ctx is None:
        small = [struct.pack("<II", MAGIC, len(arrays))]
    else:
        small = [struct.pack("<II", MAGIC_TRACE, len(arrays)),
                 _encode_ctx(ctx)]
    for a in arrays:
        a = np.ascontiguousarray(a)
        if a.dtype not in [np.dtype(d) for d in _DTYPES]:
            if np.issubdtype(a.dtype, np.floating) or \
                    a.dtype.name == "bfloat16":
                a = a.astype(np.float32)   # bf16/f16 outputs -> f32 wire
            else:
                raise ValueError(
                    f"unsupported output dtype {a.dtype} on the wire "
                    f"(supported: {[np.dtype(d).name for d in _DTYPES]})")
        dt = next(i for i, d in enumerate(_DTYPES) if np.dtype(d) == a.dtype)
        small.append(struct.pack("<BB", dt, a.ndim))
        small.append(struct.pack(f"<{a.ndim}q", *a.shape))
        if a.nbytes > _SEND_COPY_MAX:
            sock.sendall(b"".join(small))
            small = []
            sock.sendall(memoryview(a).cast("B"))
        else:
            small.append(a.tobytes())
    if small:
        sock.sendall(b"".join(small))


def write_error(sock, msg: str, ctx=None):
    m = msg.encode()[:65536]
    if ctx is None:
        sock.sendall(struct.pack("<III", MAGIC, ERR, len(m)) + m)
    else:
        sock.sendall(struct.pack("<II", MAGIC_TRACE, ERR)
                     + _encode_ctx(ctx)
                     + struct.pack("<I", len(m)) + m)


def read_reply_ctx(sock, max_bytes=None):
    """Decode one REPLY frame -> ``(arrays, errmsg, ctx)``: a tensor
    reply is ``(arrays, None, ctx)``, an error frame ``(None, message,
    ctx)``; ``ctx`` is ``None`` unless the peer sent a 'PDI2' frame
    (which it only does in answer to a 'PDI2' request)."""
    if max_bytes is None:
        max_bytes = max_request_bytes()
    magic, n = struct.unpack("<II", _recv_exact(sock, 8))
    if magic not in (MAGIC, MAGIC_TRACE):
        raise ValueError("bad magic in reply")
    ctx = _read_ctx(sock) if magic == MAGIC_TRACE else None
    if n == ERR:
        (mlen,) = struct.unpack("<I", _recv_exact(sock, 4))
        if mlen > 65536:
            raise ValueError(f"error frame claims {mlen} bytes")
        return None, _recv_exact(sock, mlen).decode("utf-8", "replace"), ctx
    if n > _MAX_TENSORS:
        raise ValueError(f"reply claims {n} tensors (cap {_MAX_TENSORS})")
    return _read_tensor_list(sock, n, max_bytes, "reply"), None, ctx


def read_reply(sock, max_bytes=None):
    """Decode one REPLY frame: ``(arrays, None)`` for a tensor reply,
    ``(None, message)`` for an error frame. The router (and any Python
    client) needs this because ``read_tensors`` treats the error marker
    as a hostile tensor count. Same size validation as ``read_tensors``.
    """
    arrays, err, _ = read_reply_ctx(sock, max_bytes)
    return arrays, err


def decode_request(sock, prompt, opts=None, trace=True,
                   on_token=None, max_bytes=None):
    """Client half of the decode wire exchange on an open socket.

    Sends the prompt (int32 [T]); with ``trace=True`` the request is a
    'PDI2' frame (``opts`` rides in its ``decode`` context field —
    including the multi-tenant QoS identity ``tenant``/``priority``,
    which server and router read from there) and
    the server streams per-token frames — ``on_token(tok, stream_ctx)``
    fires for each — before the final accumulated frame. ``trace=False``
    sends legacy 'PDI1' and blocks for the single accumulated reply.
    Returns the generated tokens as a list; raises TypedServeError on a
    typed error frame (mid-stream or otherwise). An error frame that
    arrives after token frames does NOT drop the prefix: the raised
    exception carries the tokens already received (in seq order) as
    ``.partial_tokens`` plus ``.last_seq``. Token frames are
    de-duplicated by ``seq`` (a failover relay may legally repeat one),
    and the final done frame's accumulated payload is authoritative
    regardless of token-frame arrival order."""
    from .errors import error_code
    arr = np.asarray(prompt, np.int32).reshape(-1)
    ctx = None
    if trace:
        # always carry the decode field: the router's stream detection
        # keys on its presence, not its contents
        ctx = {"trace_id": f"decode-{os.getpid()}-{id(arr):x}",
               "decode": dict(opts or {})}
    write_tensors(sock, [arr], ctx=ctx)
    by_seq = {}
    while True:
        arrays, err, rctx = read_reply_ctx(sock, max_bytes)
        if err is not None:
            code = error_code(err)
            detail = err.split(":", 1)[1].strip() if code else err
            exc = TypedServeError(code or ERR_INTERNAL, detail)
            exc.partial_tokens = [t for _, t in sorted(by_seq.items())]
            exc.last_seq = max(by_seq) if by_seq else -1
            raise exc
        stream = (rctx or {}).get("stream") or {}
        if not trace or stream.get("done"):
            return [int(t) for t in np.asarray(arrays[0]).reshape(-1)]
        tok = int(np.asarray(arrays[0]).reshape(-1)[0])
        seq = int(stream.get("seq", len(by_seq)))
        if seq in by_seq:
            continue                 # duplicate frame: already surfaced
        by_seq[seq] = tok
        if on_token is not None:
            on_token(tok, stream)


def _idle_timeout_default() -> float:
    return float(_flags.env_value("PADDLE_TPU_SERVE_IDLE_TIMEOUT"))


def _request_timeout_default() -> float:
    return float(_flags.env_value("PADDLE_TPU_SERVE_REQUEST_TIMEOUT"))


class InferenceServer:
    """Serves one loaded model over TCP.

    Two engines:
    * ``max_batch_size in (None, 0, 1)`` — legacy serialized mode: the
      predictor call runs under a global lock, one request at a time.
    * ``max_batch_size > 1`` — batched mode: connection threads only
      decode and enqueue; a DynamicBatcher forms deadline-bounded
      batches, pads them to the bucket ladder, and round-robins them
      across ``pool_size`` predictors pinned to distinct devices.
      ``warmup=True`` pre-compiles every bucket at startup so
      steady-state traffic never compiles.

    ``stats_interval > 0`` prints a periodic ``SERVE_STATS {json}`` line
    (queue depth, occupancy, padding waste, compile count, latency
    percentiles, reqs/s) from the metrics registry via
    ``profiler.serve_stats()``.

    ``metrics_port`` (or ``PADDLE_TPU_METRICS_PORT``) mounts the admin
    HTTP endpoint — ``/metrics`` (Prometheus exposition), ``/healthz``
    (503 once the dispatcher dies or the queue wedges past the request
    deadline) and ``/statusz`` (one JSON snapshot: serve stats, bucket
    ladder, warmup/compile state, per-device HBM, uptime, effective
    config). Off by default; ``0`` picks a free port
    (``srv.metrics_port``). See docs/observability.md.
    """

    def __init__(self, model_prefix: str, port: int = 0,
                 host: str = "127.0.0.1", max_batch_size: int = None,
                 batch_timeout_ms: float = 2.0, pool_size: int = 1,
                 warmup: bool = False, idle_timeout: float = None,
                 stats_interval: float = 0.0, request_timeout: float = None,
                 trailing: str = None, metrics_port: int = None,
                 max_queue: int = None, decode: bool = False,
                 decode_slots: int = None, decode_max_new: int = None,
                 draft_model: str = None, speculate_k: int = None,
                 kv_dtype: str = None, draft_quant: bool = None,
                 host_pages: int = None, role: str = None):
        # loopback by default: the daemon is unauthenticated — exposing a
        # model to the network segment must be an explicit --host choice
        if role is None:
            role = str(_flags.env_value("PADDLE_TPU_SERVE_ROLE"))
        role = str(role).lower()
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"unknown serve role {role!r} (want "
                             f"'unified', 'prefill' or 'decode')")
        if role != "unified" and not decode:
            raise ValueError(
                f"role {role!r} requires decode mode: a disaggregated "
                f"worker exports or imports KV pages (docs/serving.md)")
        self.role = role
        if max_batch_size is None:
            max_batch_size = int(_flags.env_value("PADDLE_TPU_SERVE_BATCH"))
        self._batched = (not decode) and max_batch_size \
            and int(max_batch_size) > 1
        self._batcher = None
        self._engine = None          # continuous-batching decode engine
        self.warmup_compiles = 0
        if decode:
            # autoregressive decode mode: the token-level continuous
            # batcher (inference/decode.py) replaces the one-shot
            # predictor; requests are token prompts, replies are token
            # streams (PDI2) or one accumulated frame (PDI1)
            from .decode import load_for_decode
            kw = {}
            if decode_slots:
                kw["max_slots"] = int(decode_slots)
            if decode_max_new:
                kw["max_new_tokens"] = int(decode_max_new)
            if draft_model:
                kw["draft_prefix"] = draft_model
            if speculate_k is not None:
                kw["speculate_k"] = int(speculate_k)
            if kv_dtype:
                kw["kv_dtype"] = str(kv_dtype)
            if draft_quant:
                kw["draft_quant"] = True
            if host_pages is not None:
                kw["host_pages"] = int(host_pages)
            if role != "unified":
                # disaggregated worker: arm the engine's KV handoff
                # endpoints (export on prefill, import on decode);
                # unified workers keep today's path untouched
                kw["handoff"] = True
            self._engine = load_for_decode(model_prefix, **kw)
            self._predictor = None
            if warmup:
                self.warmup_compiles = self._engine.warmup(verbose=True)
        elif self._batched:
            from . import Config, PredictorPool
            from .batching import DynamicBatcher
            cfg = Config(model_prefix)
            pool = PredictorPool(cfg, size=max(int(pool_size), 1),
                                 devices="auto" if int(pool_size) > 1
                                 else None)
            self._pool = pool
            self._predictor = pool.retrieve(0)
            self._batcher = DynamicBatcher(
                pool, max_batch_size=int(max_batch_size),
                batch_timeout_ms=batch_timeout_ms, trailing=trailing,
                max_queue=max_queue)
            if warmup:
                self.warmup_compiles = self._batcher.warmup()
        else:
            from . import Config, create_predictor
            self._predictor = create_predictor(Config(model_prefix))
        self._lock = threading.Lock()
        self._idle_timeout = _idle_timeout_default() \
            if idle_timeout is None else float(idle_timeout)
        self._request_timeout = _request_timeout_default() \
            if request_timeout is None else float(request_timeout)
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._t0 = time.monotonic()
        self._stop = threading.Event()
        self._draining = threading.Event()
        self._conn_inflight = 0      # requests read and not yet answered
        self._conn_lock = threading.Lock()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()
        if stats_interval and stats_interval > 0:
            self._stats_thread = threading.Thread(
                target=self._stats_loop, args=(float(stats_interval),),
                daemon=True)
            self._stats_thread.start()
        # admin endpoint: off unless a port is given (env or argument);
        # 0 = ephemeral. Loopback only, like the data-plane default.
        self._admin = None
        self.metrics_port = None
        if metrics_port is None:
            metrics_port = _flags.env_value("PADDLE_TPU_METRICS_PORT")
        self._varz = None
        self._slo = None
        if metrics_port is not None and int(metrics_port) >= 0:
            from ..observability import (AdminServer, SLOEngine,
                                         TimeSeriesStore,
                                         install_default_collectors,
                                         serve_objectives)
            install_default_collectors()
            # windowed history + SLO verdicts ride the same admin plane:
            # /varz is the ring-buffer view, /alertz the burn-rate
            # judgment over it (docs/observability.md)
            self._varz = TimeSeriesStore()
            self._varz.start()
            self._slo = SLOEngine(self._varz, serve_objectives())
            self._admin = AdminServer(port=int(metrics_port), host=host,
                                      health_fn=self._health,
                                      status_fn=self._status,
                                      varz_fn=self._varz.varz,
                                      alertz_fn=self._slo.alertz)
            self.metrics_port = self._admin.port

    @property
    def batched(self) -> bool:
        return bool(self._batched)

    # -- admin surface ---------------------------------------------------

    def _health(self):
        """(healthy, reasons) for /healthz: the accept loop and (in
        batched mode) the dispatcher + workers must be alive, and the
        queue must not be wedged past the request deadline."""
        reasons = []
        if self._stop.is_set():
            reasons.append("server stopped")
        elif self._draining.is_set():
            # a draining backend finishes in-flight work but must take no
            # new traffic: the router reads this as "route around me"
            reasons.append("draining")
        elif not self._thread.is_alive():
            reasons.append("accept thread dead")
        if self._engine is not None \
                and not self._engine._thread.is_alive():
            reasons.append("decode scheduler thread dead")
        if self._batcher is not None:
            if not self._batcher.dispatcher_alive:
                reasons.append("dispatcher thread dead")
            if not self._batcher.workers_alive:
                reasons.append("predictor worker thread dead")
            wedge_after = self._request_timeout \
                if self._request_timeout and self._request_timeout > 0 \
                else 300.0
            oldest = self._batcher.oldest_wait_s
            if oldest > wedge_after:
                reasons.append(
                    f"queue wedged: oldest request waiting "
                    f"{oldest:.1f}s (> {wedge_after:g}s)")
        return not reasons, reasons

    def _status(self) -> dict:
        from .. import profiler
        from ..core import monitor

        st = {
            "engine": "decode" if self._engine is not None
            else ("batched" if self._batched else "serialized"),
            "port": self.port,
            "metrics_port": self.metrics_port,
            # capability flag the router gates trace propagation on: a
            # backend advertising it accepts 'PDI2' request frames
            "trace_wire": True,
            # serving-topology role (docs/serving.md): what the worker
            # advertises into membership for topology-aware routing
            "role": self.role,
            "draining": self._draining.is_set(),
            "inflight_requests": self.inflight_requests,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "config": {
                "idle_timeout_s": self._idle_timeout,
                "request_timeout_s": self._request_timeout,
                "max_request_bytes": max_request_bytes(),
            },
            "warmup_compiles": self.warmup_compiles,
            "compiles": len(profiler.compile_events()),
            "serve": profiler.serve_stats(),
            "device_memory": monitor.all_device_memory_stats(),
        }
        if self._engine is not None:
            st["decode"] = self._engine.stats()
        # the memory plane's compact block: per-pool owner rollups +
        # fragmentation + ghost count (full detail lives at /memz)
        try:
            from ..observability import memz as _memz
            st["memory"] = _memz.status_block()
        except Exception as e:
            st["memory"] = {"error": repr(e)}
        if self._batcher is not None:
            st["batcher"] = {
                "ladder": self._batcher.ladder,
                "trailing_bucketing": self._batcher.trailing_bucketing,
                "queue_depth": self._batcher.queue_depth,
                "oldest_wait_s": round(self._batcher.oldest_wait_s, 3),
                "dispatcher_alive": self._batcher.dispatcher_alive,
            }
        return st

    def stats_line(self) -> str:
        """One ``SERVE_STATS {json}`` line from the registry snapshot;
        ``ts_monotonic`` makes consecutive lines orderable and
        rate-computable without wall-clock trust."""
        from .. import profiler
        stats = profiler.serve_stats()
        stats["ts_monotonic"] = round(time.monotonic(), 3)
        if self._batcher is not None:
            stats["queue_depth"] = self._batcher.queue_depth
        return "SERVE_STATS " + json.dumps(stats)

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _run(self, inputs, ctx=None):
        """-> (outputs, future_or_None); the future carries the request
        id and (post-delivery) the span breakdown a traced reply echoes
        back to the caller. A ``tenant`` field in the request ctx tags
        the request for the batcher's weighted-fair QoS."""
        if self._batcher is not None:
            tenant = (ctx or {}).get("tenant")
            fut = self._batcher.submit(inputs, tenant=tenant)
            deadline = self._request_timeout
            if not deadline or deadline <= 0:
                return fut.result(), fut
            try:
                return fut.result(timeout=deadline), fut
            except FuturesTimeout:
                # a wedged predictor/worker must not pin the connection
                # thread forever; the future stays abandoned (the
                # batcher delivers into it defensively) and the client
                # gets a typed error frame instead of silence
                err = TypedServeError(
                    ERR_DEADLINE_EXCEEDED,
                    f"request deadline exceeded "
                    f"({deadline:g}s in queue+execute; "
                    f"PADDLE_TPU_SERVE_REQUEST_TIMEOUT)")
                err.request_id = getattr(fut, "request_id", None)
                raise err from None
        with self._lock:
            return self._predictor.run(inputs), None

    @staticmethod
    def _reply_ctx(ctx, fut, exc=None):
        """Reply trace context for a traced request: echo the trace id,
        attach this backend's request id and span breakdown (what the
        router joins into the end-to-end trace). None for untraced
        ('PDI1') requests — the reply then stays a legacy frame."""
        if ctx is None:
            return None
        out = {"trace_id": ctx.get("trace_id")}
        src = exc if exc is not None else fut
        rid = getattr(src, "request_id", None)
        if rid is None and fut is not None:
            rid = getattr(fut, "request_id", None)
        if rid is not None:
            out["request_id"] = int(rid)
        spans = getattr(src, "spans", None)
        if spans is None and fut is not None:
            spans = getattr(fut, "spans", None)
        if spans:
            out["spans"] = {f"{k}_s": round(float(v), 6)
                            for k, v in spans.items()}
        return out

    def _serve_decode(self, conn, inputs, ctx):
        """One decode request on an open connection.

        PDI2 clients get a PDI2 frame per sampled token — one int32 [1]
        tensor, ctx ``{"stream": {"seq": i, "eos": bool, "done": false}}``
        — then a final done frame carrying the full accumulated sequence
        (``{"stream": {"done": true, "n_tokens": n}}``). PDI1 clients
        get exactly one legacy frame with the accumulated tokens:
        byte-identical framing to a one-shot reply, so pre-decode
        clients (including the C client) work unchanged. A stream that
        dies mid-flight becomes a typed error frame on the same
        connection. Returns False when the socket is unusable."""
        opts = {}
        if ctx is not None and isinstance(ctx.get("decode"), dict):
            d = ctx["decode"]
            for key in ("max_new_tokens", "top_k", "eos_id", "seed"):
                if d.get(key) is not None:
                    opts[key] = int(d[key])
            if d.get("temperature") is not None:
                opts["temperature"] = float(d["temperature"])
            # multi-tenant QoS identity (docs/serving.md): who to bill
            # the tokens to, and how urgently to schedule them
            if d.get("tenant") is not None:
                opts["tenant"] = str(d["tenant"])
            if d.get("priority") is not None:
                opts["priority"] = int(d["priority"])
        tenant = opts.get("tenant") or "default"
        tm = _tenant_serve_metrics()
        tm["requests"].labels(tenant=tenant).inc()

        def _sctx(stream_fields, req_id=None):
            if ctx is None:
                return None
            out = {"stream": stream_fields}
            if ctx.get("trace_id") is not None:
                out["trace_id"] = ctx.get("trace_id")
            if req_id is not None:
                out["request_id"] = int(req_id)
            return out

        try:
            if len(inputs) != 1:
                raise TypedServeError(
                    ERR_INVALID_ARGUMENT,
                    f"decode request wants exactly one prompt tensor, "
                    f"got {len(inputs)}")
            prompt = np.asarray(inputs[0])
            if prompt.dtype not in (np.int32, np.int64) \
                    or prompt.ndim not in (1, 2) \
                    or (prompt.ndim == 2 and prompt.shape[0] != 1):
                raise TypedServeError(
                    ERR_INVALID_ARGUMENT,
                    "decode prompt must be int32/int64 [T] or [1, T]")
            stream = self._engine.submit(prompt.reshape(-1), **opts)
        except TypedServeError as e:
            tm["errors"].labels(tenant=tenant).inc()
            try:
                write_error(conn, str(e),
                            ctx=_sctx({"done": True, "error": True}))
            except OSError:
                pass
            return True          # frame fully consumed; keep the conn
        timeout = self._request_timeout \
            if self._request_timeout and self._request_timeout > 0 else None
        seq = 0
        try:
            while True:
                ev = stream.next_event(timeout=timeout)
                if ev[0] == "done":
                    chaos.maybe_fail("serve.stream_write", detail="done")
                    final = np.asarray(ev[1], np.int32)
                    write_tensors(conn, [final],
                                  ctx=_sctx({"done": True,
                                             "n_tokens": int(final.size)},
                                            stream.request_id))
                    return True
                _, tok, eos = ev
                if ctx is not None:
                    chaos.maybe_fail("serve.stream_write", detail=seq)
                    write_tensors(
                        conn, [np.asarray([tok], np.int32)],
                        ctx=_sctx({"seq": seq, "eos": bool(eos),
                                   "done": False}, stream.request_id))
                seq += 1
        except TypedServeError as e:
            tm["errors"].labels(tenant=tenant).inc()
            try:
                write_error(conn, str(e),
                            ctx=_sctx({"done": True, "error": True,
                                       "seq": seq}))
            except OSError:
                pass
            return True
        except (ConnectionError, TimeoutError, OSError):
            return False

    def _serve_handoff(self, conn, inputs, ctx) -> bool:
        """One KV-handoff control frame (docs/serving.md "Disaggregated
        prefill/decode").

        ``kv_export`` (prefill side): the prompt tensor comes in, the
        reply frame carries the prompt's full KV pages as leaf arrays
        plus the export metadata (compat contract, page count, per-page
        checksums) in the reply ctx. ``kv_handoff`` (decode side): the
        leaf arrays come in with the metadata in the request ctx, and
        the ack frame reports how many pages landed. Any refusal —
        disabled endpoint, compat mismatch, checksum failure, pool
        exhaustion — is a typed error frame the router degrades on.
        Returns False when the socket is unusable."""
        timeout = self._request_timeout \
            if self._request_timeout and self._request_timeout > 0 \
            else 30.0
        tctx = {"trace_id": ctx.get("trace_id")} \
            if ctx.get("trace_id") is not None else {}
        try:
            try:
                if ctx.get("kv_export") is not None:
                    if len(inputs) != 1:
                        raise TypedServeError(
                            ERR_INVALID_ARGUMENT,
                            f"kv_export wants exactly one prompt "
                            f"tensor, got {len(inputs)}")
                    prompt = np.asarray(inputs[0]).reshape(-1)
                    payload = self._engine.export_kv(prompt,
                                                     timeout=timeout)
                    arrays = payload.pop("arrays")
                    write_tensors(conn, arrays,
                                  ctx=dict(tctx, kv_export=payload))
                else:
                    meta = ctx.get("kv_handoff")
                    if not isinstance(meta, dict):
                        raise TypedServeError(
                            ERR_INVALID_ARGUMENT,
                            "kv_handoff ctx must be a metadata object")
                    payload = dict(meta)
                    payload["arrays"] = [np.asarray(a) for a in inputs]
                    n = self._engine.import_kv(payload, timeout=timeout)
                    write_tensors(conn, [np.asarray([n], np.int32)],
                                  ctx=dict(tctx,
                                           kv_handoff={"landed": n}))
            except TypedServeError as e:
                write_error(conn, str(e), ctx=tctx or None)
            except AttributeError:
                # a pre-handoff engine (or none): same contract as a
                # disabled endpoint — typed refusal, router re-prefills
                write_error(conn,
                            str(TypedServeError(
                                ERR_FAILED_PRECONDITION,
                                "backend has no KV handoff endpoint")),
                            ctx=tctx or None)
            return True
        except (ConnectionError, TimeoutError, OSError):
            return False

    def _serve_conn(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        # per-connection idle timeout: a dead client must not pin a
        # daemon thread (and its socket buffers) forever
        timeout = self._idle_timeout
        if timeout and timeout > 0:
            conn.settimeout(timeout)
        try:
            while True:
                try:
                    chaos.maybe_fail("serve.conn.read")
                    inputs, ctx = read_request(conn)
                except (ConnectionError, TimeoutError, struct.error,
                        OSError):
                    return
                except (ValueError, IndexError) as e:
                    # unparseable request (bad magic / dtype code /
                    # hostile sizes): the stream is desynced —
                    # best-effort typed error frame, drop the connection
                    try:
                        write_error(conn,
                                    f"{ERR_INVALID_ARGUMENT}: malformed "
                                    f"request: {e}")
                    except OSError:
                        pass
                    return
                with self._conn_lock:
                    self._conn_inflight += 1
                t_req = time.perf_counter()
                try:
                    if ctx is not None \
                            and (ctx.get("kv_export") is not None
                                 or ctx.get("kv_handoff") is not None):
                        # KV-handoff control frames for disaggregated
                        # serving ride the same connection as decode
                        # streams (docs/serving.md)
                        if not self._serve_handoff(conn, inputs, ctx):
                            return
                    elif self._engine is not None:
                        if not self._serve_decode(conn, inputs, ctx):
                            return
                    else:
                        try:
                            outputs, fut = self._run(inputs, ctx)
                            chaos.maybe_fail("serve.conn.reply")
                            write_tensors(conn, outputs,
                                          ctx=self._reply_ctx(ctx, fut))
                        except (ConnectionError, TimeoutError):
                            return
                        except Exception as e:  # model-side error -> client
                            if getattr(e, "code", None):
                                msg = str(e)  # typed: frame leads with CODE
                            else:
                                msg = f"{type(e).__name__}: {e}"
                            rid = getattr(e, "request_id", None)
                            if rid:
                                # the id a sampled span trace / stall dump
                                # carries
                                msg += f" [request_id={rid}]"
                            write_error(conn, msg,
                                        ctx=self._reply_ctx(ctx, None,
                                                            exc=e))
                finally:
                    with self._conn_lock:
                        self._conn_inflight -= 1
                    _RING.complete("serve.request", t_req,
                                   time.perf_counter())
                if self._draining.is_set():
                    # drained: the in-flight request was answered; a
                    # keep-alive connection must not feed a retiring
                    # backend more work
                    return
        finally:
            conn.close()

    def _stats_loop(self, interval: float):
        while not self._stop.wait(interval):
            print(self.stats_line(), flush=True)

    # -- draining / lifecycle --------------------------------------------

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    @property
    def inflight_requests(self) -> int:
        """Requests read off a connection and not yet answered."""
        with self._conn_lock:
            return self._conn_inflight

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful retirement (the SIGTERM path): stop accepting new
        connections, flip /healthz to "draining" so the router routes
        around this backend, answer every request already read off a
        connection (result or typed error), then stop. Returns True when
        everything in flight was answered inside ``timeout``.

        Idle keep-alive connections are closed as soon as their current
        request (if any) is answered; a client racing a request into the
        closing socket sees a connection error, which the front router
        converts into a failover, not a lost request."""
        self._draining.set()
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._srv.close()
        deadline = time.monotonic() + float(timeout)
        drained = False
        while time.monotonic() < deadline:
            busy = self.inflight_requests > 0 or (
                self._batcher is not None
                and self._batcher.inflight > 0) or (
                self._engine is not None
                and (self._engine.stats()["active"]
                     + self._engine.stats()["pending"]) > 0)
            if not busy:
                drained = True
                break
            time.sleep(0.01)
        self.stop()
        return drained

    def stop(self):
        self._stop.set()
        if self._varz is not None:
            self._varz.stop()
        if self._admin is not None:
            self._admin.stop()
        if self._batcher is not None:
            self._batcher.stop()
        if self._engine is not None:
            self._engine.stop()
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._srv.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def main(argv=None):
    ap = argparse.ArgumentParser(description="paddle_tpu inference server")
    ap.add_argument("model", nargs="?", default=None,
                    help="jit.save artifact prefix (required unless "
                         "--router runs over pre-started --backend "
                         "daemons)")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (default loopback; 0.0.0.0 exposes "
                         "the unauthenticated daemon to the network)")
    ap.add_argument("--max-batch", type=int, default=8,
                    help="cross-request batch row budget (0/1 = legacy "
                         "serialized mode)")
    ap.add_argument("--trailing", choices=("auto", "on", "off"),
                    default=None,
                    help="trailing-dynamic-dim bucketing policy: 'auto' "
                         "(default) proves padding-invariance with a "
                         "startup probe and falls back to batch-dim-only "
                         "batching on mismatch; 'on' forces it; 'off' "
                         "merges only exact trailing shapes "
                         "(PADDLE_TPU_SERVE_TRAILING)")
    ap.add_argument("--request-timeout", type=float, default=None,
                    help="server-side deadline in seconds for one request "
                         "(queue wait + execution); on expiry the client "
                         "gets an error frame instead of blocking forever "
                         "(default PADDLE_TPU_SERVE_REQUEST_TIMEOUT or "
                         "120; 0 = off)")
    ap.add_argument("--batch-timeout-ms", type=float, default=2.0,
                    help="max wait past the oldest queued request before "
                         "dispatching a partial batch")
    ap.add_argument("--pool", type=int, default=1,
                    help="predictor pool size; >1 pins each slot to a "
                         "distinct device and round-robins batches")
    ap.add_argument("--warmup", action="store_true",
                    help="AOT-compile the whole shape-bucket ladder at "
                         "startup so steady-state traffic never compiles")
    ap.add_argument("--idle-timeout", type=float, default=None,
                    help="per-connection idle seconds before the daemon "
                         "drops it (default "
                         "PADDLE_TPU_SERVE_IDLE_TIMEOUT or 600; 0 = off)")
    ap.add_argument("--stats-interval", type=float, default=10.0,
                    help="seconds between SERVE_STATS lines (0 = off)")
    ap.add_argument("--metrics-port", type=int, default=None,
                    help="mount /metrics + /healthz + /statusz + /varz "
                         "+ /alertz on this port (0 = ephemeral; "
                         "default off, or PADDLE_TPU_METRICS_PORT)")
    ap.add_argument("--drain-timeout", type=float, default=30.0,
                    help="seconds SIGTERM waits for in-flight requests "
                         "before hard stop")
    ap.add_argument("--max-queue", type=int, default=None,
                    help="admission watermark: queued requests past this "
                         "are shed with a RESOURCE_EXHAUSTED frame "
                         "instead of queueing unboundedly (default "
                         "PADDLE_TPU_SERVE_MAX_QUEUE or off)")
    ap.add_argument("--decode", action="store_true",
                    help="autoregressive decode mode: load a "
                         "decode.save_for_decode artifact and serve "
                         "token streams through the continuous-batching "
                         "KV-cache engine (PDI2 clients stream per-token "
                         "frames; PDI1 clients get one accumulated "
                         "reply). docs/serving.md#continuous-batching-"
                         "decode")
    ap.add_argument("--decode-slots", type=int, default=None,
                    help="(decode) KV-cache slot-pool size — concurrent "
                         "sequences; default sized from free HBM "
                         "(core.monitor), fixed fallback of 8 on CPU")
    ap.add_argument("--decode-max-new", type=int, default=None,
                    help="(decode) default max new tokens per request "
                         "when the client does not specify one")
    ap.add_argument("--draft-model", default=None, metavar="PREFIX",
                    help="(decode) draft-model save_for_decode artifact "
                         "prefix enabling speculative decoding; must "
                         "share the target's vocab (default "
                         "PADDLE_TPU_DECODE_DRAFT_MODEL)")
    ap.add_argument("--speculate-k", type=int, default=None,
                    help="(decode) speculation depth: draft steps per "
                         "scheduler tick, verified in one k+1-token "
                         "target forward (default "
                         "PADDLE_TPU_DECODE_SPECULATE; 0 disables)")
    ap.add_argument("--role", choices=("unified", "prefill", "decode"),
                    default=None,
                    help="(decode) serving-topology role for "
                         "disaggregated prefill/decode: 'prefill' runs "
                         "prompt forwards and exports KV pages, 'decode' "
                         "imports them and streams tokens, 'unified' "
                         "(default) does both locally. Non-unified roles "
                         "arm the engine's KV-handoff endpoints and are "
                         "advertised in the membership meta (default "
                         "PADDLE_TPU_SERVE_ROLE; docs/serving.md)")
    ap.add_argument("--host-pages", type=int, default=None,
                    help="host-RAM KV tier capacity in pages for decode "
                         "mode (memory/migration.py): cold pages spill "
                         "to host arenas under pool pressure and refetch "
                         "on demand; default PADDLE_TPU_DECODE_HOST_PAGES "
                         "(0 = tiering off)")
    ap.add_argument("--kv-dtype", default=None,
                    choices=("float32", "int8"),
                    help="(decode) KV page-pool dtype: int8 stores "
                         "quantized pages with per-row scales, cutting "
                         "page HBM ~4x (default "
                         "PADDLE_TPU_DECODE_KV_DTYPE)")
    ap.add_argument("--draft-quant", action="store_true", default=None,
                    help="(decode) int8-quantize the draft model's "
                         "weights at load — draft numerics only move "
                         "the speculation acceptance rate, never the "
                         "target stream (default "
                         "PADDLE_TPU_DECODE_DRAFT_QUANT)")
    ap.add_argument("--router", action="store_true",
                    help="run the health-aware front router instead of a "
                         "backend: load-balance the wire protocol across "
                         "--backend daemons (or a --fleet it spawns from "
                         "the model prefix) with circuit-breaker "
                         "failover, load shedding and drain-aware "
                         "routing (docs/fault_tolerance.md)")
    ap.add_argument("--backend", action="append", default=[],
                    metavar="HOST:PORT[:ADMIN_PORT]",
                    help="(router) one backend serve daemon; repeatable. "
                         "ADMIN_PORT enables /healthz-driven routing")
    ap.add_argument("--fleet", type=int, default=0,
                    help="(router) spawn this many backend daemons from "
                         "the model prefix and supervise them "
                         "(restart-with-backoff, warm compile cache)")
    ap.add_argument("--poll-interval", type=float, default=0.5,
                    help="(router) seconds between backend health polls")
    ap.add_argument("--shed-watermark", type=int, default=64,
                    help="(router) queue depth past which a backend "
                         "counts as overloaded; when EVERY routable "
                         "backend is past it, requests are shed with "
                         "RESOURCE_EXHAUSTED")
    ap.add_argument("--membership-store", default=None,
                    metavar="ENDPOINT",
                    help="membership registry endpoint (HOST:PORT for "
                         "TCPStore, else a FileStore directory). A "
                         "backend publishes TTL'd heartbeats into it; a "
                         "router watches it and adds/removes backends "
                         "live (default PADDLE_TPU_MEMBERSHIP_STORE)")
    ap.add_argument("--membership-group", default="serve",
                    help="membership registry group name")
    ap.add_argument("--membership-ttl", type=float, default=None,
                    help="seconds without heartbeat progress before a "
                         "member expires (default "
                         "PADDLE_TPU_MEMBERSHIP_TTL)")
    args = ap.parse_args(argv)
    if args.router:
        from .router import main_router
        return main_router(args)
    if not args.model:
        ap.error("model prefix is required (or pass --router)")
    # honor JAX_PLATFORMS for the daemon: a TPU PJRT plugin outranks the
    # env var during backend registration, so an explicit config update is
    # the only way `JAX_PLATFORMS=cpu python -m ...serve` stays off-chip
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax
        jax.config.update("jax_platforms", platforms)
    srv = InferenceServer(args.model, port=args.port, host=args.host,
                          max_batch_size=args.max_batch,
                          batch_timeout_ms=args.batch_timeout_ms,
                          pool_size=args.pool, warmup=args.warmup,
                          idle_timeout=args.idle_timeout,
                          stats_interval=args.stats_interval,
                          request_timeout=args.request_timeout,
                          trailing=args.trailing,
                          metrics_port=args.metrics_port,
                          max_queue=args.max_queue, decode=args.decode,
                          decode_slots=args.decode_slots,
                          decode_max_new=args.decode_max_new,
                          draft_model=args.draft_model,
                          speculate_k=args.speculate_k,
                          kv_dtype=args.kv_dtype,
                          draft_quant=args.draft_quant,
                          host_pages=args.host_pages, role=args.role)
    if args.warmup:
        print(f"WARMUP compiles={srv.warmup_compiles}", flush=True)
    if srv.metrics_port is not None:
        print(f"METRICS {srv.metrics_port}", flush=True)
    print(f"SERVING {srv.port}", flush=True)
    # dynamic membership: publish this backend into the registry so a
    # watching router adds it to the fleet without supervisor edits;
    # leave() at drain so the router routes around it immediately
    # instead of waiting out the TTL
    publisher = None
    store_ep = args.membership_store \
        or _flags.env_value("PADDLE_TPU_MEMBERSHIP_STORE")
    if store_ep:
        from ..distributed.store.membership import (MembershipPublisher,
                                                    connect)
        ttl = float(args.membership_ttl
                    if args.membership_ttl is not None
                    else _flags.env_value("PADDLE_TPU_MEMBERSHIP_TTL"))
        # decode workers advertise their topology role and KV-compat
        # facts so a watching router can route prefill->handoff->decode
        # and refuse incompatible pairings up front (docs/serving.md)
        meta = None
        if srv._engine is not None:
            meta = {"role": srv.role}
            meta.update(srv._engine.kv_compat())
        publisher = MembershipPublisher(
            connect(store_ep), f"{args.host}:{srv.port}",
            group=args.membership_group, admin_port=srv.metrics_port,
            interval=max(ttl / 3.0, 0.05), meta=meta).start()
        print(f"MEMBERSHIP store={store_ep} group={args.membership_group} "
              f"slot={publisher.slot}", flush=True)
    # SIGTERM = graceful retirement: stop accepting, finish in-flight,
    # exit 0 — the rolling-restart contract the router drains against
    term = threading.Event()
    try:
        signal.signal(signal.SIGTERM, lambda *a: term.set())
    except ValueError:                   # non-main thread (tests)
        pass
    try:
        term.wait()
        print("DRAINING", flush=True)
        if publisher is not None:
            publisher.leave()
        ok = srv.drain(timeout=args.drain_timeout)
        print(f"DRAINED ok={ok}", flush=True)
    except KeyboardInterrupt:
        if publisher is not None:
            publisher.leave()
        srv.stop()


if __name__ == "__main__":
    main()
