"""Inference serve daemon: a TCP front-end over Predictor, the transport
behind the C/Go client APIs.

Reference: the C API (/root/reference/paddle/fluid/inference/capi/) and Go
bindings (go/paddle/) link AnalysisPredictor into the client process. A
TPU predictor cannot be linked into a C program (the runtime is
XLA/PJRT + Python), so the native-client capability is delivered as a
daemon + thin C client (inference/capi/paddle_c_api.{h,c}): same
capability boundary, process-separated — the deployment shape TPU serving
uses in practice.

Wire protocol (little endian), one request per round trip:
  request : u32 magic 'PDI1' | u32 n_tensors | tensors
  tensor  : u8 dtype | u8 ndim | i64 shape[ndim] | raw data
  reply   : u32 magic | u32 n_tensors | tensors     (or n=0xFFFFFFFF +
            u32 len + utf8 error message)
dtype codes match utils/cpp_extension: 0 f32, 1 f64, 2 i32, 3 i64, 4 u8,
5 bool.

    python -m paddle_tpu.inference.serve /path/prefix --port 9000
"""
from __future__ import annotations

import argparse
import os
import socket
import struct
import threading

import numpy as np

MAGIC = 0x31494450          # 'PDI1'
ERR = 0xFFFFFFFF
_DTYPES = [np.float32, np.float64, np.int32, np.int64, np.uint8, np.bool_]


def _recv_exact(sock, n):
    from ..utils.net import recv_exact
    return recv_exact(sock, n, what="client")


def read_tensors(sock):
    magic, n = struct.unpack("<II", _recv_exact(sock, 8))
    if magic != MAGIC:
        raise ValueError("bad magic")
    out = []
    for _ in range(n):
        dt, nd = struct.unpack("<BB", _recv_exact(sock, 2))
        shape = struct.unpack(f"<{nd}q", _recv_exact(sock, 8 * nd)) \
            if nd else ()
        dtype = np.dtype(_DTYPES[dt])
        count = int(np.prod(shape, dtype=np.int64)) if nd else 1
        data = _recv_exact(sock, count * dtype.itemsize)
        out.append(np.frombuffer(data, dtype).reshape(shape).copy())
    return out


def write_tensors(sock, arrays):
    parts = [struct.pack("<II", MAGIC, len(arrays))]
    for a in arrays:
        a = np.ascontiguousarray(a)
        if a.dtype not in [np.dtype(d) for d in _DTYPES]:
            if np.issubdtype(a.dtype, np.floating) or \
                    a.dtype.name == "bfloat16":
                a = a.astype(np.float32)   # bf16/f16 outputs -> f32 wire
            else:
                raise ValueError(
                    f"unsupported output dtype {a.dtype} on the wire "
                    f"(supported: {[np.dtype(d).name for d in _DTYPES]})")
        dt = next(i for i, d in enumerate(_DTYPES) if np.dtype(d) == a.dtype)
        parts.append(struct.pack("<BB", dt, a.ndim))
        parts.append(struct.pack(f"<{a.ndim}q", *a.shape))
        parts.append(a.tobytes())
    sock.sendall(b"".join(parts))


def write_error(sock, msg: str):
    m = msg.encode()[:65536]
    sock.sendall(struct.pack("<III", MAGIC, ERR, len(m)) + m)


class InferenceServer:
    """Serves one loaded model; thread-per-connection (the predictor call
    itself is serialized — XLA executables are thread-compatible but
    request ordering keeps tail latency predictable on one chip)."""

    def __init__(self, model_prefix: str, port: int = 0,
                 host: str = "127.0.0.1"):
        # loopback by default: the daemon is unauthenticated — exposing a
        # model to the network segment must be an explicit --host choice
        from . import Config, create_predictor
        self._predictor = create_predictor(Config(model_prefix))
        self._lock = threading.Lock()
        self._srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((host, port))
        self._srv.listen(64)
        self.port = self._srv.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn):
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while True:
                try:
                    inputs = read_tensors(conn)
                except (ConnectionError, struct.error):
                    return
                except (ValueError, IndexError) as e:
                    # unparseable request (bad magic / dtype code): the
                    # stream is desynced — best-effort error frame, drop
                    # the connection
                    try:
                        write_error(conn, f"malformed request: {e}")
                    except OSError:
                        pass
                    return
                try:
                    with self._lock:
                        outputs = self._predictor.run(inputs)
                    write_tensors(conn, outputs)
                except Exception as e:   # model-side error -> client
                    write_error(conn, f"{type(e).__name__}: {e}")
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._srv.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


def main(argv=None):
    ap = argparse.ArgumentParser(description="paddle_tpu inference server")
    ap.add_argument("model", help="jit.save artifact prefix")
    ap.add_argument("--port", type=int, default=9000)
    ap.add_argument("--host", default="127.0.0.1",
                    help="bind address (default loopback; 0.0.0.0 exposes "
                         "the unauthenticated daemon to the network)")
    args = ap.parse_args(argv)
    # honor JAX_PLATFORMS for the daemon: a TPU PJRT plugin outranks the
    # env var during backend registration, so an explicit config update is
    # the only way `JAX_PLATFORMS=cpu python -m ...serve` stays off-chip
    platforms = os.environ.get("JAX_PLATFORMS")
    if platforms:
        import jax
        jax.config.update("jax_platforms", platforms)
    srv = InferenceServer(args.model, port=args.port, host=args.host)
    print(f"SERVING {srv.port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
