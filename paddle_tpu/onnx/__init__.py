"""paddle.onnx parity surface.

Reference: python/paddle/onnx/export.py — a thin shim that delegates to
the EXTERNAL paddle2onnx package (the reference repo itself contains no
converter). This build keeps the same shape: `export` delegates to an
installed `onnx` tool-chain when one exists and otherwise raises with
the portable alternative (jit.save's StableHLO bundle, which is the
TPU-native interchange format — loadable anywhere XLA runs, including
via the serve daemon + C API for non-Python consumers).
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """paddle.onnx.export parity stub: ALWAYS raises (conversion is not
    implemented). Without the onnx package: RuntimeError pointing at the
    native jit.save path; with it: NotImplementedError (no
    StableHLO->ONNX converter in this build)."""
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise RuntimeError(
            "paddle.onnx.export needs the external `onnx` package "
            "(the reference delegates to paddle2onnx identically, "
            "python/paddle/onnx/export.py). For a portable serialized "
            "model use paddle.jit.save(layer, path, input_spec=...) — "
            "a StableHLO + params bundle servable via "
            "paddle_tpu.inference (including the C API daemon)."
        ) from e
    raise NotImplementedError(
        "onnx graph conversion from StableHLO is not implemented; "
        "use paddle.jit.save for deployment")
