"""Device-memory management: the paged KV allocator and host tiering.

`page_allocator` is deliberately decode-agnostic — it hands out integer
page ids against a fixed-size device pool and tracks refcounts, so the
decode engine, prefix cache, and (later) training remat/offload can all
share one allocator discipline. `migration` layers a host-RAM tier on
top: a two-tier allocator with per-page residency plus an async
double-buffered host<->device page-migration engine, turning the device
pool into a cache over a much larger page store.
"""
from .migration import (HostPageStore, MigrationEngine, MigrationTicket,
                        Residency, TieredPageAllocator)
from .page_allocator import (PageAllocator, PageExhausted, copy_page,
                             gather_pages, write_pages)

__all__ = ["PageAllocator", "PageExhausted", "copy_page", "write_pages",
           "gather_pages", "Residency", "TieredPageAllocator",
           "HostPageStore", "MigrationEngine", "MigrationTicket"]
