"""Device-memory management: the paged KV allocator lives here.

`page_allocator` is deliberately decode-agnostic — it hands out integer
page ids against a fixed-size device pool and tracks refcounts, so the
decode engine, prefix cache, and (later) training remat/offload can all
share one allocator discipline.
"""
from .page_allocator import (PageAllocator, PageExhausted, copy_page,
                             write_pages)

__all__ = ["PageAllocator", "PageExhausted", "copy_page", "write_pages"]
