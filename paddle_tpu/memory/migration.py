"""Async host<->device page migration: the two-tier KV memory manager.

`memory.page_allocator` made device pages a first-class resource; this
module makes HBM a *cache* over a much larger host-RAM page store
(PAPER.md L1: `paddle/fluid/memory/` spills beyond device memory via
MmapAllocator — same idea, paged). Three pieces:

* :class:`TieredPageAllocator` grows :class:`PageAllocator` into a
  two-tier manager. Device pages keep the inherited id space
  (non-negative ints); spilled page *contents* live in a bounded host
  tier addressed by negative **handles**, each with a residency state —
  ``HOST`` (payload landed, refetchable), ``IN_FLIGHT`` (a migration is
  moving it in either direction). Device-resident pages are simply
  allocator pages (residency ``DEVICE``). Pure bookkeeping behind one
  leaf lock, like the base class — it never touches device memory.
* :class:`HostPageStore` owns the payload bytes: per-pool-leaf arenas
  preallocated at construction (the pinned-buffer discipline — spills
  copy into a fixed arena slot, never allocate per page), indexed by
  the same handles.
* :class:`MigrationEngine` is the async transport: a background worker
  with per-direction queues and a bounded in-flight window that
  double-buffers transfers — it *dispatches* up to ``window`` device
  copies (``copy_to_host_async`` / ``jax.device_put``, both async under
  jax's dispatch model) before *retiring* the oldest (the blocking
  host-side copy into / out of the arena), so transfer k+1 overlaps the
  host copy of transfer k. Spills are drained before refetches, which
  (with submission order: a handle is always spilled before it can be
  refetched) makes a refetch of an in-flight spill naturally wait for
  the payload to land.

The engine is deliberately consumer-agnostic: callers hand it opaque
device chunks / handle lists plus an ``on_done`` callback, so the same
transport serves KV tiering and — via :func:`serialize_pages` /
:func:`deserialize_pages` below — the cross-process prefill/decode KV
handoff (docs/serving.md). Failure never raises out of the
worker — the callback reports it and the *caller* decides (the decode
engine degrades to a re-prefill, which is always correct).

Chaos: every migration batch passes the ``page.migrate`` site before
its device work. A ``Fail`` kills that batch (callback with the error);
``Hang@s`` sleeps the worker — both stall or fail only streams waiting
on those specific pages, because no scheduler thread ever blocks on
this worker.

Observability: the ``paddle_tpu_kv_tier_*`` families (resident pages
per tier, spill/refetch counters, per-direction migration latency,
in-flight depth) plus ``page.spill`` / ``page.refetch`` tracez spans.
"""
from __future__ import annotations

import threading
import time
import zlib
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .page_allocator import (PageAllocator, _ring_record,  # noqa: F401
                             gather_pages)

__all__ = ["Residency", "TieredPageAllocator", "HostPageStore",
           "MigrationEngine", "MigrationTicket", "gather_pages",
           "serialize_pages", "deserialize_pages", "tier_metrics"]


class Residency:
    """Residency states for a logical KV page in the two-tier manager."""
    DEVICE = "DEVICE"
    HOST = "HOST"
    IN_FLIGHT = "IN_FLIGHT"


_METRICS = None


def tier_metrics():
    """Register (idempotently) and return the paddle_tpu_kv_tier_* family."""
    global _METRICS
    if _METRICS is None:
        from ..observability import counter, gauge, histogram
        _METRICS = {
            "resident": gauge(
                "paddle_tpu_kv_tier_resident_pages",
                "KV pages resident per tier: device = allocator pages in "
                "use, host = spilled page payloads held in the host "
                "arena (in-flight pages count toward host)",
                labelnames=("tier",)),
            "spills": counter(
                "paddle_tpu_kv_tier_spills_total",
                "KV pages spilled device -> host by the migration "
                "engine"),
            "refetches": counter(
                "paddle_tpu_kv_tier_refetches_total",
                "KV pages refetched host -> device by the migration "
                "engine"),
            "migration_seconds": histogram(
                "paddle_tpu_kv_tier_migration_seconds",
                "Wall time of one migration batch by direction "
                "(out = device->host spill, in = host->device refetch)",
                labelnames=("direction",)),
            "inflight": gauge(
                "paddle_tpu_kv_tier_inflight",
                "Migration jobs submitted but not yet retired "
                "(queued + dispatched)"),
        }
    return _METRICS


class TieredPageAllocator(PageAllocator):
    """`PageAllocator` plus a bounded host tier of spilled page contents.

    Host **handles** are negative ints (``-(slot + 1)`` for arena slot
    ``slot``) so they can never collide with device page ids; callers
    that store "a page or its spilled handle" branch on the sign. The
    handle lifecycle is
    ``spill_begin (IN_FLIGHT) -> spill_commit (HOST) ->
    refetch_begin (IN_FLIGHT) -> host_drop`` with ``host_drop`` also
    serving every abort path. All transitions are O(1) bookkeeping
    under the inherited leaf lock."""

    def __init__(self, num_pages: int, *, host_pages: int,
                 reserve_null: bool = True, label: str = "kv"):
        super().__init__(num_pages, reserve_null=reserve_null,
                         label=label)
        if host_pages < 1:
            raise ValueError(f"host tier needs >= 1 page, got {host_pages}")
        self.host_pages = int(host_pages)
        self._host_free: List[int] = list(range(self.host_pages))
        self._residency: Dict[int, str] = {}     # handle -> Residency
        self._spilled = 0
        self._refetched = 0

    @staticmethod
    def handle_slot(handle: int) -> int:
        """Arena slot index a (negative) host handle addresses."""
        return -int(handle) - 1

    # ---------------------------------------------------------- spills

    def spill_begin(self, n: int) -> List[int]:
        """Reserve up to `n` host slots; returns their handles at
        residency IN_FLIGHT (the payload is still moving). Returns
        fewer — possibly none — when the host tier is near capacity;
        the caller falls back to destructive eviction for the rest."""
        with self._lock:
            take = min(max(n, 0), len(self._host_free))
            handles = [-(self._host_free.pop() + 1) for _ in range(take)]
            for h in handles:
                self._residency[h] = Residency.IN_FLIGHT
            return handles

    def spill_commit(self, handle: int) -> None:
        """The payload landed in the host arena: IN_FLIGHT -> HOST."""
        with self._lock:
            if self._residency.get(handle) != Residency.IN_FLIGHT:
                raise ValueError(f"spill_commit of handle {handle} not "
                                 f"in flight")
            self._residency[handle] = Residency.HOST
            self._spilled += 1
            host_free = len(self._host_free)
        # ring event after the lock, same discipline as the base class
        _ring_record("spill", self.label, ("tier", handle), 1, host_free)

    # -------------------------------------------------------- refetches

    def refetch_begin(self, handle: int) -> None:
        """Pin a HOST handle for refetch: HOST -> IN_FLIGHT (a pinned
        handle can neither be refetched again nor dropped under it)."""
        with self._lock:
            if self._residency.get(handle) != Residency.HOST:
                raise ValueError(f"refetch_begin of handle {handle} not "
                                 f"host-resident")
            self._residency[handle] = Residency.IN_FLIGHT

    def refetch_commit(self, handle: int) -> None:
        """The payload is back on device: count it and free the slot."""
        with self._lock:
            self._refetched += 1
        self.host_drop(handle)
        with self._lock:
            host_free = len(self._host_free)
        _ring_record("refetch", self.label, ("tier", handle), 1,
                     host_free)

    def host_drop(self, handle: int) -> None:
        """Free a host slot (restore landed, spill failed, refetch
        failed, or the entry was evicted). Idempotent."""
        with self._lock:
            if self._residency.pop(handle, None) is not None:
                self._host_free.append(self.handle_slot(handle))

    def residency(self, handle: int) -> Optional[str]:
        """Residency of a host handle (None when unknown/dropped);
        non-negative ids are device pages and report DEVICE while
        allocated."""
        if handle >= 0:
            return Residency.DEVICE if self.refcount(handle) else None
        with self._lock:
            return self._residency.get(handle)

    def host_used(self) -> int:
        with self._lock:
            return self.host_pages - len(self._host_free)

    def stats(self) -> Dict:
        st = super().stats()
        with self._lock:
            st["host_pages_total"] = self.host_pages
            st["host_pages_used"] = self.host_pages - len(self._host_free)
            st["host_inflight"] = sum(
                1 for r in self._residency.values()
                if r == Residency.IN_FLIGHT)
            st["spilled_total"] = self._spilled
            st["refetched_total"] = self._refetched
        return st


class HostPageStore:
    """Preallocated host arenas for spilled page payloads.

    ``template`` is a pytree whose leaves carry the *pool* shape
    ``[..., P, page_tokens, ...]`` (page axis 1) — concrete arrays or
    ShapeDtypeStructs both work; only ``.shape``/``.dtype`` are read.
    One numpy arena of shape ``(capacity, *leaf_shape_without_P)`` is
    allocated per leaf up front, so a spill is a bounded copy into a
    fixed slot (the pinned-buffer discipline) and the store's footprint
    is visible at construction, never a surprise mid-serve."""

    def __init__(self, template, capacity: int):
        import jax

        self.capacity = int(capacity)
        leaves = jax.tree_util.tree_flatten(template)[0]
        self._treedef = jax.tree_util.tree_structure(template)
        self._arenas = []
        for leaf in leaves:
            shape = tuple(leaf.shape)
            page_shape = shape[:1] + shape[2:]   # drop the page axis
            self._arenas.append(np.zeros((self.capacity,) + page_shape,
                                         dtype=np.dtype(leaf.dtype)))

    def nbytes(self) -> int:
        return sum(a.nbytes for a in self._arenas)

    def put(self, slot: int, chunk_leaves: Sequence[np.ndarray],
            index: int) -> None:
        """Store page `index` of a gathered chunk (leaf list, each
        ``[..., W, page_tokens, ...]``) into arena slot `slot`."""
        for arena, leaf in zip(self._arenas, chunk_leaves):
            arena[slot] = leaf[:, index]

    def assemble(self, slots: Sequence[int], rung: int):
        """Build page rows for `slots`, zero-padded to `rung` pages —
        the host-side half of a refetch, shaped for the AOT'd
        `write_pages` executable. Returns a pytree mirroring the
        template."""
        import jax

        rows = []
        for arena in self._arenas:
            out = np.zeros((arena.shape[1], int(rung)) + arena.shape[2:],
                           dtype=arena.dtype)
            for j, slot in enumerate(slots):
                out[:, j] = arena[slot]
            rows.append(out)
        return jax.tree_util.tree_unflatten(self._treedef, rows)


# ---------------------------------------------------- wire serialization
#
# The prefill/decode KV handoff ships gathered page chunks between
# processes over the serve wire protocol. Same leaf discipline as
# `HostPageStore`: a chunk is a pytree of ``[..., W, page_tokens, ...]``
# leaves (page axis 1), possibly rung-padded past the real page count.
# Serialization slices each leaf to the real count, records per-leaf
# dtype/shape metadata plus a per-page crc32 chained across leaves, and
# re-views int8 leaves as uint8 (the wire tensor codec carries no int8
# code); deserialization restores the dtypes and refuses any structural
# or checksum mismatch — a torn or mis-routed handoff must degrade to a
# re-prefill, never land garbage in a pool.

def _page_crc(leaves: Sequence[np.ndarray], index: int) -> int:
    c = 0
    for a in leaves:
        c = zlib.crc32(np.ascontiguousarray(a[:, index]).tobytes(), c)
    return c


def serialize_pages(chunk, count: int) -> Tuple[List[np.ndarray], Dict]:
    """Flatten a gathered page chunk into wire-safe arrays + metadata.

    Returns ``(arrays, meta)``: one contiguous numpy array per leaf,
    sliced to `count` real pages (int8 leaves ride as a uint8 view),
    and ``meta`` = ``{"n_pages", "leaves": [{"dtype", "shape"}, ...],
    "crcs": [per-page crc32]}``."""
    import jax

    count = int(count)
    leaves = [np.ascontiguousarray(np.asarray(x)[:, :count])
              for x in jax.tree_util.tree_flatten(chunk)[0]]
    arrays, leaf_meta = [], []
    for a in leaves:
        leaf_meta.append({"dtype": str(a.dtype), "shape": list(a.shape)})
        arrays.append(a.view(np.uint8) if a.dtype == np.int8 else a)
    meta = {"n_pages": count,
            "leaves": leaf_meta,
            "crcs": [_page_crc(leaves, j) for j in range(count)]}
    return arrays, meta


def deserialize_pages(arrays: Sequence[np.ndarray],
                      meta: Dict) -> List[np.ndarray]:
    """Inverse of :func:`serialize_pages`: restore leaf dtypes from the
    metadata and validate every page's crc32 chain. Returns the per-leaf
    arrays (``[..., n_pages, ...]``, page axis 1). Raises ``ValueError``
    on any structural or checksum mismatch."""
    leaf_meta = meta.get("leaves") or []
    crcs = list(meta.get("crcs") or [])
    n = int(meta.get("n_pages") or 0)
    if len(arrays) != len(leaf_meta):
        raise ValueError(
            f"kv payload structure mismatch: {len(arrays)} arrays for "
            f"{len(leaf_meta)} leaf descriptors")
    if len(crcs) != n:
        raise ValueError(
            f"kv payload structure mismatch: {len(crcs)} checksums for "
            f"{n} pages")
    leaves = []
    for i, (a, lm) in enumerate(zip(arrays, leaf_meta)):
        dt = np.dtype(lm.get("dtype", ""))
        shape = tuple(int(s) for s in lm.get("shape") or ())
        a = np.asarray(a)
        if dt == np.int8 and a.dtype == np.uint8:
            a = a.view(np.int8)
        if a.dtype != dt or a.shape != shape:
            raise ValueError(
                f"kv payload structure mismatch: leaf {i} is "
                f"{a.dtype}{list(a.shape)}, descriptor says "
                f"{dt}{list(shape)}")
        if len(shape) < 2 or shape[1] != n:
            raise ValueError(
                f"kv payload structure mismatch: leaf {i} holds "
                f"{shape[1] if len(shape) > 1 else 0} pages, "
                f"metadata says {n}")
        leaves.append(a)
    for j in range(n):
        if _page_crc(leaves, j) != int(crcs[j]):
            raise ValueError(f"kv page {j} checksum mismatch")
    return leaves


class MigrationTicket:
    """Async handle on one migration batch. ``poll()`` is non-blocking
    ("pending" | "ok" | "failed"); ``rows`` carries the device-resident
    page rows after a successful refetch."""

    __slots__ = ("direction", "handles", "count", "rung", "chunk",
                 "rows", "error", "duration_s", "_done", "_on_done")

    def __init__(self, direction: str, handles: List[int], count: int,
                 rung: int = 0, chunk=None,
                 on_done: Optional[Callable] = None):
        self.direction = direction        # "out" (spill) | "in" (refetch)
        self.handles = handles
        self.count = count
        self.rung = rung
        self.chunk = chunk                # device chunk to land (spill)
        self.rows = None                  # device rows to write (refetch)
        self.error: Optional[BaseException] = None
        self.duration_s = 0.0
        self._done = threading.Event()
        self._on_done = on_done

    def poll(self) -> str:
        if not self._done.is_set():
            return "pending"
        return "failed" if self.error is not None else "ok"

    def wait(self, timeout: Optional[float] = None) -> str:
        self._done.wait(timeout)
        return self.poll()

    def _finish(self, error: Optional[BaseException] = None):
        self.error = error
        self.chunk = None                 # drop the device reference
        self._done.set()
        if self._on_done is not None:
            try:
                self._on_done(self)
            except Exception:             # pragma: no cover - callback bug
                pass                      # must never kill the worker


class MigrationEngine:
    """Background double-buffered host<->device page transport.

    One daemon worker thread; per-direction submission queues (spills
    drain first); an in-flight window of `window` dispatched-but-
    unretired transfers. Submission never blocks — the decode scheduler
    hands work off and keeps stepping, so a chaos-hung migration stalls
    only the streams waiting on those pages."""

    def __init__(self, store: HostPageStore, *, window: int = 2,
                 name: str = "kv-migrate",
                 wake: Optional[Callable[[], None]] = None):
        if window < 1:
            raise ValueError(f"in-flight window must be >= 1, got {window}")
        self._store = store
        self._window = int(window)
        self._wake = wake                 # poked after every retirement
        self._m = tier_metrics()
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._out_q: deque = deque()      # spills (device -> host)
        self._in_q: deque = deque()       # refetches (host -> device)
        self._inflight = 0                # submitted - retired
        self._spill_s: deque = deque(maxlen=256)
        self._refetch_s: deque = deque(maxlen=256)
        self._stop = False
        self._thread = threading.Thread(
            target=self._loop, name=name, daemon=True)
        self._thread.start()

    # ------------------------------------------------------ submission

    def spill(self, chunk, handles: List[int], count: int,
              on_done: Optional[Callable] = None) -> MigrationTicket:
        """Queue a device->host spill. `chunk` is an already-gathered
        device pytree of `count` pages (plus rung padding); page j
        lands in `handles[j]`'s arena slot. The gather copied the
        content, so the caller releases the device pages immediately —
        the ticket only tracks when the host copy is durable."""
        t = MigrationTicket("out", list(handles), int(count),
                            chunk=chunk, on_done=on_done)
        self._submit(self._out_q, t)
        return t

    def refetch(self, handles: List[int], rung: int,
                on_done: Optional[Callable] = None) -> MigrationTicket:
        """Queue a host->device refetch of `handles`, padded to `rung`
        pages. On success ``ticket.rows`` holds the device page rows,
        shaped for the AOT'd `write_pages` executable."""
        t = MigrationTicket("in", list(handles), len(handles),
                            rung=int(rung), on_done=on_done)
        self._submit(self._in_q, t)
        return t

    def _submit(self, q: deque, t: MigrationTicket):
        with self._cond:
            if self._stop:
                raise RuntimeError("migration engine stopped")
            q.append(t)
            self._inflight += 1
            self._m["inflight"].set(self._inflight)
            self._cond.notify_all()

    # ---------------------------------------------------------- worker

    def _next(self, block: bool) -> Optional[MigrationTicket]:
        with self._cond:
            while True:
                if self._out_q:            # spills first: a refetch of an
                    return self._out_q.popleft()   # in-flight spill must
                if self._in_q:                     # see its payload land
                    return self._in_q.popleft()
                if self._stop or not block:
                    return None
                self._cond.wait(timeout=0.1)

    def _loop(self):
        from ..testing import chaos

        inflight: deque = deque()          # (ticket, t0) dispatched
        while True:
            t = self._next(block=not inflight)
            if t is None and not inflight:
                if self._stop:
                    return
                continue
            if t is not None:
                t0 = time.perf_counter()
                try:
                    chaos.maybe_fail(
                        "page.migrate",
                        detail=f"{t.direction}:{t.count}")
                    self._dispatch(t)
                except BaseException as exc:
                    self._retire_err(t, exc, t0)
                else:
                    inflight.append((t, t0))
            # retire the oldest once the window is full, or when the
            # queues are momentarily empty (nothing to overlap with)
            while inflight and (len(inflight) >= self._window
                                or not self._queued()):
                self._retire(*inflight.popleft())

    def _queued(self) -> bool:
        with self._lock:
            return bool(self._out_q or self._in_q)

    def _dispatch(self, t: MigrationTicket):
        """Start the device half of a transfer (async under jax)."""
        import jax

        if t.direction == "out":
            for leaf in jax.tree_util.tree_flatten(t.chunk)[0]:
                start = getattr(leaf, "copy_to_host_async", None)
                if start is not None:
                    start()
        else:
            rows = self._store.assemble(
                [TieredPageAllocator.handle_slot(h) for h in t.handles],
                t.rung)
            t.rows = jax.device_put(rows)

    def _retire(self, t: MigrationTicket, t0: float):
        """Block on the transfer, land payloads, finish the ticket."""
        import jax

        try:
            if t.direction == "out":
                leaves = [np.asarray(x) for x in
                          jax.tree_util.tree_flatten(t.chunk)[0]]
                for j, h in enumerate(t.handles):
                    self._store.put(
                        TieredPageAllocator.handle_slot(h), leaves, j)
                self._m["spills"].inc(t.count)
            else:
                jax.block_until_ready(t.rows)
                self._m["refetches"].inc(t.count)
        except BaseException as exc:
            self._retire_err(t, exc, t0)
            return
        t.duration_s = time.perf_counter() - t0
        from ..observability.tracez import RING as _RING

        span = "page.spill" if t.direction == "out" else "page.refetch"
        _RING.complete(span, t0, time.perf_counter(),
                       {"pages": t.count})
        self._m["migration_seconds"].labels(
            direction=t.direction).observe(t.duration_s)
        (self._spill_s if t.direction == "out"
         else self._refetch_s).append(t.duration_s)
        self._done(t, None)

    def _retire_err(self, t: MigrationTicket, exc: BaseException,
                    t0: float):
        t.duration_s = time.perf_counter() - t0
        self._done(t, exc)

    def _done(self, t: MigrationTicket, exc: Optional[BaseException]):
        with self._cond:
            self._inflight -= 1
            self._m["inflight"].set(self._inflight)
        t._finish(exc)
        if self._wake is not None:
            try:
                self._wake()
            except Exception:              # pragma: no cover
                pass

    # ------------------------------------------------------------ misc

    def stats(self) -> Dict:
        with self._lock:
            spill_s = sorted(self._spill_s)
            refetch_s = sorted(self._refetch_s)
            inflight = self._inflight
        def _p(vals, q):
            if not vals:
                return 0.0
            return vals[min(len(vals) - 1, int(q * len(vals)))]
        return {
            "window": self._window,
            "inflight": inflight,
            "host_arena_bytes": self._store.nbytes(),
            "spill_p50_ms": round(_p(spill_s, 0.50) * 1e3, 3),
            "spill_p95_ms": round(_p(spill_s, 0.95) * 1e3, 3),
            "refetch_p50_ms": round(_p(refetch_s, 0.50) * 1e3, 3),
            "refetch_p95_ms": round(_p(refetch_s, 0.95) * 1e3, 3),
        }

    def stop(self, timeout: float = 30.0):
        """Drain queued work and join the worker. Idempotent."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)
