"""Refcounted page allocator over a fixed device-resident pool.

The framework's first real device-memory manager (PAPER.md L1:
`paddle/fluid/memory/` keeps a strategy-selectable allocator stack for
exactly this job). The allocator itself never touches device memory —
it hands out integer *page ids* into a pool whose storage the caller
owns (for decode: `[layers, pages, page_tokens, heads, head_dim]` K/V
arrays). That keeps it decode-agnostic: any subsystem that wants paged
device buffers (KV caches today, remat/offload spill later) can reuse
the same alloc/retain/release/refcount discipline.

Conventions:

  * page 0 is reserved as the **null page** when ``reserve_null`` —
    a scratch sink for block-table padding and padded-batch writes, so
    garbage writes land somewhere harmless instead of clobbering live
    data. It is never allocated and never freed.
  * every page has a refcount. `alloc` returns pages at refcount 1;
    `retain` increments (copy-on-write sharing: a prefix cache maps the
    same page into many sequences); `release` decrements and returns
    the page to the free list at zero.
  * `alloc` raises :class:`PageExhausted` (typed, catchable) instead of
    over-committing — callers turn that into backpressure.
  * thread-safe behind one leaf lock; no callback, device work, or I/O
    ever runs under it (tsan-lite TPR102 clean by construction).

`write_pages` / `copy_page` are the pure-jax pool ops that pair with
the bookkeeping: both are shape-stable (jit/AOT-cacheable) updates over
a pool whose axis 1 is the page axis.
"""
from __future__ import annotations

import threading
from typing import Dict, List

import jax
import jax.numpy as jnp


class PageExhausted(RuntimeError):
    """Raised by `PageAllocator.alloc` when the free list cannot cover
    the request — the caller's cue for eviction or backpressure."""


class PageAllocator:
    """Bookkeeping for a pool of `num_pages` fixed-size device pages."""

    def __init__(self, num_pages: int, *, reserve_null: bool = True):
        if num_pages < (2 if reserve_null else 1):
            raise ValueError(f"page pool needs >= 2 pages, got {num_pages}")
        self.num_pages = int(num_pages)
        self.null_page = 0 if reserve_null else -1
        self._lock = threading.Lock()
        first = 1 if reserve_null else 0
        self._free: List[int] = list(range(first, self.num_pages))
        self._refs: Dict[int, int] = {}
        self._allocs = 0
        self._failures = 0
        self._high_water = 0

    # ------------------------------------------------------------- ops

    def alloc(self, n: int = 1) -> List[int]:
        """Hand out `n` pages at refcount 1 (lowest ids first — keeps
        the pool dense so fragmentation stays measurable and low)."""
        if n <= 0:
            return []
        with self._lock:
            if n > len(self._free):
                self._failures += 1
                raise PageExhausted(
                    f"requested {n} pages, {len(self._free)} free "
                    f"of {self.num_pages}")
            self._free.sort()
            pages = self._free[:n]
            del self._free[:n]
            for p in pages:
                self._refs[p] = 1
            self._allocs += n
            self._high_water = max(self._high_water, len(self._refs))
            return pages

    def retain(self, page: int) -> int:
        """Add a reference to an allocated page (sharing); returns the
        new refcount."""
        with self._lock:
            if page not in self._refs:
                raise ValueError(f"retain of unallocated page {page}")
            self._refs[page] += 1
            return self._refs[page]

    def release(self, page: int) -> int:
        """Drop a reference; the page rejoins the free list at zero.
        Returns the remaining refcount."""
        with self._lock:
            refs = self._refs.get(page)
            if refs is None:
                raise ValueError(f"release of unallocated page {page}")
            if refs > 1:
                self._refs[page] = refs - 1
                return refs - 1
            del self._refs[page]
            self._free.append(page)
            return 0

    def release_range(self, ids, from_idx: int) -> int:
        """Drop one reference on every page in ``ids[from_idx:]`` under a
        single lock acquisition — the speculative-decode rollback path,
        which strands a tail of a block table past the last accepted
        token. Returns the number of references dropped. Any unallocated
        id raises ValueError before *any* refcount changes, so a bad
        call never half-applies."""
        tail = [int(p) for p in list(ids)[max(int(from_idx), 0):]]
        with self._lock:
            for p in tail:
                if p not in self._refs:
                    raise ValueError(f"release of unallocated page {p}")
            for p in tail:
                refs = self._refs[p]
                if refs > 1:
                    self._refs[p] = refs - 1
                else:
                    del self._refs[p]
                    self._free.append(p)
        return len(tail)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    # ----------------------------------------------------------- stats

    def stats(self) -> Dict:
        """Occupancy + fragmentation snapshot (all counts exclude the
        reserved null page). Fragmentation is 1 − largest contiguous
        free run / free pages: 0.0 when the free space is one block
        (or empty), approaching 1.0 as it shatters."""
        with self._lock:
            free = sorted(self._free)
            used = len(self._refs)
            shared = sum(1 for r in self._refs.values() if r > 1)
            refs_total = sum(self._refs.values())
            allocs, failures = self._allocs, self._failures
            high = self._high_water
        longest = run = 0
        for i, p in enumerate(free):
            run = run + 1 if i and p == free[i - 1] + 1 else 1
            longest = max(longest, run)
        frag = 0.0 if not free else 1.0 - longest / len(free)
        return {
            "pages_total": self.num_pages - (1 if self.null_page == 0 else 0),
            "pages_free": len(free),
            "pages_used": used,
            "pages_shared": shared,
            "refs_total": refs_total,
            "fragmentation": round(frag, 4),
            "allocs_total": allocs,
            "alloc_failures_total": failures,
            "high_watermark": high,
        }


# ----------------------------------------------------------- pool ops

def write_pages(pool, rows, page_ids):
    """Scatter whole pages into the pool.

    pool      [..., P, page_tokens, ...]  (page axis = 1 on every leaf)
    rows      [..., W, page_tokens, ...]  page-shaped rows to write
    page_ids  [W] int32                   destination pages (traced ok)

    `pool` may be a bare array or a pytree (e.g. the int8 pool's
    ``(data, scale)`` pair from `quant.kv`); `rows` must mirror its
    structure. Duplicate destinations (e.g. several padding rows aimed
    at the null page) resolve arbitrarily — by convention only
    don't-care data is ever aimed at a duplicated id.
    """
    return jax.tree.map(lambda p, r: p.at[:, page_ids].set(r), pool, rows)


def copy_page(pool, src, dst):
    """Copy one page (copy-on-write): pool[:, dst] = pool[:, src] on
    every pool leaf. `src`/`dst` may be traced scalars, so one
    executable serves every (src, dst) pair."""
    return jax.tree.map(lambda p: p.at[:, dst].set(p[:, src]), pool)


def gather_pages(pool, page_ids):
    """Gather whole pages out of the pool into a fresh buffer — the
    shape-stable read twin of `write_pages`.

    pool      [..., P, page_tokens, ...]  (page axis = 1 on every leaf)
    page_ids  [W] int32                   source pages (traced ok)

    The result is an *independent* `[..., W, page_tokens, ...]` buffer
    per leaf, so the caller may release (and even donate) the pool right
    after dispatch — jax orders the in-flight read before any later
    donation. This is the spill-side primitive of host tiering: gather
    cold pages, hand the chunk to the migration engine, free the pages.
    """
    return jax.tree.map(lambda p: p[:, page_ids], pool)


__all__ = ["PageAllocator", "PageExhausted", "write_pages", "copy_page",
           "gather_pages"]


if __name__ == "__main__":  # pragma: no cover - smoke
    a = PageAllocator(8)
    pages = a.alloc(3)
    a.retain(pages[0])
    print(pages, a.stats())
    for p in pages:
        a.release(p)
    a.release(pages[0])
    print(jnp.asarray(0), a.stats())
