"""Refcounted page allocator over a fixed device-resident pool.

The framework's first real device-memory manager (PAPER.md L1:
`paddle/fluid/memory/` keeps a strategy-selectable allocator stack for
exactly this job). The allocator itself never touches device memory —
it hands out integer *page ids* into a pool whose storage the caller
owns (for decode: `[layers, pages, page_tokens, heads, head_dim]` K/V
arrays). That keeps it decode-agnostic: any subsystem that wants paged
device buffers (KV caches today, remat/offload spill later) can reuse
the same alloc/retain/release/refcount discipline.

Conventions:

  * page 0 is reserved as the **null page** when ``reserve_null`` —
    a scratch sink for block-table padding and padded-batch writes, so
    garbage writes land somewhere harmless instead of clobbering live
    data. It is never allocated and never freed.
  * every page has a refcount. `alloc` returns pages at refcount 1;
    `retain` increments (copy-on-write sharing: a prefix cache maps the
    same page into many sequences); `release` decrements and returns
    the page to the free list at zero.
  * `alloc` raises :class:`PageExhausted` (typed, catchable) instead of
    over-committing — callers turn that into backpressure. The error
    carries the pool label, the denied owner tag, and the
    requested/free counts so the resulting ``RESOURCE_EXHAUSTED``
    frame says *who* was denied *what*.
  * thread-safe behind one leaf lock; no callback, device work, or I/O
    ever runs under it (tsan-lite TPR102 clean by construction).

Owner attribution (observability/memz.py): every alloc/retain/release
accepts an optional lightweight ``owner`` tag — a small tuple such as
``("slot", req_id, tenant)``, ``("trie", node)``, ``("tier", handle)``,
``("draft", req_id)`` or ``("handoff", stream)`` — kept in a side table
under the same leaf lock. Rollups attribute each used page to its
**primary owner** (the first still-holding tagger), so the per-owner
page counts always sum to exactly ``pages_used`` even when a page is
shared between a slot and the prefix trie. Untagged calls fall into a
distinguished ``("untagged",)`` bucket and a mismatched release
degrades gracefully — attribution can never turn a correct refcount
operation into an error. Each operation also lands one event on the
bounded memz allocation ring (recorded *after* the leaf lock is
dropped, so no lock ever nests inside the allocator's).

The free list is kept sorted by insertion (`bisect.insort` on release)
rather than re-sorted on every alloc, so `alloc` stays O(n) in the
pages granted, not O(free · log free).

`write_pages` / `copy_page` are the pure-jax pool ops that pair with
the bookkeeping: both are shape-stable (jit/AOT-cacheable) updates over
a pool whose axis 1 is the page axis.
"""
from __future__ import annotations

import threading
from bisect import insort
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

#: Attribution bucket for alloc/retain/release calls with no owner tag.
UNTAGGED: Tuple[str, ...] = ("untagged",)


class PageExhausted(RuntimeError):
    """Raised by `PageAllocator.alloc` when the free list cannot cover
    the request — the caller's cue for eviction or backpressure.
    Attributes ``pool`` / ``owner`` / ``requested`` / ``free`` identify
    the denied pool, the requester's owner tag, and the shortfall."""

    def __init__(self, message: str, *, pool: str = "",
                 owner: Tuple = UNTAGGED, requested: int = 0,
                 free: int = 0):
        super().__init__(message)
        self.pool = pool
        self.owner = owner
        self.requested = requested
        self.free = free


def owner_str(owner) -> str:
    """Stable printable form of an owner tag (JSON-safe dict key)."""
    return ":".join(str(x) for x in owner)


_RING = None


def _ring_record(op: str, pool: str, owner, n: int, free: int) -> None:
    """Land one event on the memz allocation ring (lazily bound so
    `memory` never imports `observability` at module load). Called only
    outside the allocator lock."""
    global _RING
    ring = _RING
    if ring is None:
        from ..observability import memz as _memz
        ring = _RING = _memz.RING
    ring.record(op, pool, owner, n, free)


class PageAllocator:
    """Bookkeeping for a pool of `num_pages` fixed-size device pages."""

    def __init__(self, num_pages: int, *, reserve_null: bool = True,
                 label: str = "kv"):
        if num_pages < (2 if reserve_null else 1):
            raise ValueError(f"page pool needs >= 2 pages, got {num_pages}")
        self.num_pages = int(num_pages)
        self.null_page = 0 if reserve_null else -1
        self.label = str(label)
        self._lock = threading.Lock()
        first = 1 if reserve_null else 0
        # kept sorted ascending at all times: alloc slices the head,
        # release bisect-inserts — never a full sort on the hot path
        self._free: List[int] = list(range(first, self.num_pages))
        self._refs: Dict[int, int] = {}
        # page -> {owner tag -> refs held under that tag}; insertion
        # order makes the first surviving key the page's primary owner
        self._owners: Dict[int, Dict[Tuple, int]] = {}
        self._allocs = 0
        self._failures = 0
        self._high_water = 0

    # ------------------------------------------------- owner side table

    def _owner_add(self, page: int, owner: Tuple, n: int = 1) -> None:
        d = self._owners.get(page)
        if d is None:
            d = self._owners[page] = {}
        d[owner] = d.get(owner, 0) + n

    def _owner_drop(self, page: int, owner: Tuple) -> None:
        """Drop one owner ref for `page`: the given tag if it holds one,
        else the untagged bucket, else the newest holder — a mismatched
        tag degrades attribution, never correctness."""
        d = self._owners.get(page)
        if not d:
            return
        key = owner if owner in d else (
            UNTAGGED if UNTAGGED in d else next(reversed(d)))
        left = d[key] - 1
        if left > 0:
            d[key] = left
        else:
            del d[key]

    # ------------------------------------------------------------- ops

    def alloc(self, n: int = 1, owner: Optional[Tuple] = None) -> List[int]:
        """Hand out `n` pages at refcount 1 (lowest ids first — keeps
        the pool dense so fragmentation stays measurable and low),
        attributed to `owner` (or the untagged bucket)."""
        if n <= 0:
            return []
        tag = owner if owner is not None else UNTAGGED
        with self._lock:
            free = len(self._free)
            if n > free:
                self._failures += 1
                pages = None
            else:
                pages = self._free[:n]
                del self._free[:n]
                for p in pages:
                    self._refs[p] = 1
                    self._owners[p] = {tag: 1}
                self._allocs += n
                self._high_water = max(self._high_water, len(self._refs))
        if pages is None:
            _ring_record("exhausted", self.label, tag, n, free)
            raise PageExhausted(
                f"pool '{self.label}': requested {n} pages for "
                f"{owner_str(tag)}, {free} free of {self.num_pages}",
                pool=self.label, owner=tag, requested=n, free=free)
        _ring_record("alloc", self.label, tag, n, free - n)
        return pages

    def retain(self, page: int, owner: Optional[Tuple] = None) -> int:
        """Add a reference to an allocated page (sharing); returns the
        new refcount."""
        tag = owner if owner is not None else UNTAGGED
        with self._lock:
            if page not in self._refs:
                raise ValueError(f"retain of unallocated page {page}")
            self._refs[page] += 1
            refs = self._refs[page]
            self._owner_add(page, tag)
            free = len(self._free)
        _ring_record("retain", self.label, tag, 1, free)
        return refs

    def release(self, page: int, owner: Optional[Tuple] = None) -> int:
        """Drop a reference; the page rejoins the free list at zero.
        Returns the remaining refcount."""
        tag = owner if owner is not None else UNTAGGED
        with self._lock:
            refs = self._refs.get(page)
            if refs is None:
                raise ValueError(f"release of unallocated page {page}")
            if refs > 1:
                self._refs[page] = refs - 1
                self._owner_drop(page, tag)
                left = refs - 1
            else:
                del self._refs[page]
                self._owners.pop(page, None)
                insort(self._free, page)
                left = 0
            free = len(self._free)
        _ring_record("release", self.label, tag, 1, free)
        return left

    def release_range(self, ids, from_idx: int,
                      owner: Optional[Tuple] = None) -> int:
        """Drop one reference on every page in ``ids[from_idx:]`` under a
        single lock acquisition — the speculative-decode rollback path,
        which strands a tail of a block table past the last accepted
        token. Returns the number of references dropped. Any unallocated
        id raises ValueError before *any* refcount changes, so a bad
        call never half-applies."""
        tag = owner if owner is not None else UNTAGGED
        tail = [int(p) for p in list(ids)[max(int(from_idx), 0):]]
        with self._lock:
            for p in tail:
                if p not in self._refs:
                    raise ValueError(f"release of unallocated page {p}")
            for p in tail:
                refs = self._refs[p]
                if refs > 1:
                    self._refs[p] = refs - 1
                    self._owner_drop(p, tag)
                else:
                    del self._refs[p]
                    self._owners.pop(p, None)
                    insort(self._free, p)
            free = len(self._free)
        if tail:
            _ring_record("release", self.label, tag, len(tail), free)
        return len(tail)

    def retag(self, page: int, old: Tuple, new: Tuple) -> None:
        """Move one owner ref of `page` from tag `old` to tag `new`
        without touching the refcount — used when a reference changes
        hands (e.g. a tier refetch lands and the trie becomes the
        holder). No-op on an unallocated page."""
        with self._lock:
            if page not in self._refs:
                return
            self._owner_drop(page, old)
            self._owner_add(page, new)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._refs.get(page, 0)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    # ----------------------------------------------------------- stats

    def owner_rollups(self) -> Tuple[Dict, Dict, Dict]:
        """(by_owner, by_kind, by_tenant) page counts under primary-owner
        attribution: each used page counts once, toward the first owner
        tag still holding it — so every rollup sums to ``pages_used``
        exactly. Tenants come from ``("slot", req, tenant)`` tags; pages
        not held by any slot count toward tenant ``"-"``."""
        by_owner: Dict[Tuple, int] = {}
        by_kind: Dict[str, int] = {}
        by_tenant: Dict[str, int] = {}
        with self._lock:
            primaries = [next(iter(d)) for d in self._owners.values() if d]
        for owner in primaries:
            by_owner[owner] = by_owner.get(owner, 0) + 1
            kind = str(owner[0])
            by_kind[kind] = by_kind.get(kind, 0) + 1
            tenant = str(owner[2]) if kind == "slot" and len(owner) > 2 \
                else "-"
            by_tenant[tenant] = by_tenant.get(tenant, 0) + 1
        return by_owner, by_kind, by_tenant

    def owned_pages(self) -> List[Tuple[int, Tuple, int]]:
        """Snapshot of ``(page, primary_owner, refcount)`` for every
        allocated page — the memz ghost-page audit's raw material."""
        with self._lock:
            return [(p, next(iter(self._owners.get(p) or [UNTAGGED])),
                     r) for p, r in self._refs.items()]

    def stats(self) -> Dict:
        """Occupancy + fragmentation snapshot (all counts exclude the
        reserved null page). Fragmentation is 1 − largest contiguous
        free run / free pages: 0.0 when the free space is one block
        (or empty), approaching 1.0 as it shatters. ``owners`` /
        ``owner_kinds`` / ``tenants`` are the primary-owner page
        rollups (each sums to ``pages_used``)."""
        with self._lock:
            free = list(self._free)        # already sorted ascending
            used = len(self._refs)
            shared = sum(1 for r in self._refs.values() if r > 1)
            refs_total = sum(self._refs.values())
            allocs, failures = self._allocs, self._failures
            high = self._high_water
        longest = run = 0
        for i, p in enumerate(free):
            run = run + 1 if i and p == free[i - 1] + 1 else 1
            longest = max(longest, run)
        frag = 0.0 if not free else 1.0 - longest / len(free)
        by_owner, by_kind, by_tenant = self.owner_rollups()
        return {
            "pages_total": self.num_pages - (1 if self.null_page == 0 else 0),
            "pages_free": len(free),
            "pages_used": used,
            "pages_shared": shared,
            "refs_total": refs_total,
            "fragmentation": round(frag, 4),
            "allocs_total": allocs,
            "alloc_failures_total": failures,
            "high_watermark": high,
            "owners": {owner_str(o): c for o, c in sorted(
                by_owner.items(), key=lambda kv: -kv[1])},
            "owner_kinds": by_kind,
            "tenants": by_tenant,
        }

    def fragmentation_map(self) -> List[List[int]]:
        """Free-space layout as ``[start, length]`` runs over the sorted
        free list — the OOM forensic dump's picture of *where* the holes
        are, not just how many."""
        with self._lock:
            free = list(self._free)
        runs: List[List[int]] = []
        for p in free:
            if runs and p == runs[-1][0] + runs[-1][1]:
                runs[-1][1] += 1
            else:
                runs.append([p, 1])
        return runs


# ----------------------------------------------------------- pool ops

def write_pages(pool, rows, page_ids):
    """Scatter whole pages into the pool.

    pool      [..., P, page_tokens, ...]  (page axis = 1 on every leaf)
    rows      [..., W, page_tokens, ...]  page-shaped rows to write
    page_ids  [W] int32                   destination pages (traced ok)

    `pool` may be a bare array or a pytree (e.g. the int8 pool's
    ``(data, scale)`` pair from `quant.kv`); `rows` must mirror its
    structure. Duplicate destinations (e.g. several padding rows aimed
    at the null page) resolve arbitrarily — by convention only
    don't-care data is ever aimed at a duplicated id.
    """
    return jax.tree.map(lambda p, r: p.at[:, page_ids].set(r), pool, rows)


def copy_page(pool, src, dst):
    """Copy one page (copy-on-write): pool[:, dst] = pool[:, src] on
    every pool leaf. `src`/`dst` may be traced scalars, so one
    executable serves every (src, dst) pair."""
    return jax.tree.map(lambda p: p.at[:, dst].set(p[:, src]), pool)


def gather_pages(pool, page_ids):
    """Gather whole pages out of the pool into a fresh buffer — the
    shape-stable read twin of `write_pages`.

    pool      [..., P, page_tokens, ...]  (page axis = 1 on every leaf)
    page_ids  [W] int32                   source pages (traced ok)

    The result is an *independent* `[..., W, page_tokens, ...]` buffer
    per leaf, so the caller may release (and even donate) the pool right
    after dispatch — jax orders the in-flight read before any later
    donation. This is the spill-side primitive of host tiering: gather
    cold pages, hand the chunk to the migration engine, free the pages.
    """
    return jax.tree.map(lambda p: p[:, page_ids], pool)


__all__ = ["PageAllocator", "PageExhausted", "UNTAGGED", "owner_str",
           "write_pages", "copy_page", "gather_pages"]


if __name__ == "__main__":  # pragma: no cover - smoke
    a = PageAllocator(8)
    pages = a.alloc(3, owner=("slot", "r0", "tenant-a"))
    a.retain(pages[0], owner=("trie", "n0"))
    print(pages, a.stats())
    for p in pages:
        a.release(p, owner=("slot", "r0", "tenant-a"))
    a.release(pages[0], owner=("trie", "n0"))
    print(jnp.asarray(0), a.stats())
