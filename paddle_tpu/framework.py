"""Framework-level glue: Parameter, ParamAttr, save/load, functional bridge.

Reference analogs:
  * ParamBase / ParamAttr — python/paddle/fluid/framework.py, param_attr.py
  * paddle.save/paddle.load — fluid/dygraph/checkpoint.py:56,128 (pickle of
    state_dict); the sharded/distributed variant lives in io/checkpoint.py
    (orbax-style), this is the single-process path.
  * functional_call — no reference analog: it is the TPU-native bridge that
    turns a mutable Layer tree into a pure params->outputs function so the
    hot path can be jax.jit + jax.grad instead of an op-at-a-time tape.
"""
from __future__ import annotations

import contextlib
import os
import pickle
from typing import Any, Dict

import jax
import numpy as np

from .core import dtype as dtype_mod
from .core.tensor import Tensor, no_grad


class Parameter(Tensor):
    """Trainable tensor (ParamBase analog): stop_gradient=False by default."""

    def __init__(self, data, dtype=None, name=None, trainable=True):
        super().__init__(data, dtype=dtype, stop_gradient=not trainable,
                         name=name or _unique_param_name(), persistable=True)
        self.trainable = trainable

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


_param_counter = [0]


def _unique_param_name():
    _param_counter[0] += 1
    return f"param_{_param_counter[0]}"


class ParamAttr:
    """Parameter attribute bundle (reference: python/paddle/fluid/param_attr.py).

    Carries name / initializer / learning-rate scale / regularizer /
    trainable — consumed by Layer.create_parameter.
    """

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        if callable(attr):  # bare initializer
            return ParamAttr(initializer=attr)
        raise TypeError(f"Cannot convert {attr!r} to ParamAttr")


# ---------------------------------------------------------------------------
# save / load
# ---------------------------------------------------------------------------

def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_saveable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, cipher_key=None):
    """paddle.save parity: pickle a (possibly nested) state dict.

    Tensors are converted to host numpy arrays (device→host transfer).
    cipher_key (32 bytes) encrypts the file (io/crypto — the reference's
    model-encryption capability, framework/io/crypto/cipher.cc)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    if cipher_key is None:      # streaming path: no full-blob buffering
        with open(path, "wb") as f:
            pickle.dump(_to_saveable(obj), f, protocol=protocol)
        return
    from .io import crypto
    blob = crypto.encrypt(pickle.dumps(_to_saveable(obj),
                                       protocol=protocol), cipher_key)
    with open(path, "wb") as f:
        f.write(blob)


def load(path, return_numpy=False, cipher_key=None):
    """paddle.load parity; cipher_key decrypts a file written with one."""
    if cipher_key is None:      # streaming path
        with open(path, "rb") as f:
            obj = pickle.load(f)
    else:
        from .io import crypto
        with open(path, "rb") as f:
            obj = pickle.loads(crypto.decrypt(f.read(), cipher_key))
    if return_numpy:
        return obj
    return _from_saved(obj)


def _from_saved(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, dict):
        return {k: _from_saved(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saved(v) for v in obj)
    return obj


# ---------------------------------------------------------------------------
# Functional bridge (the jit fast path)
# ---------------------------------------------------------------------------

def unaliased_put(v, sharding=None):
    """device_put a TRUE copy of ``v`` (optionally onto ``sharding``).

    ``jax.device_put(..., may_alias=False)`` still aliases the source
    buffer on this jax build's CPU backend, so donating the result also
    deletes the source — a Layer's own Tensor ends up pointing at a
    deleted array after step 1. Route through ``jnp.array(copy=True)``
    (an XLA copy, never an alias) before the placement."""
    import jax.numpy as jnp

    v = jnp.array(v, copy=True)
    return v if sharding is None else jax.device_put(v, sharding)


def param_arrays(layer) -> Dict[str, jax.Array]:
    """Trainable parameter payloads keyed by qualified name."""
    return {n: p._data for n, p in layer.named_parameters()
            if not p.stop_gradient}


def state_arrays(layer) -> Dict[str, jax.Array]:
    """Non-trainable state: buffers + frozen params."""
    out = {n: b._data for n, b in layer.named_buffers()}
    out.update({n: p._data for n, p in layer.named_parameters()
                if p.stop_gradient})
    return out


@contextlib.contextmanager
def _swapped(layer, arrays: Dict[str, jax.Array]):
    """Temporarily replace named param/buffer payloads with `arrays`."""
    lookup = dict(layer.named_parameters())
    lookup.update(dict(layer.named_buffers()))
    saved = {}
    try:
        for name, arr in arrays.items():
            t = lookup[name]
            saved[name] = t._data
            t._data = arr
        yield lookup
    finally:
        for name, old in saved.items():
            lookup[name]._data = old


def functional_call(layer, params: Dict[str, jax.Array],
                    state: Dict[str, jax.Array], *args,
                    mutable_state: bool = True, **kwargs):
    """Run `layer(*args, **kwargs)` as a pure function of (params, state).

    Returns (outputs, new_state). `outputs` has Tensors unwrapped to raw
    jax arrays (pytree). Tape recording is disabled — differentiate with
    jax.grad around this call.
    """
    merged = {**params, **state}
    with _swapped(layer, merged) as lookup:
        with no_grad():
            out = layer(*args, **kwargs)
        new_state = {n: lookup[n]._data for n in state} if mutable_state else state
    return unwrap(out), new_state


class MethodAdapter:
    """Present `getattr(layer, method)` as the __call__ surface that
    functional_call drives, sharing the layer's parameter tree — e.g.
    MethodAdapter(gpt, "loss") makes functional_call run gpt.loss(ids,
    labels) purely."""

    def __init__(self, layer, method: str):
        self._layer = layer
        self._method = method

    def named_parameters(self, *a, **k):
        return self._layer.named_parameters(*a, **k)

    def named_buffers(self, *a, **k):
        return self._layer.named_buffers(*a, **k)

    def __call__(self, *args, **kwargs):
        return getattr(self._layer, self._method)(*args, **kwargs)


def unwrap(obj):
    """Tensor pytree -> raw jax array pytree."""
    if isinstance(obj, Tensor):
        return obj._data
    if isinstance(obj, dict):
        return {k: unwrap(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(unwrap(v) for v in obj)
    return obj


def wrap(obj):
    """Raw array pytree -> Tensor pytree."""
    if isinstance(obj, jax.Array):
        return Tensor(obj)
    if isinstance(obj, dict):
        return {k: wrap(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(wrap(v) for v in obj)
    return obj
