"""paddle.device — device management surface (reference:
python/paddle/device/__init__.py set_device/get_device; init
platform/init.cc InitDevices).

TPU-native: PJRT owns device discovery/initialization at first use (the
InitDevices analog is jax's lazy backend init); this module gives the
reference's naming. Synchronize flushes outstanding device work."""
from __future__ import annotations

import jax

from ..core.place import (CPUPlace, Place, TPUPlace,  # noqa: F401
                          device_count, get_device, is_compiled_with_cuda,
                          is_compiled_with_tpu, set_device)

__all__ = ["set_device", "get_device", "device_count", "synchronize",
           "is_compiled_with_cuda", "is_compiled_with_tpu", "CPUPlace",
           "TPUPlace", "Place", "get_all_device_type"]


def synchronize(device=None):
    """Block until outstanding device work completes (cuda.synchronize
    parity; on TPU a tiny transfer is the sync point). `device` may be a
    Place or a jax device; default = all local devices."""
    if device is None:
        targets = jax.local_devices()
    else:
        targets = [device.jax_device() if isinstance(device, Place)
                   else device]
    for d in targets:
        (jax.device_put(0.0, d) + 0).block_until_ready()


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})
