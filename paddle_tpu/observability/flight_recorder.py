"""Stall flight recorder: turn a wedged pipeline into a diagnosis.

A wedged predictor (deadlocked worker, hung collective, a device call
that never returns) historically produced *silence*: requests time out,
the daemon looks alive, and the on-call engineer has nothing to bisect.
The flight recorder is a watchdog thread armed by ``PADDLE_TPU_STALL_DUMP``
(the directory dumps are written to; unset = disabled). The instrumented
component calls :meth:`FlightRecorder.beat` every time it makes progress
(a batch dispatched, a step retired); when the component reports itself
busy (`busy_fn`) but no beat lands for ``PADDLE_TPU_STALL_TIMEOUT``
seconds (default 60), the recorder writes ONE timestamped JSON dump:

  * every live thread's stack (``sys._current_frames``), keyed by thread
    name — the "where is everyone stuck" snapshot;
  * the component's context (`context_fn`: queue depth, oldest request
    age, in-flight tickets...);
  * the tail of the tracez event ring (last ~200 events per thread) —
    what each thread was *doing* before it parked, not just where;
  * the full metrics registry snapshot.

It re-arms only after progress resumes, so a single stall produces a
single dump, not a dump per poll tick.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
import traceback
from typing import Callable, Optional

from ..core import flags as _flags
from . import metrics as _metrics

__all__ = ["FlightRecorder", "stall_dump_dir", "stall_timeout",
           "capture_thread_stacks"]

_DUMPS = _metrics.counter(
    "paddle_tpu_stall_dumps_total",
    "Flight-recorder stall dumps written (PADDLE_TPU_STALL_DUMP).")


def stall_dump_dir(env: Optional[str] = None) -> str:
    """Dump directory from ``PADDLE_TPU_STALL_DUMP``; '' = disabled."""
    return (_flags.env_raw("PADDLE_TPU_STALL_DUMP") or "") \
        if env is None else env


def stall_timeout(default: float = 60.0) -> float:
    raw = _flags.env_raw("PADDLE_TPU_STALL_TIMEOUT")
    try:
        return float(raw) if raw is not None else float(default)
    except ValueError:
        return default


def _event_ring_tail(per_thread: int = 200) -> dict:
    """Last ~200 trace-ring events per thread (tracez.TraceRing.tail);
    degrades to an error marker rather than spoiling a dump."""
    try:
        from . import tracez as _tracez
        return _tracez.RING.tail(per_thread=per_thread)
    except Exception as e:   # the dump must land even if the ring can't
        return {"events_error": repr(e)}


def _memz_block() -> dict:
    """Compact memory-plane summary (memz.status_block): top holders +
    fragmentation per registered pool, so a wedged-batcher dump also
    explains memory state; degrades like the event-ring tail."""
    try:
        from . import memz as _memz
        return _memz.status_block()
    except Exception as e:   # the dump must land even if memz can't
        return {"memz_error": repr(e)}


def capture_thread_stacks() -> dict:
    """{thread_name (id): [stack lines, innermost last]} for every live
    thread — the core of the dump, usable standalone."""
    names = {t.ident: f"{t.name} ({t.ident})"
             for t in threading.enumerate()}
    out = {}
    for ident, frame in sys._current_frames().items():
        key = names.get(ident, f"unknown ({ident})")
        out[key] = [ln.rstrip("\n")
                    for ln in traceback.format_stack(frame)]
    return out


class FlightRecorder:
    """Watchdog over one producer/consumer component.

    ``busy_fn() -> bool`` must be cheap and lock-light: True when there
    is outstanding work that SHOULD be progressing (queued requests,
    in-flight tickets). ``context_fn() -> dict`` (optional) is only
    called at dump time. Disabled entirely (no thread spawned) unless a
    dump directory is configured, so the hot path cost when off is one
    attribute check."""

    def __init__(self, label: str, busy_fn: Callable[[], bool],
                 context_fn: Optional[Callable[[], dict]] = None,
                 threshold_s: Optional[float] = None,
                 dump_dir: Optional[str] = None,
                 poll_s: Optional[float] = None,
                 registry: Optional[_metrics.MetricsRegistry] = None):
        self.label = label
        self._busy_fn = busy_fn
        self._context_fn = context_fn
        self.dump_dir = stall_dump_dir() if dump_dir is None else dump_dir
        self.threshold_s = stall_timeout() if threshold_s is None \
            else float(threshold_s)
        self.enabled = bool(self.dump_dir) and self.threshold_s > 0
        self._registry = registry or _metrics.REGISTRY
        self._last_beat = time.monotonic()
        self._armed = True
        self._stop = threading.Event()
        self._thread = None
        self.dumps = []          # paths written (newest last)
        if self.enabled:
            self._poll_s = poll_s if poll_s is not None \
                else min(max(self.threshold_s / 4.0, 0.05), 5.0)
            self._thread = threading.Thread(
                target=self._watch_loop, daemon=True,
                name=f"stall-recorder-{label}")
            self._thread.start()

    def beat(self):
        """Mark progress (called by the instrumented component)."""
        self._last_beat = time.monotonic()
        self._armed = True

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)

    # -- watchdog ---------------------------------------------------------

    def _watch_loop(self):
        while not self._stop.wait(self._poll_s):
            try:
                self._check(time.monotonic())
            except Exception:
                pass     # the watchdog must never take the daemon down

    def _check(self, now: float):
        try:
            busy = bool(self._busy_fn())
        except Exception:
            busy = False
        if not busy:
            # idle is not a stall; restart the clock so a burst after a
            # quiet hour is not instantly "stalled"
            self._last_beat = now
            self._armed = True
            return
        stalled_for = now - self._last_beat
        if stalled_for >= self.threshold_s and self._armed:
            self._armed = False      # one dump per stall
            self.dump(reason=f"no progress for {stalled_for:.1f}s "
                             f"with work outstanding",
                      stalled_for_s=stalled_for)

    # -- dumping ----------------------------------------------------------

    def dump(self, reason: str = "manual",
             stalled_for_s: float = 0.0) -> Optional[str]:
        """Write one dump file; returns its path (None when no dump dir
        is configured — the payload is still returned via ``self.last``)."""
        context = {}
        if self._context_fn is not None:
            try:
                context = dict(self._context_fn())
            except Exception as e:
                context = {"context_error": repr(e)}
        payload = {
            "kind": "paddle_tpu_stall_dump",
            "label": self.label,
            "reason": reason,
            "stalled_for_s": round(float(stalled_for_s), 3),
            "threshold_s": self.threshold_s,
            "time": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "pid": os.getpid(),
            "context": context,
            "threads": capture_thread_stacks(),
            # the event-ring tail: stacks say where each thread is
            # parked, the tail says what it was doing on the way there
            "events": _event_ring_tail(),
            # the memory plane: who held which pages while it wedged
            "memz": _memz_block(),
            "metrics": self._registry.flat(),
        }
        self.last = payload
        _DUMPS.inc()
        if not self.dump_dir:
            return None
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            fname = (f"stall_{self.label}_"
                     f"{time.strftime('%Y%m%d_%H%M%S')}_"
                     f"{os.getpid()}_{len(self.dumps)}.json")
            path = os.path.join(self.dump_dir, fname)
            with open(path, "w") as f:
                json.dump(payload, f, indent=1)
            self.dumps.append(path)
            sys.stderr.write(
                f"paddle_tpu: stall detected in {self.label!r} "
                f"({reason}); flight-recorder dump -> {path}\n")
            return path
        except OSError:
            return None
