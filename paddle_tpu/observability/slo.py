"""Declarative SLOs evaluated as multi-window burn rates over /varz.

An :class:`Objective` states a target the fleet can be judged against —
"99.9 % of requests succeed" (availability) or "99 % of requests finish
under 250 ms" (latency) — against counters / histograms already in the
registry. The :class:`SLOEngine` turns the :class:`TimeSeriesStore`
history into *burn rates*: the ratio of the observed bad-event rate to
the rate the error budget allows. Burn rate 1.0 spends the budget
exactly at the target; 10x spends a month's budget in three days.

Alerting follows the SRE multi-window recipe: a state trips only when
the burn exceeds the factor over BOTH the long window (meaningful
spend) and the short window (still happening right now), which is what
keeps a recovered incident from paging for an hour:

    state = firing   if burn(long) >= firing_factor and
                        burn(short) >= firing_factor
          = warning  if burn(long) >= warn_factor and
                        burn(short) >= warn_factor
          = ok       otherwise

``/alertz`` (admin route) serves the verdicts as JSON; the serve
daemon mounts a default availability objective (plus a latency one when
``PADDLE_TPU_SLO_P99_MS`` is set), and the router both serves its own
``/alertz`` and *consumes* each backend's — a firing backend is demoted
in the routing score, closing the loop from observability back into
routing.

Env knobs (all optional):

  * ``PADDLE_TPU_SLO_AVAILABILITY``  target success fraction
    (default 0.999; ``0``/``off`` disables the availability objective)
  * ``PADDLE_TPU_SLO_P99_MS``        latency threshold in ms (default
    off); ``PADDLE_TPU_SLO_LATENCY_TARGET`` fraction of requests that
    must beat it (default 0.99)
  * ``PADDLE_TPU_SLO_TENANTS``       ``tenant[:target]`` comma list:
    one extra availability objective per named tenant over the
    ``paddle_tpu_tenant_*`` counters (default off)
  * ``PADDLE_TPU_SLO_WINDOWS``       ``short,long`` seconds
    (default ``60,300``)
  * ``PADDLE_TPU_SLO_BURN``          ``warn,firing`` factors
    (default ``2,10``)
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import flags as _flags
from . import metrics as _metrics
from .timeseries import TimeSeriesStore

__all__ = ["Objective", "SLOEngine", "slo_windows", "slo_burn_factors",
           "serve_objectives", "router_objectives", "tenant_objectives"]


def _env_float(name: str, default: float) -> float:
    raw = (_flags.env_raw(name) or "").strip().lower()
    if not raw:
        return default
    if raw == "off":
        return 0.0              # explicit opt-out, not "use the default"
    try:
        return float(raw)
    except ValueError:
        return default


def _env_pair(name: str, default: Tuple[float, float]
              ) -> Tuple[float, float]:
    raw = (_flags.env_raw(name) or "").strip()
    if raw:
        try:
            a, b = (float(x) for x in raw.split(",", 1))
            if a > 0 and b > 0:
                return a, b
        except ValueError:
            pass
    return default


def slo_windows() -> Tuple[float, float]:
    """(short_s, long_s) evaluation windows."""
    short, long_ = _env_pair("PADDLE_TPU_SLO_WINDOWS", (60.0, 300.0))
    return (min(short, long_), max(short, long_))


def slo_burn_factors() -> Tuple[float, float]:
    """(warn_factor, firing_factor)."""
    warn, fire = _env_pair("PADDLE_TPU_SLO_BURN", (2.0, 10.0))
    return (min(warn, fire), max(warn, fire))


class Objective:
    """One declarative objective over registry series.

    ``kind="availability"``: ``bad_keys`` / ``total_keys`` are flat
    counter sample keys (a trailing ``*`` prefix-matches, for labeled
    families); target is the success fraction.

    ``kind="latency"``: ``hist_key`` is a histogram child key;
    ``threshold_s`` the latency bound; target the fraction of requests
    that must land under it.
    """

    def __init__(self, name: str, kind: str, target: float,
                 total_keys: Sequence[str] = (),
                 bad_keys: Sequence[str] = (),
                 hist_key: str = "",
                 threshold_s: float = 0.0):
        if kind not in ("availability", "latency"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        if not 0.0 < float(target) < 1.0:
            raise ValueError(
                f"SLO {name}: target must be in (0, 1), got {target}")
        self.name = name
        self.kind = kind
        self.target = float(target)
        self.total_keys = tuple(total_keys)
        self.bad_keys = tuple(bad_keys)
        self.hist_key = hist_key
        self.threshold_s = float(threshold_s)

    @property
    def budget(self) -> float:
        """Allowed bad-event fraction (1 - target)."""
        return 1.0 - self.target

    def describe(self) -> dict:
        d = {"name": self.name, "kind": self.kind, "target": self.target}
        if self.kind == "latency":
            d["threshold_s"] = self.threshold_s
        return d


def _sum_keys(store: TimeSeriesStore, keys: Sequence[str],
              window_s: float, now: Optional[float]) -> float:
    total = 0.0
    for k in keys:
        if k.endswith("*"):
            prefix = k[:-1]
            latest = store._ring[-1].scalars if store._ring else {}
            for name in latest:
                if name.startswith(prefix):
                    total += store.delta(name, window_s, now)
        else:
            total += store.delta(k, window_s, now)
    return total


class SLOEngine:
    """Evaluates objectives against a TimeSeriesStore on demand.

    Evaluation is a pure read over the ring (no locks beyond the
    store's), so serving ``/alertz`` is as cheap as serving ``/varz``.
    State gauges (`paddle_tpu_slo_state`, 0 ok / 1 warning / 2 firing,
    and `paddle_tpu_slo_burn_rate`, the long-window burn) make the
    verdicts scrapeable alongside everything else.
    """

    _STATES = ("ok", "warning", "firing")

    def __init__(self, store: TimeSeriesStore,
                 objectives: Sequence[Objective],
                 windows: Optional[Tuple[float, float]] = None,
                 burn_factors: Optional[Tuple[float, float]] = None,
                 registry: Optional[_metrics.MetricsRegistry] = None):
        self.store = store
        self.objectives = list(objectives)
        self.short_s, self.long_s = windows or slo_windows()
        self.warn_factor, self.firing_factor = \
            burn_factors or slo_burn_factors()
        reg = registry or _metrics.REGISTRY
        self._state_g = reg.gauge(
            "paddle_tpu_slo_state",
            "Objective alert state: 0 ok, 1 warning, 2 firing.",
            labelnames=("slo",))
        self._burn_g = reg.gauge(
            "paddle_tpu_slo_burn_rate",
            "Long-window error-budget burn rate per objective "
            "(1.0 = spending exactly the budget).",
            labelnames=("slo",))

    # -- burn math --------------------------------------------------------

    def _bad_fraction(self, obj: Objective, window_s: float,
                      now: Optional[float]) -> Tuple[float, float]:
        """(bad fraction of events in window, event count)."""
        if obj.kind == "availability":
            total = _sum_keys(self.store, obj.total_keys, window_s, now)
            if total <= 0:
                return 0.0, 0.0
            bad = _sum_keys(self.store, obj.bad_keys, window_s, now)
            return min(bad / total, 1.0), total
        frac, count = self.store.frac_over(
            obj.hist_key, obj.threshold_s, window_s, now)
        return frac, float(count)

    def _burn(self, obj: Objective, window_s: float,
              now: Optional[float]) -> Tuple[float, float]:
        """(burn rate over the window, events seen)."""
        frac, n = self._bad_fraction(obj, window_s, now)
        return frac / obj.budget, n

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One verdict dict per objective (also refreshes the gauges)."""
        out = []
        for obj in self.objectives:
            burn_s, n_s = self._burn(obj, self.short_s, now)
            burn_l, n_l = self._burn(obj, self.long_s, now)
            state = "ok"
            reasons: List[str] = []
            if burn_l >= self.firing_factor and \
                    burn_s >= self.firing_factor:
                state = "firing"
            elif burn_l >= self.warn_factor and \
                    burn_s >= self.warn_factor:
                state = "warning"
            if state != "ok":
                reasons.append(
                    f"burn {burn_l:.1f}x over {self.long_s:g}s and "
                    f"{burn_s:.1f}x over {self.short_s:g}s "
                    f"(budget {obj.budget:g}"
                    + (f", threshold {obj.threshold_s:g}s"
                       if obj.kind == "latency" else "")
                    + ")")
            verdict = {
                **obj.describe(),
                "state": state,
                "reasons": reasons,
                "burn": {"short_s": self.short_s,
                         "long_s": self.long_s,
                         "short": round(burn_s, 3),
                         "long": round(burn_l, 3),
                         "events_short": n_s,
                         "events_long": n_l},
            }
            out.append(verdict)
            self._state_g.labels(slo=obj.name).set(
                self._STATES.index(state))
            self._burn_g.labels(slo=obj.name).set(burn_l)
        return out

    def alertz(self) -> dict:
        """The /alertz body: worst state first, plus config echo."""
        verdicts = self.evaluate()
        worst = "ok"
        for v in verdicts:
            if self._STATES.index(v["state"]) > self._STATES.index(worst):
                worst = v["state"]
        return {
            "state": worst,
            "ts": round(time.time(), 3),
            "windows_s": [self.short_s, self.long_s],
            "burn_factors": [self.warn_factor, self.firing_factor],
            "slos": verdicts,
        }


# -- default objective sets ------------------------------------------------

def serve_objectives() -> List[Objective]:
    """The serve daemon's defaults: availability over the request
    counters, latency-p99 only when a threshold is configured."""
    objs: List[Objective] = []
    avail = _env_float("PADDLE_TPU_SLO_AVAILABILITY", 0.999)
    if 0.0 < avail < 1.0:
        objs.append(Objective(
            "serve_availability", "availability", avail,
            total_keys=("paddle_tpu_serve_requests_total",
                        "paddle_tpu_serve_errors_total"),
            bad_keys=("paddle_tpu_serve_errors_total",)))
    p99_ms = _env_float("PADDLE_TPU_SLO_P99_MS", 0.0)
    if p99_ms > 0:
        target = _env_float("PADDLE_TPU_SLO_LATENCY_TARGET", 0.99)
        target = min(max(target, 0.5), 0.9999)
        objs.append(Objective(
            "serve_latency", "latency", target,
            hist_key="paddle_tpu_serve_request_latency_seconds",
            threshold_s=p99_ms / 1000.0))
    objs.extend(tenant_objectives())
    return objs


def tenant_objectives() -> List[Objective]:
    """Per-tenant availability objectives from ``PADDLE_TPU_SLO_TENANTS``
    (a ``tenant[:target]`` comma list; target defaults to the fleet
    availability target) over the per-tenant serve counters. Each tenant
    burns its own error budget on ``/alertz``, so one tenant melting
    down cannot trip another tenant's — or the fleet's — alert."""
    raw = (_flags.env_raw("PADDLE_TPU_SLO_TENANTS") or "").strip()
    if not raw:
        return []
    default = _env_float("PADDLE_TPU_SLO_AVAILABILITY", 0.999)
    if not 0.0 < default < 1.0:
        default = 0.999
    objs: List[Objective] = []
    for part in raw.replace(";", ",").split(","):
        part = part.strip()
        if not part:
            continue
        name, _, val = part.partition(":")
        name = name.strip()
        if not name:
            continue
        try:
            target = float(val) if val.strip() else default
        except ValueError:
            target = default
        if not 0.0 < target < 1.0:
            continue
        objs.append(Objective(
            f"tenant_availability:{name}", "availability", target,
            total_keys=(
                f'paddle_tpu_tenant_requests_total{{tenant="{name}"}}',),
            bad_keys=(
                f'paddle_tpu_tenant_errors_total{{tenant="{name}"}}',)))
    return objs


def router_objectives() -> List[Objective]:
    """The router judges the fleet as one service: availability over
    request outcomes (shed/unavailable spend budget, relayed model
    errors do not — the backend answered), same optional latency
    objective."""
    objs: List[Objective] = []
    avail = _env_float("PADDLE_TPU_SLO_AVAILABILITY", 0.999)
    if 0.0 < avail < 1.0:
        objs.append(Objective(
            "router_availability", "availability", avail,
            total_keys=("paddle_tpu_router_requests_total*",),
            bad_keys=(
                'paddle_tpu_router_requests_total{outcome="shed"}',
                'paddle_tpu_router_requests_total{outcome="unavailable"}',
            )))
    p99_ms = _env_float("PADDLE_TPU_SLO_P99_MS", 0.0)
    if p99_ms > 0:
        target = _env_float("PADDLE_TPU_SLO_LATENCY_TARGET", 0.99)
        target = min(max(target, 0.5), 0.9999)
        objs.append(Objective(
            "router_latency", "latency", target,
            hist_key="paddle_tpu_router_request_latency_seconds",
            threshold_s=p99_ms / 1000.0))
    return objs
