"""tracez: always-on bounded event ring + Chrome trace-event exporter.

The fleet already answers "how much" (metrics, /varz) and "how bad"
(/alertz, stall dumps); tracez answers "what happened, in order".  Every
process keeps one :data:`RING` — a fixed-capacity, overwrite-on-wrap
event ring the hot paths write begin/end/instant/counter events into:
the dynamic batcher's form/pad/execute/unpad, the decode engine's tick
phases, the async step pipeline's dispatch/block, every AOT'd
executable's dispatch (via ``jit.compile_cache``), and the router's
pick/forward/reply.  Recording one event is a tuple build plus one slot
assignment under a lock — no I/O, no allocation beyond the tuple, no
device work — so the ring can stay armed in production (< 2 µs/event on
CPU; ``PADDLE_TPU_TRACEZ_CAPACITY=0`` turns it into a no-op).

**Clock model.** Events carry ``time.perf_counter()`` timestamps
(monotonic, immune to NTP steps); each ring records a *wall-clock
anchor* — one ``(time.time(), time.perf_counter())`` pair captured at
ring creation — and the exporter maps every monotonic timestamp through
it.  Two processes' monotonic epochs are unrelated, but their anchored
wall clocks agree to NTP precision, so merging a router ring with its
backends' rings yields one skew-corrected timeline where a request's
spans nest across processes.  ``observability.spans`` uses the same
anchoring for its JSONL ``ts`` field, so span lines and ring events
correlate.

**Export.** :meth:`TraceRing.chrome_trace` renders the ring as Chrome
trace-event JSON (``{"traceEvents": [...]}``, timestamps in µs) loadable
directly in ui.perfetto.dev or chrome://tracing.  The AdminServer serves
it as ``/tracez``; ``python -m paddle_tpu.observability.tracez merge``
assembles one file from several rings (local files or live ``/tracez``
URLs) for offline fleet-wide timelines.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

from ..core import flags as _flags
from . import metrics as _metrics

__all__ = ["TraceRing", "RING", "ring_capacity", "merge_traces",
           "fetch_trace", "load_trace", "main"]

DEFAULT_CAPACITY = 65536


def ring_capacity() -> int:
    """``PADDLE_TPU_TRACEZ_CAPACITY``; 0 disables the ring entirely."""
    try:
        return max(int(_flags.env_value("PADDLE_TPU_TRACEZ_CAPACITY")), 0)
    except Exception:
        return DEFAULT_CAPACITY


class TraceRing:
    """Bounded in-process event ring with a wall-clock anchor.

    Events are tuples ``(ph, name, ts, dur, tid, args)`` where ``ph`` is
    the Chrome trace-event phase ("X" complete, "B"/"E" begin/end, "i"
    instant, "C" counter), ``ts``/``dur`` are ``perf_counter`` seconds,
    and ``args`` is an optional small dict.  The ring never grows and
    never blocks its writer beyond one uncontended lock: when full, the
    oldest event is overwritten (``dropped`` counts the losses).
    """

    def __init__(self, capacity: Optional[int] = None,
                 component: str = "paddle_tpu",
                 pid: Optional[int] = None):
        self.capacity = ring_capacity() if capacity is None \
            else max(int(capacity), 0)
        self.component = component
        self.pid = os.getpid() if pid is None else int(pid)
        # Wall-clock anchor: captured ONCE so every export of this ring
        # uses the same mapping — re-anchoring per export would let NTP
        # slew tear spans recorded minutes apart.
        self.anchor_wall = time.time()
        self.anchor_mono = time.perf_counter()
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._n = 0
        self._lock = threading.Lock()

    # -- hot path ---------------------------------------------------------

    def record(self, ph: str, name: str, ts: float, dur: float = 0.0,
               args: Optional[dict] = None, tid: Optional[int] = None):
        """Append one raw event; the ring's only write path."""
        cap = self.capacity
        if cap == 0:
            return
        evt = (ph, name, ts, dur,
               threading.get_ident() if tid is None else tid, args)
        with self._lock:
            self._buf[self._n % cap] = evt
            self._n += 1

    def begin(self, name: str, args: Optional[dict] = None) -> float:
        """Open a span on the calling thread; returns the begin time so
        the caller can also feed a duration elsewhere."""
        t = time.perf_counter()
        self.record("B", name, t, 0.0, args)
        return t

    def end(self, name: str):
        self.record("E", name, time.perf_counter())

    def complete(self, name: str, t0: float, t1: float,
                 args: Optional[dict] = None):
        """One finished span as a single "X" event (cheaper than B+E and
        immune to a lost half when the ring wraps mid-span)."""
        self.record("X", name, t0, t1 - t0, args)

    def instant(self, name: str, args: Optional[dict] = None):
        self.record("i", name, time.perf_counter(), 0.0, args)

    def counter(self, name: str, value: float):
        # the value rides in the dur slot: no dict allocation on the
        # hot path; the exporter moves it into args
        self.record("C", name, time.perf_counter(), float(value))

    @contextmanager
    def span(self, name: str, args: Optional[dict] = None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.complete(name, t0, time.perf_counter(), args)

    # -- reads ------------------------------------------------------------

    @property
    def total(self) -> int:
        """Events recorded since creation (including overwritten ones)."""
        return self._n

    @property
    def dropped(self) -> int:
        return max(self._n - self.capacity, 0)

    def wall(self, ts: float) -> float:
        """Map a perf_counter timestamp onto the anchored wall clock."""
        return self.anchor_wall + (ts - self.anchor_mono)

    def snapshot(self) -> Tuple[List[tuple], int]:
        """(events oldest->newest, total recorded). O(capacity), taken
        under the ring lock — a pure list copy, no rendering."""
        with self._lock:
            n, cap = self._n, self.capacity
            if cap == 0 or n == 0:
                return [], n
            if n <= cap:
                return list(self._buf[:n]), n
            i = n % cap
            return self._buf[i:] + self._buf[:i], n

    def clear(self):
        with self._lock:
            self._buf = [None] * self.capacity
            self._n = 0

    # -- rendering --------------------------------------------------------

    def _thread_names(self) -> Dict[int, str]:
        return {t.ident: t.name for t in threading.enumerate()
                if t.ident is not None}

    def tail(self, per_thread: int = 200) -> Dict[str, list]:
        """Last ``per_thread`` events per thread, rendered human-readable
        — what the flight recorder embeds in stall dumps so a wedged
        dispatcher's dump shows what it was *doing*, not just where it
        is parked."""
        names = self._thread_names()
        events, _ = self.snapshot()
        by_thread: Dict[str, list] = {}
        for ph, name, ts, dur, tid, args in events:
            key = f"{names.get(tid, 'unknown')} ({tid})"
            row = {"t": round(self.wall(ts), 6), "ph": ph, "name": name}
            if ph in ("X", "B") and dur:
                row["dur_ms"] = round(dur * 1e3, 3)
            if ph == "C":
                row["value"] = dur
            if args:
                row["args"] = args
            by_thread.setdefault(key, []).append(row)
        for key in by_thread:
            by_thread[key] = by_thread[key][-per_thread:]
        return by_thread

    def chrome_trace(self) -> dict:
        """Render as Chrome trace-event JSON (ts/dur in microseconds,
        anchored wall clock) — the /tracez body."""
        events, total = self.snapshot()
        names = self._thread_names()
        out = [{"ph": "M", "pid": self.pid, "tid": 0,
                "name": "process_name",
                "args": {"name": f"{self.component}/{self.pid}"}}]
        seen_tids = set()
        rows = []
        for ph, name, ts, dur, tid, args in events:
            seen_tids.add(tid)
            e: Dict[str, Any] = {
                "ph": ph, "name": name, "cat": self.component,
                "pid": self.pid, "tid": tid,
                "ts": round(self.wall(ts) * 1e6, 3)}
            if ph == "X":
                e["dur"] = round(dur * 1e6, 3)
            elif ph == "C":
                e["args"] = {"value": dur}
            elif ph == "i":
                e["s"] = "t"
            if args:
                e.setdefault("args", {}).update(args)
            rows.append(e)
        for tid in sorted(seen_tids):
            out.append({"ph": "M", "pid": self.pid, "tid": tid,
                        "name": "thread_name",
                        "args": {"name": names.get(tid, f"tid-{tid}")}})
        out.extend(rows)
        return {"traceEvents": out, "displayTimeUnit": "ms",
                "metadata": {"component": self.component, "pid": self.pid,
                             "anchor_wall": self.anchor_wall,
                             "capacity": self.capacity,
                             "events": len(events),
                             "events_recorded": total,
                             "events_dropped": self.dropped}}


# ---------------------------------------------------------------------------
# process-default ring + registry gauges
# ---------------------------------------------------------------------------

RING = TraceRing()

_EVENTS = _metrics.gauge(
    "paddle_tpu_tracez_events",
    "Events recorded into the default trace ring since process start "
    "(overwritten events included).")
_DROPPED = _metrics.gauge(
    "paddle_tpu_tracez_dropped",
    "Events lost to ring wrap in the default trace ring.")
_CAPACITY = _metrics.gauge(
    "paddle_tpu_tracez_capacity",
    "Configured default trace-ring capacity "
    "(PADDLE_TPU_TRACEZ_CAPACITY; 0 disables recording).")


def _collect_ring():
    _EVENTS.set(RING.total)
    _DROPPED.set(RING.dropped)
    _CAPACITY.set(RING.capacity)


_metrics.REGISTRY.add_collector(_collect_ring)


# ---------------------------------------------------------------------------
# merge: several rings -> one fleet timeline
# ---------------------------------------------------------------------------

def fetch_trace(url: str, timeout: float = 5.0) -> dict:
    """GET a live ``/tracez`` body (Chrome trace JSON) from an admin
    endpoint."""
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def load_trace(src: str, timeout: float = 5.0) -> dict:
    """A merge source: an ``http(s)://.../tracez`` URL or a JSON file."""
    if src.startswith("http://") or src.startswith("https://"):
        return fetch_trace(src, timeout=timeout)
    with open(src) as f:
        return json.load(f)


def merge_traces(traces) -> dict:
    """Merge Chrome trace dicts into one timeline.

    Because every ring exports anchored wall-clock microseconds, merging
    is concatenation: no per-process offset fitting.  Metadata ("M")
    events lead, the rest are sorted by timestamp so the merged stream
    is monotonic."""
    meta, rows, procs = [], [], []
    for t in traces:
        if not t:
            continue
        for e in t.get("traceEvents", []):
            (meta if e.get("ph") == "M" else rows).append(e)
        md = t.get("metadata")
        if md:
            # an already-merged input (a router's fleet /tracez) carries
            # per-process anchors under "processes": flatten, don't nest
            procs.extend(md.get("processes") or [md])
    rows.sort(key=lambda e: e.get("ts", 0.0))
    return {"traceEvents": meta + rows, "displayTimeUnit": "ms",
            "metadata": {"merged": len(procs), "processes": procs}}


def main(argv: Optional[list] = None) -> int:
    """``python -m paddle_tpu.observability.tracez merge`` CLI."""
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m paddle_tpu.observability.tracez",
        description="Assemble per-process /tracez rings into one "
                    "Perfetto-loadable timeline.")
    sub = p.add_subparsers(dest="cmd", required=True)
    m = sub.add_parser("merge", help="merge trace files and/or live "
                                     "/tracez URLs")
    m.add_argument("sources", nargs="+",
                   help="trace JSON files or http://host:port/tracez URLs")
    m.add_argument("-o", "--out", default="-",
                   help="output path ('-' = stdout)")
    m.add_argument("--timeout", type=float, default=5.0,
                   help="per-URL fetch timeout, seconds")
    args = p.parse_args(argv)

    traces = []
    for src in args.sources:
        try:
            traces.append(load_trace(src, timeout=args.timeout))
        except Exception as e:
            sys.stderr.write(f"tracez merge: skipping {src!r}: {e!r}\n")
    merged = merge_traces(traces)
    text = json.dumps(merged)
    if args.out == "-":
        sys.stdout.write(text + "\n")
    else:
        with open(args.out, "w") as f:
            f.write(text)
        sys.stderr.write(
            f"tracez merge: {len(traces)}/{len(args.sources)} sources, "
            f"{len(merged['traceEvents'])} events -> {args.out}\n")
    return 0 if traces else 1


if __name__ == "__main__":
    sys.exit(main())
