"""profilez: continuous per-executable profiler over the AOT dispatch hook.

The serving stack funnels every steady-state device call through
``jit.compile_cache.AotCache`` — prefill, decode step, page write/COW,
draft rollout, verify, the batcher's bucket executables.  That single
choke point makes a continuous profiler nearly free: the cache wraps
each compiled executable so every dispatch reports

  * **wall** — how long the Python call took (JAX dispatches
    asynchronously, so this is host-side dispatch cost);
  * **block** — how long ``block_until_ready`` on the outputs took
    (device execution + transfer: the part that "eats the decode tick");
  * **donated bytes** — input buffers handed to XLA for reuse this call.

Observations land in the ``paddle_tpu_exec_*`` metric families (labeled
by executable) and in a process-global :class:`ExecProfiler` whose
:meth:`top` ranks executables by total block time — served live as the
AdminServer's ``/profilez`` and embedded in serve_bench ``--decode``
JSON as ``profilez_top``.  Compiles are counted per executable too, so
"did steady state stay compile-free" is one scrape away.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional

from . import metrics as _metrics

__all__ = ["ExecProfiler", "PROFILER"]

# decode steps sit in the 100 µs..10 ms band on CPU and lower on TPU;
# the default serve buckets start too coarse to separate them
EXEC_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


class ExecProfiler:
    """Per-executable dispatch aggregates + the /profilez summary.

    One instance per process (:data:`PROFILER`); metric registration is
    idempotent so tests may build their own against a private registry.
    """

    def __init__(self, registry: Optional[_metrics.MetricsRegistry] = None):
        reg = registry or _metrics.REGISTRY
        self._wall = reg.histogram(
            "paddle_tpu_exec_wall_seconds",
            "Per-executable dispatch wall time (the Python call; async "
            "under JAX, so host-side cost).",
            labelnames=("exe",), buckets=EXEC_BUCKETS, sample_cap=512)
        self._block = reg.histogram(
            "paddle_tpu_exec_block_seconds",
            "Per-executable block_until_ready time (device execution "
            "and transfer).",
            labelnames=("exe",), buckets=EXEC_BUCKETS, sample_cap=512)
        self._calls = reg.counter(
            "paddle_tpu_exec_calls_total",
            "Dispatches per AOT executable.", labelnames=("exe",))
        self._donated = reg.gauge(
            "paddle_tpu_exec_donated_bytes",
            "Input bytes donated to XLA by the last dispatch of each "
            "executable.", labelnames=("exe",))
        self._compiles = reg.counter(
            "paddle_tpu_exec_compiles_total",
            "AOT compiles per executable family (steady state should "
            "add zero).", labelnames=("exe",))
        self._lock = threading.Lock()
        # exe -> [calls, wall_sum, block_sum, donated_sum, compiles]
        self._stats: Dict[str, list] = {}

    # -- feed (the AotCache dispatch hook calls these) --------------------

    def observe(self, exe: str, wall_s: float, block_s: float,
                donated_bytes: int = 0):
        self._wall.labels(exe=exe).observe(wall_s)
        self._block.labels(exe=exe).observe(block_s)
        self._calls.labels(exe=exe).inc()
        if donated_bytes:
            self._donated.labels(exe=exe).set(donated_bytes)
        with self._lock:
            st = self._stats.get(exe)
            if st is None:
                st = self._stats[exe] = [0, 0.0, 0.0, 0, 0]
            st[0] += 1
            st[1] += wall_s
            st[2] += block_s
            st[3] += donated_bytes

    def record_compile(self, exe: str, compile_s: float):
        self._compiles.labels(exe=exe).inc()
        with self._lock:
            st = self._stats.get(exe)
            if st is None:
                st = self._stats[exe] = [0, 0.0, 0.0, 0, 0]
            st[4] += 1

    # -- reads ------------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        """exe -> {calls, wall_s, block_s, donated_bytes, compiles}."""
        with self._lock:
            return {exe: {"calls": st[0],
                          "wall_s": round(st[1], 6),
                          "block_s": round(st[2], 6),
                          "donated_bytes": st[3],
                          "compiles": st[4]}
                    for exe, st in self._stats.items()}

    def top(self, n: int = 5) -> list:
        """Executables ranked by total block time (the device-side cost
        an optimization PR should chase first)."""
        rows = []
        for exe, st in self.snapshot().items():
            row = dict(st, exe=exe)
            try:
                row["block_p50_ms"] = round(
                    self._block.labels(exe=exe).percentile(0.50) * 1e3, 3)
                row["block_p99_ms"] = round(
                    self._block.labels(exe=exe).percentile(0.99) * 1e3, 3)
            except Exception:
                pass
            rows.append(row)
        rows.sort(key=lambda r: r["block_s"], reverse=True)
        return rows[:max(int(n), 0)]

    def profilez(self, n: int = 10) -> dict:
        """The /profilez body."""
        snap = self.snapshot()
        return {"executables": len(snap),
                "total_calls": sum(s["calls"] for s in snap.values()),
                "total_block_s": round(
                    sum(s["block_s"] for s in snap.values()), 6),
                "top": self.top(n)}


PROFILER = ExecProfiler()
