"""Thread-safe, label-aware metrics registry with Prometheus exposition.

Reference: the framework's StatRegistry (platform/monitor.h:77) is a
global map of named int counters; production serving additionally needs
typed instruments (counters that only go up, gauges, latency histograms)
rendered in a format an external monitor can scrape. This module is that
single backing store: `core.monitor` stat shims, the `profiler`
serve/step/compile aggregates, and the serving-engine span histograms all
register here, so one `REGISTRY.render()` call is the whole framework's
scrape surface (`observability.admin` serves it at `/metrics`).

Design points:
  * One family per metric name; labeled children are created on demand
    (`family.labels(stage="pad").observe(...)`). Registration is
    idempotent for an identical (type, labelnames) signature — module
    reloads and multiple recorders share the same instrument — and
    raises on a conflicting re-registration.
  * Every value operation takes the family lock; increments are exact
    under concurrency (tests hammer this).
  * Histograms keep cumulative Prometheus buckets (+Inf implicit) and,
    optionally, a bounded reservoir of raw samples so exact percentiles
    (`profiler.serve_stats` p50/p95/p99) read from the same store the
    scrape surface does.
  * `render()` emits text exposition format 0.0.4: HELP/TYPE per family,
    escaped help and label values, labels in declaration order, buckets
    cumulative with `le="+Inf"` equal to `_count`.
"""
from __future__ import annotations

import math
import re
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram", "DEFAULT_BUCKETS"]

_NAME_RE = re.compile(r"^[a-z_:][a-z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-z_][a-z0-9_]*$")

# latency-oriented default ladder (seconds): sub-ms dispatch up to
# multi-second compile-class events
DEFAULT_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _fmt(v: float) -> str:
    """Exposition value formatting: integral floats render as ints."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_str(names: Sequence[str], values: Sequence[str],
               extra: str = "") -> str:
    parts = [f'{n}="{_escape_label(str(v))}"'
             for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Metric:
    """One metric family: a name, a help string, label names, and a map
    of label-value tuples to children. With no labels the family itself
    is the single sample."""

    typename = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        if not help or not str(help).strip():
            raise ValueError(f"metric {name} needs a non-empty help string")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        self.name = name
        self.help = str(help)
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], object] = {}

    # -- labeled children -------------------------------------------------

    def _child_key(self, kwargs) -> Tuple[str, ...]:
        if set(kwargs) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, "
                f"got {tuple(kwargs)}")
        return tuple(str(kwargs[n]) for n in self.labelnames)

    def labels(self, **kwargs):
        key = self._child_key(kwargs)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def remove(self, **kwargs):
        """Drop one labeled child (no-op if absent)."""
        key = self._child_key(kwargs)
        with self._lock:
            self._children.pop(key, None)

    def clear(self):
        """Drop every labeled child and zero the direct value."""
        with self._lock:
            self._children.clear()
            self._reset_direct()

    reset = clear

    def samples(self) -> List[Tuple[Dict[str, str], object]]:
        """[(labels_dict, child_or_direct_state), ...] — stable order."""
        with self._lock:
            items = list(self._children.items())
        return [(dict(zip(self.labelnames, key)), child)
                for key, child in items]

    # subclass hooks
    def _make_child(self):
        raise NotImplementedError

    def _reset_direct(self):
        pass

    def render(self) -> List[str]:
        raise NotImplementedError

    def _header(self) -> List[str]:
        return [f"# HELP {self.name} {_escape_help(self.help)}",
                f"# TYPE {self.name} {self.typename}"]


class _Value:
    """A single scalar sample (counter/gauge child)."""

    __slots__ = ("_value", "_lock")

    def __init__(self):
        self._value = 0.0
        self._lock = threading.Lock()

    def get(self) -> float:
        with self._lock:
            return self._value

    def _inc(self, v: float) -> float:
        with self._lock:
            self._value += v
            return self._value

    def _set(self, v: float) -> float:
        with self._lock:
            self._value = float(v)
            return self._value

    def _set_max(self, v: float) -> float:
        with self._lock:
            if v > self._value:
                self._value = float(v)
            return self._value


class _CounterValue(_Value):
    def inc(self, value: float = 1) -> float:
        if value < 0:
            raise ValueError("counters can only increase")
        return self._inc(float(value))


class _GaugeValue(_Value):
    def inc(self, value: float = 1) -> float:
        return self._inc(float(value))

    def dec(self, value: float = 1) -> float:
        return self._inc(-float(value))

    def set(self, value: float) -> float:
        return self._set(value)

    def set_max(self, value: float) -> float:
        """Monotonic high-water mark (queue_depth_max class of gauge)."""
        return self._set_max(float(value))


class _ScalarFamily(_Metric):
    """Counter/Gauge family: delegates direct (label-less) operations to
    an embedded value so `registry.counter(...).inc()` works without a
    labels() hop."""

    _value_cls = _Value

    def __init__(self, name, help, labelnames=()):
        super().__init__(name, help, labelnames)
        self._direct = self._value_cls()

    def _make_child(self):
        return self._value_cls()

    def _reset_direct(self):
        self._direct = self._value_cls()

    def _no_labels(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; "
                f"use .labels(...)")
        return self._direct

    def get(self) -> float:
        return self._no_labels().get()

    def inc(self, value: float = 1) -> float:
        return self._no_labels().inc(value)

    def value(self, **kwargs) -> Optional[float]:
        """Read one labeled sample without creating it; None if absent."""
        key = self._child_key(kwargs)
        with self._lock:
            child = self._children.get(key)
        return child.get() if child is not None else None

    def render(self) -> List[str]:
        lines = self._header()
        if self.labelnames:
            for labels, child in self.samples():
                ls = _label_str(self.labelnames,
                                [labels[n] for n in self.labelnames])
                lines.append(f"{self.name}{ls} {_fmt(child.get())}")
        else:
            lines.append(f"{self.name} {_fmt(self._direct.get())}")
        return lines


class Counter(_ScalarFamily):
    typename = "counter"
    _value_cls = _CounterValue


class Gauge(_ScalarFamily):
    typename = "gauge"
    _value_cls = _GaugeValue

    def dec(self, value: float = 1) -> float:
        return self._no_labels().dec(value)

    def set(self, value: float) -> float:
        return self._no_labels().set(value)

    def set_max(self, value: float) -> float:
        return self._no_labels().set_max(value)


class _HistogramValue:
    """One histogram sample set: cumulative bucket counts + sum + count,
    plus an optional bounded reservoir of raw observations for exact
    percentiles."""

    __slots__ = ("_bounds", "_counts", "_sum", "_count", "_samples",
                 "_lock")

    def __init__(self, bounds: Sequence[float], sample_cap: int = 0):
        self._bounds = tuple(bounds)
        self._counts = [0] * len(self._bounds)
        self._sum = 0.0
        self._count = 0
        self._samples = deque(maxlen=sample_cap) if sample_cap else None
        self._lock = threading.Lock()

    def observe(self, value: float):
        v = float(value)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self._bounds):
                if v <= b:
                    self._counts[i] += 1
            if self._samples is not None:
                self._samples.append(v)

    def state(self):
        with self._lock:
            return (list(self._counts), self._sum, self._count)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def percentile(self, q: float) -> float:
        """Exact percentile over the reservoir (ceil-rank, matching the
        historical profiler convention); 0.0 with no samples or no
        reservoir."""
        with self._lock:
            vals = sorted(self._samples) if self._samples else []
        if not vals:
            return 0.0
        k = max(0, min(len(vals) - 1, int(math.ceil(q * len(vals))) - 1))
        return vals[k]


class Histogram(_Metric):
    typename = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=None,
                 sample_cap: int = 0):
        super().__init__(name, help, labelnames)
        bounds = tuple(sorted(buckets if buckets is not None
                              else DEFAULT_BUCKETS))
        if not bounds:
            raise ValueError(f"{name}: histogram needs >= 1 bucket")
        self.buckets = bounds
        self.sample_cap = int(sample_cap)
        self._direct = _HistogramValue(bounds, self.sample_cap)

    def _make_child(self):
        return _HistogramValue(self.buckets, self.sample_cap)

    def _reset_direct(self):
        self._direct = _HistogramValue(self.buckets, self.sample_cap)

    def _no_labels(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; "
                f"use .labels(...)")
        return self._direct

    def observe(self, value: float):
        self._no_labels().observe(value)

    def percentile(self, q: float) -> float:
        return self._no_labels().percentile(q)

    @property
    def count(self) -> int:
        return self._no_labels().count

    @property
    def sum(self) -> float:
        return self._no_labels().sum

    def _render_one(self, labels: Dict[str, str],
                    child: _HistogramValue) -> List[str]:
        counts, total, count = child.state()
        values = [labels[n] for n in self.labelnames]
        lines = []
        # counts[i] holds observations <= bounds[i] (cumulative by
        # construction in observe)
        for b, c in zip(self.buckets, counts):
            ls = _label_str(self.labelnames, values,
                            extra=f'le="{_fmt(b)}"')
            lines.append(f"{self.name}_bucket{ls} {c}")
        ls_inf = _label_str(self.labelnames, values, extra='le="+Inf"')
        lines.append(f"{self.name}_bucket{ls_inf} {count}")
        ls = _label_str(self.labelnames, values)
        lines.append(f"{self.name}_sum{ls} {_fmt(total)}")
        lines.append(f"{self.name}_count{ls} {count}")
        return lines

    def render(self) -> List[str]:
        lines = self._header()
        if self.labelnames:
            for labels, child in self.samples():
                lines.extend(self._render_one(labels, child))
        else:
            lines.extend(self._render_one({}, self._direct))
        return lines


class MetricsRegistry:
    """Name -> family map plus pre-scrape collectors.

    Collectors are zero-arg callables run (best-effort) before every
    `render()`/`snapshot()`; they refresh gauges whose truth lives
    elsewhere (uptime, per-device HBM, queue depth)."""

    def __init__(self):
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}
        self._collectors: List[Callable[[], None]] = []

    # -- registration -----------------------------------------------------

    def _register(self, cls, name, help, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (type(existing) is not cls
                        or existing.labelnames != tuple(labelnames)):
                    raise ValueError(
                        f"metric {name} already registered as "
                        f"{type(existing).__name__}"
                        f"{existing.labelnames}")
                return existing
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name, help, labelnames=()) -> Counter:
        return self._register(Counter, name, help, labelnames)

    def gauge(self, name, help, labelnames=()) -> Gauge:
        return self._register(Gauge, name, help, labelnames)

    def histogram(self, name, help, labelnames=(), buckets=None,
                  sample_cap: int = 0) -> Histogram:
        return self._register(Histogram, name, help, labelnames,
                              buckets=buckets, sample_cap=sample_cap)

    def unregister(self, name: str):
        with self._lock:
            self._metrics.pop(name, None)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def metrics(self) -> List[_Metric]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    # -- collectors -------------------------------------------------------

    def add_collector(self, fn: Callable[[], None]):
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def remove_collector(self, fn):
        with self._lock:
            try:
                self._collectors.remove(fn)
            except ValueError:
                pass

    def collect(self):
        with self._lock:
            fns = list(self._collectors)
        for fn in fns:
            try:
                fn()
            except Exception:
                pass            # a broken collector must not break scrapes

    # -- output -----------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition 0.0.4 over every family, collectors
        first, families in sorted-name order."""
        self.collect()
        lines: List[str] = []
        for m in self.metrics():
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Structured JSON-able snapshot (statusz / bench)."""
        self.collect()
        out = {}
        for m in self.metrics():
            entry = {"type": m.typename, "help": m.help}
            if isinstance(m, Histogram):
                def hstate(child):
                    counts, total, count = child.state()
                    return {"sum": total, "count": count}
                if m.labelnames:
                    entry["samples"] = [
                        {"labels": labels, **hstate(child)}
                        for labels, child in m.samples()]
                else:
                    entry.update(hstate(m._direct))
            else:
                if m.labelnames:
                    entry["samples"] = [
                        {"labels": labels, "value": child.get()}
                        for labels, child in m.samples()]
                else:
                    entry["value"] = m._direct.get()
            out[m.name] = entry
        return out

    def flat(self, prefix: str = "") -> Dict[str, float]:
        """Compact {exposition_sample_name: value} map of every scalar
        sample (histograms contribute _sum/_count) — the bench JSON's
        `metrics` section."""
        self.collect()
        out: Dict[str, float] = {}
        for m in self.metrics():
            if prefix and not m.name.startswith(prefix):
                continue
            if isinstance(m, Histogram):
                if m.labelnames:
                    for labels, child in m.samples():
                        ls = _label_str(
                            m.labelnames,
                            [labels[n] for n in m.labelnames])
                        _, total, count = child.state()
                        out[f"{m.name}_sum{ls}"] = total
                        out[f"{m.name}_count{ls}"] = count
                else:
                    out[f"{m.name}_sum"] = m.sum
                    out[f"{m.name}_count"] = m.count
            else:
                if m.labelnames:
                    for labels, child in m.samples():
                        ls = _label_str(
                            m.labelnames,
                            [labels[n] for n in m.labelnames])
                        out[f"{m.name}{ls}"] = child.get()
                else:
                    out[m.name] = m._direct.get()
        return out


#: process-global default registry — the framework's scrape surface
REGISTRY = MetricsRegistry()


def counter(name, help, labelnames=()) -> Counter:
    return REGISTRY.counter(name, help, labelnames)


def gauge(name, help, labelnames=()) -> Gauge:
    return REGISTRY.gauge(name, help, labelnames)


def histogram(name, help, labelnames=(), buckets=None,
              sample_cap: int = 0) -> Histogram:
    return REGISTRY.histogram(name, help, labelnames, buckets=buckets,
                              sample_cap=sample_cap)
