"""Request-scoped spans: per-stage histograms + sampled JSONL traces.

Every request through the serving engine gets a monotonically increasing
id and a span breakdown — ``queue_wait_s`` (enqueue to batch formation),
``pad_s`` (bucket assembly), ``execute_s`` (predictor run) and
``unpad_s`` (slice-back) — recorded into the stage-labeled
``paddle_tpu_serve_span_seconds`` histogram. A sampled fraction of
requests (``PADDLE_TPU_TRACE_SAMPLE``, 0..1, default 0) is additionally
emitted as one JSONL line per request to ``PADDLE_TPU_TRACE_FILE``
(default stderr), so a production incident can be traced without a
profiler attach. Sampling is deterministic in the request id (a hashed
rate gate), which keeps traces reproducible under replay.

Trace-line ``ts`` values come from the recorder's wall-clock anchor —
one ``(time.time(), time.perf_counter())`` pair captured at recorder
construction, the same anchoring :mod:`.tracez` uses for its event ring
— so timestamps from different processes sit on one skew-corrected
timeline (and an NTP step mid-run cannot tear a trace apart). The JSONL
file rotates at ``PADDLE_TPU_TRACE_MAX_BYTES`` (keep-last-2: the live
file plus ``<path>.1``), bounding what an always-sampled incident
window can write.
"""
from __future__ import annotations

import itertools
import json
import os
import sys
import threading
import time
from typing import Dict, Optional

from ..core import flags as _flags
from . import metrics as _metrics

__all__ = ["SpanRecorder", "next_request_id", "request_id_base",
           "trace_sample_rate", "trace_max_bytes"]

SPAN_STAGES = ("queue_wait", "pad", "execute", "unpad")


def _mint_id_base() -> int:
    # Fleet-unique prefix in the high bits: pid (recycled slowly) XOR a
    # nanosecond salt (breaks pid reuse across restarts), occupying bits
    # 32..61 so `base + counter` stays a positive 62-bit int — exactly
    # representable in JSON/float64 and in the C client's int64_t.
    salt = ((os.getpid() & 0x3FFF) << 16) | (time.time_ns() & 0xFFFF)
    return (salt & 0x3FFFFFFF) << 32


_ID_BASE = _mint_id_base()

# process-wide request id stream: ids stay unique across batcher
# restarts so a JSONL trace never aliases two requests; the high-bit
# prefix keeps them unique across PROCESSES too, so a --fleet N run's
# merged traces never alias two backends' requests
_req_ids = itertools.count(1)


def request_id_base() -> int:
    """This process's id prefix (high 30 bits of every minted id)."""
    return _ID_BASE


def next_request_id() -> int:
    """Monotonic within the process, globally unique across a fleet."""
    return _ID_BASE + next(_req_ids)


def trace_sample_rate(env: Optional[str] = None) -> float:
    """``PADDLE_TPU_TRACE_SAMPLE`` clamped to [0, 1]; 0 disables."""
    raw = (_flags.env_raw("PADDLE_TPU_TRACE_SAMPLE") or "") \
        if env is None else env
    try:
        rate = float(raw) if str(raw).strip() else 0.0
    except ValueError:
        return 0.0
    return min(max(rate, 0.0), 1.0)


def trace_max_bytes() -> int:
    """``PADDLE_TPU_TRACE_MAX_BYTES``; <= 0 disables rotation."""
    try:
        return int(_flags.env_value("PADDLE_TPU_TRACE_MAX_BYTES"))
    except (ValueError, TypeError):
        return 0


class SpanRecorder:
    """Feeds span breakdowns into the registry and (sampled) a JSONL sink.

    One instance per batcher; instrument registration is idempotent, so
    multiple recorders share the same histogram family."""

    def __init__(self, component: str = "serve",
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 sample: Optional[float] = None,
                 path: Optional[str] = None,
                 metric: str = "paddle_tpu_serve_span_seconds",
                 help: str = "Per-request span breakdown by stage "
                             "(queue_wait, pad, execute, unpad), "
                             "seconds."):
        reg = registry or _metrics.REGISTRY
        self.component = component
        self._hist = reg.histogram(metric, help, labelnames=("stage",))
        self.sample = trace_sample_rate() if sample is None \
            else min(max(float(sample), 0.0), 1.0)
        self.path = _flags.env_value("PADDLE_TPU_TRACE_FILE") \
            if path is None else path
        self.max_bytes = trace_max_bytes()
        # wall anchor (see module docstring): ts = anchor_wall + elapsed
        # monotonic, matching tracez.TraceRing's clock model exactly
        self._anchor_wall = time.time()
        self._anchor_mono = time.perf_counter()
        self._lock = threading.Lock()
        self._file = None
        self._bytes = 0

    def sampled(self, req_id: int) -> bool:
        if self.sample <= 0.0:
            return False
        if self.sample >= 1.0:
            return True
        # Knuth multiplicative hash of the id -> uniform [0, 1) gate;
        # deterministic per id, no RNG state
        h = (int(req_id) * 2654435761) & 0xFFFFFFFF
        return (h / 2 ** 32) < self.sample

    def observe_stage(self, stage: str, dur: float):
        """Feed one extra stage observation into the histogram only —
        for stages that overlap the spans passed to :meth:`record`
        (e.g. a router's view of the backend's breakdown) and so must
        not be double-counted into the trace line's ``total_s``."""
        self._hist.labels(stage=stage).observe(max(float(dur), 0.0))

    def record(self, req_id: int, spans: Dict[str, float],
               extra: Optional[dict] = None,
               force: Optional[bool] = None):
        """Record one request's breakdown; ``spans`` maps stage name
        (without the ``_s`` suffix) to seconds. ``force`` overrides the
        sampling gate for the JSONL line (True: always emit, e.g. a
        propagated trace context; False: histogram only)."""
        for stage, dur in spans.items():
            self._hist.labels(stage=stage).observe(max(float(dur), 0.0))
        emit = self.sampled(req_id) if force is None else bool(force)
        if not emit:
            return
        line = {"ts": round(self._anchor_wall +
                            (time.perf_counter() - self._anchor_mono), 6),
                "component": self.component,
                "request_id": int(req_id)}
        line.update({f"{k}_s": round(float(v), 6)
                     for k, v in spans.items()})
        line["total_s"] = round(sum(float(v) for v in spans.values()), 6)
        if extra:
            line.update(extra)
        self._emit(json.dumps(line))

    def _emit(self, text: str):
        data = text + "\n"
        with self._lock:
            try:
                if self.path:
                    if self._file is None:
                        self._file = open(self.path, "a")
                        try:
                            self._bytes = os.fstat(
                                self._file.fileno()).st_size
                        except OSError:
                            self._bytes = 0
                    if self.max_bytes > 0 and self._bytes > 0 and \
                            self._bytes + len(data) > self.max_bytes:
                        self._rotate_locked()
                    self._file.write(data)
                    self._file.flush()
                    self._bytes += len(data)
                else:
                    sys.stderr.write("SPAN " + data)
            except OSError:
                pass            # tracing must never fail a request

    def _rotate_locked(self):
        # keep-last-2: the live file plus one predecessor (<path>.1,
        # overwritten each rotation). A single line larger than the cap
        # still lands whole — the cap bounds growth, it never truncates
        # a trace line.
        try:
            self._file.close()
        except OSError:
            pass
        self._file = None
        try:
            os.replace(self.path, self.path + ".1")
        except OSError:
            pass
        self._file = open(self.path, "a")
        self._bytes = 0

    def close(self):
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                finally:
                    self._file = None
