"""Unified observability layer: metrics registry, admin endpoint,
request spans, and the stall flight recorder.

This package is the single backing store for every counter the
framework keeps (docs/observability.md has the full catalog):

  * :mod:`.metrics` — thread-safe, label-aware Counter / Gauge /
    Histogram families with Prometheus text exposition; the global
    :data:`REGISTRY` is what ``core.monitor`` stat shims, the
    ``profiler`` serve/step/compile aggregates, and the serving-engine
    span histograms all write into.
  * :mod:`.admin` — stdlib-HTTP ``/metrics`` + ``/healthz`` +
    ``/statusz`` server the serve daemon mounts on ``--metrics-port``.
  * :mod:`.spans` — per-request span breakdowns + sampled JSONL traces
    (``PADDLE_TPU_TRACE_SAMPLE``).
  * :mod:`.flight_recorder` — the stall watchdog
    (``PADDLE_TPU_STALL_DUMP``): all-thread stack dumps when a busy
    pipeline stops making progress.
  * :mod:`.tracez` — the always-on bounded event ring + Chrome
    trace-event exporter (``/tracez``, Perfetto-loadable, merged
    across processes via wall-clock anchoring).
  * :mod:`.profilez` — the continuous per-executable profiler fed by
    the AOT dispatch hook (``paddle_tpu_exec_*``, ``/profilez``).
  * :mod:`.memz` — the memory plane: page-level owner attribution over
    registered page pools, the bounded allocation event ring, OOM
    forensic dumps, and the ghost-page audit (``paddle_tpu_mem_*``,
    ``/memz``).
"""
from __future__ import annotations

import time as _time

from .metrics import (Counter, Gauge, Histogram, MetricsRegistry,
                      REGISTRY, counter, gauge, histogram,
                      DEFAULT_BUCKETS)
from .admin import AdminServer
from .spans import (SpanRecorder, next_request_id, request_id_base,
                    trace_sample_rate)
from .flight_recorder import (FlightRecorder, capture_thread_stacks,
                              stall_dump_dir, stall_timeout)
from .timeseries import TimeSeriesStore, varz_interval, varz_capacity
from .slo import (Objective, SLOEngine, slo_windows, slo_burn_factors,
                  serve_objectives, router_objectives)
from .tracez import (TraceRing, RING, ring_capacity, merge_traces,
                     fetch_trace, load_trace)
from .profilez import ExecProfiler, PROFILER
from .memz import (MemRing, RING as MEM_RING, register_pool,
                   capture_oom, oom_dumps, merge_memz, fetch_memz)

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "REGISTRY",
           "counter", "gauge", "histogram", "DEFAULT_BUCKETS",
           "AdminServer", "SpanRecorder", "next_request_id",
           "request_id_base", "trace_sample_rate", "FlightRecorder",
           "capture_thread_stacks", "stall_dump_dir", "stall_timeout",
           "TimeSeriesStore", "varz_interval", "varz_capacity",
           "Objective", "SLOEngine", "slo_windows", "slo_burn_factors",
           "serve_objectives", "router_objectives",
           "TraceRing", "RING", "ring_capacity", "merge_traces",
           "fetch_trace", "load_trace", "ExecProfiler", "PROFILER",
           "MemRing", "MEM_RING", "register_pool", "capture_oom",
           "oom_dumps", "merge_memz", "fetch_memz",
           "install_default_collectors"]

_PROC_T0 = _time.monotonic()
_collectors_installed = False

_UPTIME = gauge("paddle_tpu_uptime_seconds",
                "Seconds since the observability layer was imported "
                "into this process.")
_HBM_IN_USE = gauge("paddle_tpu_hbm_bytes_in_use",
                    "Per-device HBM bytes in use (PJRT memory_stats).",
                    labelnames=("device",))
_HBM_PEAK = gauge("paddle_tpu_hbm_peak_bytes_in_use",
                  "Per-device peak HBM bytes in use.",
                  labelnames=("device",))
_HBM_LIMIT = gauge("paddle_tpu_hbm_bytes_limit",
                   "Per-device HBM capacity reported by the runtime.",
                   labelnames=("device",))


def _collect_uptime():
    _UPTIME.set(_time.monotonic() - _PROC_T0)


def _collect_hbm():
    # lazy import: the registry itself must stay importable without jax
    from ..core import monitor as _monitor
    for dev, st in _monitor.all_device_memory_stats().items():
        if not st:
            continue
        _HBM_IN_USE.labels(device=dev).set(st.get("bytes_in_use", 0))
        _HBM_PEAK.labels(device=dev).set(st.get("peak_bytes_in_use", 0))
        _HBM_LIMIT.labels(device=dev).set(st.get("bytes_limit", 0))


def install_default_collectors(registry: MetricsRegistry = REGISTRY):
    """Register the uptime + per-device-HBM collectors (idempotent).

    Explicit rather than import-time because the HBM collector touches
    ``jax.devices()`` at scrape time — the serve daemon and bench opt
    in; a unit test importing the registry does not pay backend init."""
    global _collectors_installed
    registry.add_collector(_collect_uptime)
    registry.add_collector(_collect_hbm)
    _collectors_installed = True
