"""Admin HTTP endpoint: /metrics, /healthz, /statusz, /varz, /alertz,
/tracez, /profilez, /memz, with a / index.

A stdlib ``http.server`` front-end (no new dependencies) the serving
daemon exposes on ``--metrics-port`` / ``PADDLE_TPU_METRICS_PORT`` —
off by default; loopback by default, like the data-plane socket. All
routes are GET:

  * ``/``         — index: every endpoint this server mounts, as links.
  * ``/metrics``  — Prometheus text exposition 0.0.4 from the registry
    (Content-Type ``text/plain; version=0.0.4``), scrape-ready.
  * ``/healthz``  — liveness: 200 ``{"status": "ok"}`` while the
    supplied ``health_fn`` reports healthy, 503 with the reasons list
    otherwise (a load balancer or k8s probe points here).
  * ``/statusz``  — one JSON snapshot: serve stats, bucket ladder,
    compile/warmup state, per-device HBM, uptime, effective config.
  * ``/varz``     — bounded windowed history (``varz_fn``, normally
    :meth:`..timeseries.TimeSeriesStore.varz`); 404 when not mounted.
  * ``/alertz``   — SLO verdicts (``alertz_fn``, normally
    :meth:`..slo.SLOEngine.alertz`); 404 when not mounted.
  * ``/tracez``   — the event ring as Chrome trace-event JSON (open in
    ui.perfetto.dev). Defaults to this process's ring; a router mounts
    a merged fleet view instead.
  * ``/profilez`` — per-executable continuous-profiler summary, top-N
    by total block time.
  * ``/memz``     — the memory plane: every registered page pool's
    per-owner attribution, fragmentation map and ghost-page audit;
    ``/memz?oom=1`` serves the retained OOM forensic dumps. Defaults
    to this process's pools; a router mounts a merged fleet view.

Handlers never execute model code, so a scrape can never trigger a
compile or perturb the request path beyond a registry/ring read.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional, Tuple

from . import memz as _memz
from . import metrics as _metrics
from . import profilez as _profilez
from . import tracez as _tracez

__all__ = ["AdminServer", "CONTENT_TYPE_METRICS"]

CONTENT_TYPE_METRICS = "text/plain; version=0.0.4; charset=utf-8"


class AdminServer:
    """Serves the observability surface for one process.

    ``health_fn() -> (healthy, reasons)``: reasons is a list of strings
    explaining an unhealthy verdict (empty when healthy). ``status_fn()
    -> dict`` supplies the /statusz body; both default to trivial
    always-healthy implementations so the server is usable standalone.
    """

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 health_fn: Optional[
                     Callable[[], Tuple[bool, list]]] = None,
                 status_fn: Optional[Callable[[], dict]] = None,
                 varz_fn: Optional[Callable[[], dict]] = None,
                 alertz_fn: Optional[Callable[[], dict]] = None,
                 tracez_fn: Optional[Callable[[], dict]] = None,
                 profilez_fn: Optional[Callable[[], dict]] = None,
                 memz_fn: Optional[Callable[..., dict]] = None):
        self.registry = registry or _metrics.REGISTRY
        self.health_fn = health_fn or (lambda: (True, []))
        self.status_fn = status_fn
        self.varz_fn = varz_fn
        self.alertz_fn = alertz_fn
        # tracez/profilez default to the process-global ring/profiler so
        # every admin server ships the execution timeline; a router
        # passes its own tracez_fn to serve a merged fleet view
        self.tracez_fn = tracez_fn or (lambda: _tracez.RING.chrome_trace())
        self.profilez_fn = profilez_fn or \
            (lambda: _profilez.PROFILER.profilez())
        # memz defaults to the process pool registry; a router passes a
        # memz_fn serving the merged fleet view. Called as
        # memz_fn(oom=<bool>) from the ?oom=1 query.
        self.memz_fn = memz_fn or _memz.snapshot
        self._t0 = time.monotonic()
        admin = self

        class _Handler(BaseHTTPRequestHandler):
            # one admin request must not block the next: ThreadingHTTPServer
            # already threads per connection; keep them short-lived
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):     # stdout belongs to SERVE_STATS
                pass

            def _reply(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.split("?", 1)[0].rstrip("/") or "/"
                try:
                    if path == "/metrics":
                        body = admin.registry.render().encode()
                        self._reply(200, body, CONTENT_TYPE_METRICS)
                    elif path == "/healthz":
                        ok, reasons = admin._health()
                        body = json.dumps(
                            {"status": "ok" if ok else "unhealthy",
                             "reasons": list(reasons)}).encode()
                        self._reply(200 if ok else 503, body,
                                    "application/json")
                    elif path == "/statusz":
                        body = json.dumps(admin._status(),
                                          default=str).encode()
                        self._reply(200, body, "application/json")
                    elif path == "/varz" and admin.varz_fn is not None:
                        body = json.dumps(admin.varz_fn(),
                                          default=str).encode()
                        self._reply(200, body, "application/json")
                    elif path == "/alertz" and \
                            admin.alertz_fn is not None:
                        body = json.dumps(admin.alertz_fn(),
                                          default=str).encode()
                        self._reply(200, body, "application/json")
                    elif path == "/tracez":
                        body = json.dumps(admin.tracez_fn(),
                                          default=str).encode()
                        self._reply(200, body, "application/json")
                    elif path == "/profilez":
                        body = json.dumps(admin.profilez_fn(),
                                          default=str).encode()
                        self._reply(200, body, "application/json")
                    elif path == "/memz":
                        from urllib.parse import parse_qs, urlsplit
                        q = parse_qs(urlsplit(self.path).query)
                        oom = (q.get("oom") or ["0"])[0] \
                            not in ("", "0", "false")
                        body = json.dumps(admin.memz_fn(oom=oom),
                                          default=str).encode()
                        self._reply(200, body, "application/json")
                    elif path == "/":
                        self._reply(200, admin._index().encode(),
                                    "text/html; charset=utf-8")
                    else:
                        self._reply(
                            404,
                            json.dumps({"error": "unknown path",
                                        "endpoints": sorted(
                                            admin.endpoints())}).encode(),
                            "application/json")
                except BrokenPipeError:
                    pass
                except Exception as e:   # a handler bug must not 500 raw
                    try:
                        self._reply(
                            500,
                            json.dumps({"error": repr(e)}).encode(),
                            "application/json")
                    except OSError:
                        pass

        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        kwargs={"poll_interval": 0.25},
                                        daemon=True,
                                        name=f"admin-http-{self.port}")
        self._thread.start()

    def endpoints(self) -> dict:
        """path -> one-line description for every mounted route."""
        out = {
            "/metrics": "Prometheus text exposition (registry scrape)",
            "/healthz": "liveness verdict (200 ok / 503 + reasons)",
            "/statusz": "one-shot JSON status snapshot",
            "/tracez": "event ring as Chrome trace-event JSON "
                       "(open in ui.perfetto.dev)",
            "/profilez": "per-executable profiler, top-N by block time",
            "/memz": "page-pool owner attribution + ghost audit "
                     "(?oom=1 = retained OOM forensic dumps)",
        }
        if self.varz_fn is not None:
            out["/varz"] = "windowed time-series history"
        if self.alertz_fn is not None:
            out["/alertz"] = "SLO burn-rate verdicts"
        return out

    def _index(self) -> str:
        """The / index page: mounted endpoints as links, so operators
        stop guessing paths."""
        rows = "\n".join(
            f'  <li><a href="{p}"><code>{p}</code></a> — {desc}</li>'
            for p, desc in sorted(self.endpoints().items()))
        return ("<!DOCTYPE html>\n<html><head>"
                "<title>paddle_tpu admin</title></head>\n"
                f"<body><h1>paddle_tpu admin :{self.port}</h1>\n"
                f"<ul>\n{rows}\n</ul></body></html>\n")

    # wrapped so a raising callback degrades to "unhealthy, reason" /
    # a minimal status body instead of a 500
    def _health(self) -> Tuple[bool, list]:
        try:
            ok, reasons = self.health_fn()
            return bool(ok), list(reasons or [])
        except Exception as e:
            return False, [f"health check raised: {e!r}"]

    def _status(self) -> dict:
        base = {"uptime_s": round(time.monotonic() - self._t0, 3)}
        if self.status_fn is not None:
            try:
                base.update(self.status_fn())
            except Exception as e:
                base["status_error"] = repr(e)
        return base

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
