"""Bounded time-series history over the metrics registry.

The registry answers "what is the value NOW"; this module answers "what
happened over the last minute / five minutes / hour" without an
external Prometheus. A :class:`TimeSeriesStore` snapshots every scalar
sample (counters, gauges, histogram sums/counts) *and* every
histogram's cumulative bucket counts into a fixed-capacity ring buffer
on a background thread, then derives windowed views on demand:

  * ``rate(name, window)`` / ``delta(name, window)`` — counter movement
    between the two snapshots bracketing the window;
  * ``quantile(hist, q, window)`` — Prometheus-style
    ``histogram_quantile`` over the window's bucket-count delta
    (linear interpolation inside the winning bucket), i.e. the p99 *of
    the window*, not of all time;
  * ``varz()`` — one bounded JSON document (the ``/varz`` admin route)
    with per-window rates and latency trends for every family.

Memory is capped by construction: ``capacity`` snapshots of a
fixed-size sample set — the ring never grows, and a scrape only reads
what the sampler thread already wrote (no compile, no model code).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from ..core import flags as _flags

from . import metrics as _metrics

__all__ = ["TimeSeriesStore", "varz_interval", "varz_capacity"]

#: the windows /varz reports, label -> seconds
DEFAULT_WINDOWS = (("1m", 60.0), ("5m", 300.0), ("1h", 3600.0))


def varz_interval(default: float = 10.0) -> float:
    """``PADDLE_TPU_VARZ_INTERVAL`` seconds (sampler period)."""
    raw = _flags.env_raw("PADDLE_TPU_VARZ_INTERVAL") or ""
    try:
        v = float(raw) if raw.strip() else default
    except ValueError:
        return default
    return max(v, 0.05)


def varz_capacity(default: int = 400) -> int:
    """``PADDLE_TPU_VARZ_CAPACITY`` ring size (snapshot count)."""
    raw = _flags.env_raw("PADDLE_TPU_VARZ_CAPACITY") or ""
    try:
        v = int(raw) if raw.strip() else default
    except ValueError:
        return default
    return max(v, 8)


class _Snap:
    """One ring entry: timestamp + scalar map + histogram states."""

    __slots__ = ("ts", "scalars", "hists")

    def __init__(self, ts: float, scalars: Dict[str, float],
                 hists: Dict[str, Tuple[list, float, int]]):
        self.ts = ts
        self.scalars = scalars     # flat sample name -> value
        self.hists = hists         # key -> (bucket_counts, sum, count)


class TimeSeriesStore:
    """Fixed-capacity ring of registry snapshots + windowed queries."""

    def __init__(self,
                 registry: Optional[_metrics.MetricsRegistry] = None,
                 interval_s: Optional[float] = None,
                 capacity: Optional[int] = None,
                 prefix: str = "paddle_tpu_"):
        self.registry = registry or _metrics.REGISTRY
        self.interval_s = varz_interval() if interval_s is None \
            else max(float(interval_s), 0.05)
        cap = varz_capacity() if capacity is None else int(capacity)
        self.capacity = max(cap, 8)
        self.prefix = prefix
        self._ring: deque = deque(maxlen=self.capacity)
        self._bounds: Dict[str, Tuple[float, ...]] = {}   # hist family
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.time()

    # -- sampling ---------------------------------------------------------

    def sample(self, now: Optional[float] = None):
        """Take one snapshot (the background thread calls this; tests
        call it directly with a synthetic clock)."""
        ts = time.time() if now is None else float(now)
        scalars: Dict[str, float] = {}
        hists: Dict[str, Tuple[list, float, int]] = {}
        self.registry.collect()
        for m in self.registry.metrics():
            if self.prefix and not m.name.startswith(self.prefix):
                continue
            if isinstance(m, _metrics.Histogram):
                self._bounds.setdefault(m.name, tuple(m.buckets))
                if m.labelnames:
                    for labels, child in m.samples():
                        key = m.name + _metrics._label_str(
                            m.labelnames,
                            [labels[n] for n in m.labelnames])
                        hists[key] = child.state()
                else:
                    hists[m.name] = m._direct.state()
            else:
                if m.labelnames:
                    for labels, child in m.samples():
                        key = m.name + _metrics._label_str(
                            m.labelnames,
                            [labels[n] for n in m.labelnames])
                        scalars[key] = child.get()
                else:
                    scalars[m.name] = m._direct.get()
        with self._lock:
            self._ring.append(_Snap(ts, scalars, hists))

    def start(self):
        """Start the background sampler (idempotent)."""
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.sample()
                except Exception:
                    pass        # history must never take the server down

        self._thread = threading.Thread(
            target=loop, daemon=True, name="varz-sampler")
        self._thread.start()

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2)
            self._thread = None

    # -- window selection -------------------------------------------------

    def _window(self, window_s: float,
                now: Optional[float] = None) -> Tuple[Optional[_Snap],
                                                      Optional[_Snap]]:
        """(oldest snapshot inside the window, newest snapshot). The
        baseline is the *last* snapshot at or before ``now - window_s``
        when one exists, so a delta covers the full window rather than
        only the part the ring happens to hold."""
        with self._lock:
            snaps = list(self._ring)
        if not snaps:
            return None, None
        newest = snaps[-1]
        t_lo = (newest.ts if now is None else float(now)) - float(window_s)
        base = None
        for s in snaps:
            if s.ts <= t_lo:
                base = s        # latest snapshot before the window opens
            else:
                break
        if base is None:
            base = snaps[0]     # ring shorter than the window: best effort
        return base, newest

    def samples_len(self) -> int:
        with self._lock:
            return len(self._ring)

    # -- queries ----------------------------------------------------------

    def latest(self, name: str) -> Optional[float]:
        with self._lock:
            if not self._ring:
                return None
            return self._ring[-1].scalars.get(name)

    def delta(self, name: str, window_s: float,
              now: Optional[float] = None) -> float:
        """Counter movement across the window (clamped at 0 so a
        restart's counter reset reads as no traffic, not negative)."""
        base, newest = self._window(window_s, now)
        if base is None or base is newest:
            return 0.0
        return max(newest.scalars.get(name, 0.0)
                   - base.scalars.get(name, 0.0), 0.0)

    def rate(self, name: str, window_s: float,
             now: Optional[float] = None) -> float:
        base, newest = self._window(window_s, now)
        if base is None or base is newest:
            return 0.0
        dt = newest.ts - base.ts
        if dt <= 0:
            return 0.0
        return max(newest.scalars.get(name, 0.0)
                   - base.scalars.get(name, 0.0), 0.0) / dt

    def hist_delta(self, key: str, window_s: float,
                   now: Optional[float] = None
                   ) -> Tuple[List[float], float, int]:
        """(bucket_count_deltas, sum_delta, count_delta) for one
        histogram child across the window."""
        base, newest = self._window(window_s, now)
        if base is None or base is newest:
            return [], 0.0, 0
        new = newest.hists.get(key)
        if new is None:
            return [], 0.0, 0
        old = base.hists.get(key)
        counts_n, sum_n, count_n = new
        if old is None:
            return list(counts_n), sum_n, count_n
        counts_o, sum_o, count_o = old
        dc = [max(a - b, 0) for a, b in zip(counts_n, counts_o)]
        return dc, max(sum_n - sum_o, 0.0), max(count_n - count_o, 0)

    def quantile(self, key: str, q: float, window_s: float,
                 now: Optional[float] = None) -> float:
        """``histogram_quantile(q)`` over the window's bucket deltas.
        ``key`` is the flat child key (family name + label string);
        0.0 when the window saw no observations."""
        family = key.split("{", 1)[0]
        bounds = self._bounds.get(family)
        if not bounds:
            return 0.0
        counts, _, total = self.hist_delta(key, window_s, now)
        if not counts or total <= 0:
            return 0.0
        rank = q * total
        prev_cum, prev_bound = 0, 0.0
        for bound, cum in zip(bounds, counts):
            if cum >= rank:
                in_bucket = cum - prev_cum
                if in_bucket <= 0 or bound == float("inf"):
                    return prev_bound
                frac = (rank - prev_cum) / in_bucket
                return prev_bound + (bound - prev_bound) * frac
            prev_cum, prev_bound = cum, bound
        return prev_bound

    def frac_over(self, key: str, threshold_s: float, window_s: float,
                  now: Optional[float] = None) -> Tuple[float, int]:
        """(fraction of the window's observations above ``threshold_s``,
        window observation count) — the latency-SLO "bad event" rate.
        Interpolates inside the bucket containing the threshold."""
        family = key.split("{", 1)[0]
        bounds = self._bounds.get(family)
        counts, _, total = self.hist_delta(key, window_s, now)
        if not bounds or not counts or total <= 0:
            return 0.0, 0
        prev_cum, prev_bound = 0, 0.0
        le = float(total)
        for bound, cum in zip(bounds, counts):
            if threshold_s <= bound:
                if bound == float("inf") or bound == prev_bound:
                    le = float(cum)
                else:
                    frac = (threshold_s - prev_bound) / (bound - prev_bound)
                    le = prev_cum + (cum - prev_cum) * frac
                break
            prev_cum, prev_bound = cum, bound
        bad = max(float(total) - le, 0.0)
        return bad / float(total), int(total)

    # -- the /varz document ----------------------------------------------

    def varz(self) -> dict:
        """Bounded JSON: per-window rate/delta for every counter,
        last/min/max for every gauge, windowed p50/p99 + throughput for
        every histogram. Size is O(families x windows), independent of
        uptime."""
        with self._lock:
            snaps = list(self._ring)
        out = {
            "now": round(time.time(), 3),
            "interval_s": self.interval_s,
            "ring": {"capacity": self.capacity, "samples": len(snaps),
                     "oldest_ts": round(snaps[0].ts, 3) if snaps else None,
                     "newest_ts": round(snaps[-1].ts, 3) if snaps else None},
            "windows": {},
        }
        if not snaps:
            return out
        newest = snaps[-1]
        for label, w in DEFAULT_WINDOWS:
            sec: Dict[str, dict] = {}
            base, _ = self._window(w)
            covered = (newest.ts - base.ts) if base is not None else 0.0
            for name in sorted(newest.scalars):
                if name.endswith("_sum") or name.endswith("_count"):
                    continue       # folded into the histogram entry
                d = self.delta(name, w)
                entry = {"last": round(newest.scalars[name], 6)}
                if d or self.rate(name, w):
                    entry["delta"] = round(d, 6)
                    entry["rate_per_s"] = round(self.rate(name, w), 6)
                sec[name] = entry
            for key in sorted(newest.hists):
                _, sum_d, count_d = self.hist_delta(key, w)
                entry = {"count_delta": count_d,
                         "sum_delta_s": round(sum_d, 6)}
                if count_d:
                    entry["mean_s"] = round(sum_d / count_d, 6)
                    entry["p50_s"] = round(self.quantile(key, 0.50, w), 6)
                    entry["p99_s"] = round(self.quantile(key, 0.99, w), 6)
                sec[key] = entry
            out["windows"][label] = {
                "window_s": w, "covered_s": round(covered, 3),
                "series": sec}
        return out
