"""memz: page-level memory attribution, OOM forensics, fleet memory plane.

tracez (PR 14) gave every process a time plane — "what happened, in
order"; memz gives it the missing **memory plane** — "who holds page
17, right now".  Three pieces:

* :class:`MemRing` — a bounded, overwrite-on-wrap allocation event ring
  (same discipline as ``tracez.TraceRing``: one tuple build plus one
  slot assignment under one lock, < 2 µs/event, no I/O, no device
  work).  Every ``PageAllocator`` alloc/retain/release/exhausted lands
  one ``(op, pool, owner, n, pages_free, ts)`` event on the process
  default :data:`RING` — recorded *after* the allocator's leaf lock is
  dropped, so the two locks never nest.
* A weakref **pool registry**: engines register their page pools (plus
  an optional context callback contributing kv ladder/rung state and
  the set of live request ids) and ``/memz`` renders every registered
  pool's owner rollups, fragmentation map, and **ghost-page audit** —
  pages whose owning stream/slot has finished but whose refcount is
  still > 0.
* **OOM forensics**: on ``PageExhausted`` the decode engine calls
  :func:`capture_oom`, which snapshots top holders by tenant and
  owner kind, trie-pinned vs slot-held vs tier-in-flight counts, the
  fragmentation map, engine context, and the tail of the allocation
  ring.  The last N dumps (``PADDLE_TPU_MEMZ_OOM_DUMPS``) are retained
  and served at ``/memz?oom=1`` — the post-mortem for "what exactly was
  resident when this RESOURCE_EXHAUSTED fired".

The ``paddle_tpu_mem_*`` families (pages by owner kind and tenant,
fragmentation, ghost pages, oom_dumps_total) refresh from the registry
on every scrape, so ``/varz`` keeps their history automatically.  The
router merges backend ``/memz`` bodies into a fleet view with
:func:`merge_memz` (next to ``_fleet_tracez``).
"""
from __future__ import annotations

import json
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..core import flags as _flags
from . import metrics as _metrics

__all__ = ["MemRing", "RING", "ring_capacity", "oom_dump_limit",
           "register_pool", "snapshot", "status_block", "capture_oom",
           "oom_dumps", "ghost_audit", "fetch_memz", "merge_memz"]

DEFAULT_CAPACITY = 4096
DEFAULT_OOM_DUMPS = 4

#: Owner kinds every pool reports (fixed set so gauges zero out cleanly
#: when a kind's last page is released).
OWNER_KINDS = ("slot", "trie", "tier", "draft", "handoff", "untagged")

#: Owner kinds whose second element is a stream/slot id the ghost-page
#: audit can check against the engine's live set.
_STREAM_KINDS = ("slot", "draft", "handoff")


def _owner_str(owner) -> str:
    return ":".join(str(x) for x in owner)


def ring_capacity() -> int:
    """``PADDLE_TPU_MEMZ_RING_CAPACITY``; 0 disables the ring entirely."""
    try:
        return max(int(_flags.env_value("PADDLE_TPU_MEMZ_RING_CAPACITY")), 0)
    except Exception:
        return DEFAULT_CAPACITY


def oom_dump_limit() -> int:
    """``PADDLE_TPU_MEMZ_OOM_DUMPS``: OOM forensic dumps retained."""
    try:
        return max(int(_flags.env_value("PADDLE_TPU_MEMZ_OOM_DUMPS")), 1)
    except Exception:
        return DEFAULT_OOM_DUMPS


class MemRing:
    """Bounded allocation-event ring with a wall-clock anchor.

    Events are tuples ``(op, pool, owner, n, free, ts)`` where ``op`` is
    one of alloc/retain/release/exhausted/spill/refetch, ``owner`` is
    the allocator owner tag, ``n`` the page count the operation moved,
    ``free`` the pool's free pages after it, and ``ts`` a
    ``perf_counter`` timestamp.  When full, the oldest event is
    overwritten (``dropped`` counts the losses)."""

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = ring_capacity() if capacity is None \
            else max(int(capacity), 0)
        self.anchor_wall = time.time()
        self.anchor_mono = time.perf_counter()
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._n = 0
        self._lock = threading.Lock()

    # -- hot path ---------------------------------------------------------

    def record(self, op: str, pool: str, owner, n: int, free: int):
        """Append one raw event; the ring's only write path."""
        cap = self.capacity
        if cap == 0:
            return
        evt = (op, pool, owner, n, free, time.perf_counter())
        with self._lock:
            self._buf[self._n % cap] = evt
            self._n += 1

    # -- reads ------------------------------------------------------------

    @property
    def total(self) -> int:
        """Events recorded since creation (including overwritten ones)."""
        return self._n

    @property
    def dropped(self) -> int:
        return max(self._n - self.capacity, 0)

    def wall(self, ts: float) -> float:
        return self.anchor_wall + (ts - self.anchor_mono)

    def snapshot(self) -> Tuple[List[tuple], int]:
        """(events oldest->newest, total recorded)."""
        with self._lock:
            n, cap = self._n, self.capacity
            if cap == 0 or n == 0:
                return [], n
            if n <= cap:
                return list(self._buf[:n]), n
            i = n % cap
            return self._buf[i:] + self._buf[:i], n

    def clear(self):
        with self._lock:
            self._buf = [None] * self.capacity
            self._n = 0

    def tail(self, limit: int = 64) -> List[dict]:
        """Last `limit` events rendered human-readable — what the OOM
        forensic dump and the flight recorder embed."""
        events, _ = self.snapshot()
        return [{"t": round(self.wall(ts), 6), "op": op, "pool": pool,
                 "owner": _owner_str(owner), "n": n, "free": free}
                for op, pool, owner, n, free, ts in events[-limit:]]


# ---------------------------------------------------------------------------
# process-default ring + registry gauges
# ---------------------------------------------------------------------------

RING = MemRing()

_PAGES = _metrics.gauge(
    "paddle_tpu_mem_pages",
    "Used pages per registered pool attributed to their primary owner "
    "kind (slot/trie/tier/draft/handoff/untagged); kinds sum to the "
    "pool's pages_used exactly.",
    labelnames=("pool", "owner_kind"))
_TENANT_PAGES = _metrics.gauge(
    "paddle_tpu_mem_tenant_pages",
    "Used pages per registered pool attributed to the tenant of their "
    "primary slot owner ('-' = not slot-held).",
    labelnames=("pool", "tenant"))
_FRAG = _metrics.gauge(
    "paddle_tpu_mem_fragmentation",
    "Free-space fragmentation per registered pool (1 - largest "
    "contiguous free run / free pages).",
    labelnames=("pool",))
_GHOSTS = _metrics.gauge(
    "paddle_tpu_mem_ghost_pages",
    "Ghost pages per registered pool: pages whose owning stream/slot "
    "has finished but whose refcount is still > 0.",
    labelnames=("pool",))
_RING_EVENTS = _metrics.gauge(
    "paddle_tpu_mem_ring_events",
    "Allocation events recorded into the default memz ring since "
    "process start (overwritten events included).")
_OOM_TOTAL = _metrics.counter(
    "paddle_tpu_mem_oom_dumps_total",
    "OOM forensic dumps captured on PageExhausted (served at "
    "/memz?oom=1; last PADDLE_TPU_MEMZ_OOM_DUMPS retained).")


# ---------------------------------------------------------------------------
# pool registry
# ---------------------------------------------------------------------------

_POOLS: Dict[str, Tuple[Any, Any]] = {}   # label -> (alloc ref, ctx ref)
_POOLS_LOCK = threading.Lock()


def register_pool(alloc, context_fn: Optional[Callable[[], dict]] = None,
                  label: Optional[str] = None) -> str:
    """Register a ``PageAllocator`` (weakly — a stopped engine's pool
    unregisters itself by dying) under its label.  ``context_fn``
    contributes engine context to snapshots and OOM dumps — any dict,
    conventionally including ``live_owner_ids`` (ids of streams still
    alive) which powers the ghost-page audit.  Re-registering a label
    replaces the previous pool (engine restarts)."""
    key = str(label if label is not None else alloc.label)
    ctx = None
    if context_fn is not None:
        try:
            ctx = weakref.WeakMethod(context_fn)
        except TypeError:
            ctx = (lambda fn=context_fn: fn)
    with _POOLS_LOCK:
        _POOLS[key] = (weakref.ref(alloc), ctx)
    return key


def _iter_pools():
    """Yield (label, alloc, context dict or {}) for live pools, pruning
    dead weakrefs."""
    with _POOLS_LOCK:
        items = list(_POOLS.items())
    for label, (aref, ctxref) in items:
        alloc = aref()
        if alloc is None:
            with _POOLS_LOCK:
                if _POOLS.get(label) == (aref, ctxref):
                    del _POOLS[label]
            continue
        ctx = {}
        if ctxref is not None:
            fn = ctxref()
            if fn is not None:
                try:
                    ctx = fn() or {}
                except Exception:
                    ctx = {"error": "context callback failed"}
        yield label, alloc, ctx


def ghost_audit(alloc, context: Optional[dict]) -> List[dict]:
    """Pages whose owning stream/slot has finished but refcount > 0.

    Checks each page's primary owner: if its kind names a stream
    (slot/draft/handoff) and the owner id is absent from the engine's
    ``live_owner_ids``, the page is leaked-but-held — a ghost.  Without
    a live set the audit reports nothing (no false positives)."""
    live = (context or {}).get("live_owner_ids")
    if live is None:
        return []
    live = {str(x) for x in live}
    ghosts = []
    for page, owner, refs in alloc.owned_pages():
        if (str(owner[0]) in _STREAM_KINDS and len(owner) > 1
                and str(owner[1]) not in live):
            ghosts.append({"page": page, "owner": _owner_str(owner),
                           "refs": refs})
    return ghosts


# ---------------------------------------------------------------------------
# snapshots (the /memz body) + OOM forensics
# ---------------------------------------------------------------------------

_OOM_DUMPS: deque = deque(maxlen=64)
_OOM_LOCK = threading.Lock()
_OOM_SEQ = [0]


def snapshot(oom: bool = False) -> dict:
    """The ``/memz`` body: every registered pool's stats + owner
    rollups + fragmentation map + ghost audit, the allocation-ring
    tail, and the OOM dump count.  With ``oom=True`` (``/memz?oom=1``)
    returns the retained OOM forensic dumps instead."""
    if oom:
        with _OOM_LOCK:
            return {"oom_dumps": list(_OOM_DUMPS)}
    pools = {}
    for label, alloc, ctx in _iter_pools():
        st = alloc.stats()
        ghosts = ghost_audit(alloc, ctx)
        entry = {
            "stats": st,
            "fragmentation_map": alloc.fragmentation_map(),
            "ghost_pages": len(ghosts),
            "ghosts": ghosts[:32],
        }
        if ctx:
            entry["context"] = {k: v for k, v in ctx.items()
                                if k != "live_owner_ids"}
        pools[label] = entry
    with _OOM_LOCK:
        n_dumps = len(_OOM_DUMPS)
    return {
        "pools": pools,
        "ring": {"events_recorded": RING.total,
                 "events_dropped": RING.dropped,
                 "capacity": RING.capacity,
                 "tail": RING.tail(64)},
        "oom_dumps": n_dumps,
        "time": time.time(),
    }


def status_block() -> dict:
    """Compact per-pool summary for /statusz and stall dumps: owner
    rollups + fragmentation + ghost count, no maps or ring tail."""
    pools = {}
    for label, alloc, ctx in _iter_pools():
        st = alloc.stats()
        pools[label] = {
            "pages_used": st["pages_used"],
            "pages_free": st["pages_free"],
            "fragmentation": st["fragmentation"],
            "owner_kinds": st["owner_kinds"],
            "tenants": st["tenants"],
            "top_owners": dict(list(st["owners"].items())[:8]),
            "ghost_pages": len(ghost_audit(alloc, ctx)),
        }
    with _OOM_LOCK:
        n_dumps = len(_OOM_DUMPS)
    return {"pools": pools, "oom_dumps": n_dumps,
            "ring_events": RING.total}


def capture_oom(alloc, *, owner=None, requested: int = 0,
                context: Optional[dict] = None) -> dict:
    """Snapshot the pool at the moment a ``PageExhausted`` fired — the
    OOM forensic dump.  Retained (last ``PADDLE_TPU_MEMZ_OOM_DUMPS``)
    and served at ``/memz?oom=1``.  Pure bookkeeping reads; safe on the
    scheduler thread, never called under the allocator lock."""
    st = alloc.stats()
    ghosts = ghost_audit(alloc, context)
    dump = {
        "pool": alloc.label,
        "time": time.time(),
        "denied_owner": _owner_str(owner) if owner else "untagged",
        "requested": int(requested),
        "pages_free": st["pages_free"],
        "pages_used": st["pages_used"],
        "top_owners": dict(list(st["owners"].items())[:20]),
        "owner_kinds": st["owner_kinds"],
        "tenants": st["tenants"],
        "fragmentation": st["fragmentation"],
        "fragmentation_map": alloc.fragmentation_map(),
        "ghost_pages": len(ghosts),
        "ghosts": ghosts[:32],
        "stats": st,
        "context": {k: v for k, v in (context or {}).items()
                    if k != "live_owner_ids"},
        "ring_tail": RING.tail(64),
    }
    with _OOM_LOCK:
        _OOM_SEQ[0] += 1
        dump["seq"] = _OOM_SEQ[0]
        _OOM_DUMPS.append(dump)
        while len(_OOM_DUMPS) > oom_dump_limit():
            _OOM_DUMPS.popleft()
    _OOM_TOTAL.inc()
    return dump


def oom_dumps() -> List[dict]:
    with _OOM_LOCK:
        return list(_OOM_DUMPS)


def clear_oom_dumps():                     # test hook
    with _OOM_LOCK:
        _OOM_DUMPS.clear()


# ---------------------------------------------------------------------------
# registry collector: mem gauges refresh from live pools on every scrape
# ---------------------------------------------------------------------------

def _collect_mem():
    _RING_EVENTS.set(RING.total)
    for label, alloc, ctx in _iter_pools():
        st = alloc.stats()
        kinds = st["owner_kinds"]
        for kind in OWNER_KINDS:
            _PAGES.labels(pool=label, owner_kind=kind).set(
                kinds.get(kind, 0))
        tenants = st["tenants"]
        for labels, _ in _TENANT_PAGES.samples():
            if labels["pool"] == label and labels["tenant"] not in tenants:
                _TENANT_PAGES.remove(**labels)
        for tenant, n in tenants.items():
            _TENANT_PAGES.labels(pool=label, tenant=tenant).set(n)
        _FRAG.labels(pool=label).set(st["fragmentation"])
        _GHOSTS.labels(pool=label).set(len(ghost_audit(alloc, ctx)))


_metrics.REGISTRY.add_collector(_collect_mem)


# ---------------------------------------------------------------------------
# fleet view: the router merges backend /memz bodies
# ---------------------------------------------------------------------------

def fetch_memz(url: str, timeout: float = 5.0) -> dict:
    """GET a live ``/memz`` body from an admin endpoint."""
    import urllib.request
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode())


def merge_memz(snapshots, keys: Optional[List[str]] = None) -> dict:
    """Merge per-backend ``/memz`` bodies into one fleet view: summed
    owner-kind/tenant rollups and pool totals across the fleet, with
    every backend's full body retained under ``backends``.  OOM-mode
    bodies (``{"oom_dumps": [...]}``) merge into one time-ordered dump
    list.  An unreachable backend is simply absent."""
    backends = {}
    kinds: Dict[str, int] = {}
    tenants: Dict[str, int] = {}
    totals = {"pages_total": 0, "pages_used": 0, "pages_free": 0,
              "ghost_pages": 0, "oom_dumps": 0}
    all_dumps: List[dict] = []
    for i, snap in enumerate(snapshots):
        if not snap:
            continue
        key = keys[i] if keys and i < len(keys) else f"backend-{i}"
        backends[key] = snap
        dumps = snap.get("oom_dumps")
        if isinstance(dumps, list):
            all_dumps.extend(dumps)
            totals["oom_dumps"] += len(dumps)
            continue
        totals["oom_dumps"] += int(dumps or 0)
        for entry in (snap.get("pools") or {}).values():
            st = entry.get("stats") or {}
            for k in ("pages_total", "pages_used", "pages_free"):
                totals[k] += int(st.get(k, 0))
            totals["ghost_pages"] += int(entry.get("ghost_pages", 0))
            for k, n in (st.get("owner_kinds") or {}).items():
                kinds[k] = kinds.get(k, 0) + int(n)
            for t, n in (st.get("tenants") or {}).items():
                tenants[t] = tenants.get(t, 0) + int(n)
    if all_dumps:
        all_dumps.sort(key=lambda d: d.get("time", 0.0))
        return {"merged": len(backends), "oom_dumps": all_dumps,
                "backends": sorted(backends)}
    out = {"merged": len(backends), "owner_kinds": kinds,
           "tenants": tenants, "backends": backends}
    out.update(totals)
    return out
