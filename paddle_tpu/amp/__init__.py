"""Automatic mixed precision.

Reference analogs: dygraph autocast (imperative/amp_auto_cast.cc + fluid/
dygraph/amp/loss_scaler.py:27 AmpScaler), static rewrite
(contrib/mixed_precision/decorator.py:36, fp16_lists.py), amp ops
(operators/amp/check_finite_and_unscale_op, update_loss_scaling_op).

TPU-native design: bf16 is the native reduced precision — same exponent
range as fp32, so loss scaling is a no-op for bf16 (GradScaler becomes
pass-through but keeps the fp16 dynamic-scaling logic for API parity and for
fp16 runs). Autocast wraps the eager dispatcher: ops on the white list cast
inputs to the amp dtype before execution; black-list ops force fp32.
"""
from __future__ import annotations

import threading
from typing import Dict

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtype as dtype_mod
from ..core.flags import get_flags
from ..core.tensor import Tensor, no_grad

__all__ = ["auto_cast", "amp_guard", "GradScaler", "decorate",
           "WHITE_LIST", "BLACK_LIST", "amp_state"]

# fp16_lists.py analog: ops that are numerically safe/beneficial in low
# precision (matmul-class feeds the MXU) vs ops that must stay fp32.
WHITE_LIST = {"matmul", "linear", "conv1d", "conv2d", "conv3d", "einsum",
              "flash_attention", "sdpa", "sp_attention", "mm", "bmm"}
BLACK_LIST = {"softmax", "log_softmax", "cross_entropy", "layer_norm",
              "batch_norm", "norm", "mean", "sum", "exp", "log", "logsumexp",
              "cumsum", "softmax_with_cross_entropy", "kl_div", "nll_loss"}

_state = threading.local()


def amp_state():
    return getattr(_state, "amp", None)


class auto_cast:
    """Context manager: `with paddle.amp.auto_cast(): ...`"""

    def __init__(self, enable=True, custom_white_list=None,
                 custom_black_list=None, level="O1", dtype=None):
        self.enable = enable
        self.level = level
        self.dtype = dtype_mod.convert_dtype(dtype or get_flags("amp_dtype"))
        self.white = set(WHITE_LIST)
        self.black = set(BLACK_LIST)
        if custom_white_list:
            self.white |= set(custom_white_list)
            self.black -= set(custom_white_list)
        if custom_black_list:
            self.black |= set(custom_black_list)
            self.white -= set(custom_black_list)

    def __enter__(self):
        self._prev = amp_state()
        _state.amp = self if self.enable else None
        return self

    def __exit__(self, *exc):
        _state.amp = self._prev
        return False


amp_guard = auto_cast


from ..core import tensor as _tensor_mod


def maybe_cast_inputs(op_name, arrays):
    """Called by the eager dispatcher: cast op inputs per AMP lists."""
    st = amp_state()
    if st is None:
        return arrays
    def is_float(a):
        return hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating)
    if op_name in st.white or (st.level == "O2" and op_name not in st.black):
        return [a.astype(st.dtype) if is_float(a) and a.dtype != st.dtype
                else a for a in arrays]
    if op_name in st.black:
        return [a.astype(jnp.float32)
                if is_float(a) and a.dtype in (jnp.float16, jnp.bfloat16)
                else a for a in arrays]
    # gray: promote to widest floating dtype among inputs
    dtypes = {a.dtype for a in arrays if is_float(a)}
    if len(dtypes) > 1:
        tgt = jnp.float32 if jnp.float32 in dtypes else st.dtype
        return [a.astype(tgt) if is_float(a) else a for a in arrays]
    return arrays


_tensor_mod._amp_hook[0] = maybe_cast_inputs


@jax.jit
def _fused_unscale(grads, inv_scale):
    """One fused kernel: unscale every grad and reduce a single finite
    flag (check_finite_and_unscale_op analog — O(1) host syncs/step)."""
    scaled = [g.astype(jnp.float32) * inv_scale for g in grads]
    finite = jnp.asarray(True)
    for g in scaled:
        finite = jnp.logical_and(finite, jnp.isfinite(g).all())
    return scaled, finite


class GradScaler:
    """Dynamic loss scaling (reference: amp/grad_scaler.py:20 wrapping
    AmpScaler loss_scaler.py:27; kernels update_loss_scaling_op,
    check_finite_and_unscale_op)."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = set()  # optimizer ids unscaled since last update()

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return self._scale

    def scale(self, loss):
        if not self._enable:
            return loss
        self._unscaled.clear()  # new iteration begins
        return loss * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        # guard against double unscale (the documented pattern is
        # unscale_ → clip → step; step() calls unscale_ again)
        if id(optimizer) in self._unscaled:
            return
        self._unscaled.add(id(optimizer))
        params = [p for p in (optimizer._parameter_list or [])
                  if p.grad is not None]
        if not params:
            self._found_inf = False
            return
        grads = [p.grad._data for p in params]
        new_grads, finite = _fused_unscale(
            grads, jnp.float32(1.0 / self._scale))
        # ONE device->host sync for the whole parameter set (reference
        # fuses this the same way: check_finite_and_unscale_op takes the
        # full grad list and emits a single FoundInfinite scalar)
        self._found_inf = not bool(finite)
        for p, g in zip(params, new_grads):
            p.grad.set_value(g.astype(p.grad.dtype)
                             if p.grad.dtype not in (jnp.float32,)
                             else g)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def update(self):
        self._unscaled.clear()
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n,
                "good_steps": self._good_steps, "bad_steps": self._bad_steps}

    def load_state_dict(self, state):
        self._scale = state["scale"]
        self._good_steps = state.get("good_steps", 0)
        self._bad_steps = state.get("bad_steps", 0)


def decorate(models=None, optimizers=None, level="O2", dtype=None,
             master_weight=None, save_dtype=None):
    """O2 decoration: cast model params to the amp dtype, keep fp32 masters
    in the optimizer (reference: amp 'pure fp16' cast_model_to_fp16)."""
    dt = dtype_mod.convert_dtype(dtype or get_flags("amp_dtype"))
    single_model = not isinstance(models, (list, tuple))
    ms = [models] if single_model else list(models)
    for m in ms:
        if m is None:
            continue
        for p in m.parameters():
            if jnp.issubdtype(p.dtype, jnp.floating):
                p._data = p._data.astype(dt)
    if optimizers is not None:
        single_opt = not isinstance(optimizers, (list, tuple))
        opts = [optimizers] if single_opt else list(optimizers)
        for o in opts:
            o._multi_precision = True
        if models is None:
            return optimizers
        return (ms[0] if single_model else ms,
                opts[0] if single_opt else opts)
    return ms[0] if single_model else ms
