"""paddle_tpu.testing: deterministic fault-injection tooling.

`chaos` is the injection harness the fault-tolerance layer is verified
with (docs/fault_tolerance.md); it is import-light so production modules
can hook injection sites unconditionally.
"""
from . import chaos  # noqa: F401

__all__ = ["chaos"]
