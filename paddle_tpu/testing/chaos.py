"""Deterministic fault injection for the checkpoint/store/recovery stack.

Production code marks *named injection sites* with `maybe_fail(site)`;
with no schedule active that is a dict lookup and a return, so the hooks
stay in the hot path permanently. A schedule arms sites to raise a
chosen exception on chosen call numbers — deterministically, so every
recovery path is exercised by ordinary pytest instead of hope:

    from paddle_tpu.testing import chaos
    with chaos.inject("ckpt.rename:1:OSError"):
        save_checkpoint(...)        # first rename raises OSError

or, process-wide, via the environment:

    PADDLE_TPU_CHAOS="store.req:1-3:ConnectionError;step.fn:5:RuntimeError"

Spec grammar (';'-separated rules):

    <site>:<calls>:<ExcName>

    site      dotted site name; '*' suffix wildcard matches a prefix
              ("ckpt.*"). Shipped sites: fs.put, ckpt.write,
              ckpt.rename, store.req, step.fn, and the serving path:
              serve.conn.read (before decoding a request),
              serve.conn.reply (before writing the reply),
              batcher.dispatch (dispatcher loop, per formed batch),
              batcher.worker (pool worker, per batch),
              router.forward (router, per backend attempt).
    calls     which hits fire, 1-based per site counter:
                "3"        call #3 only
                "1-4"      calls 1..4
                "2,5"      calls 2 and 5
                "3+"       call 3 and every later call
                "p0.3@7"   each call fails with prob 0.3, seeded RNG(7)
                           (seeded => the schedule is reproducible)
    ExcName   OSError | ConnectionError | ConnectionResetError |
              BrokenPipeError | TimeoutError | RuntimeError | IOError —
              the site raises; or the action form ``Hang@<seconds>``,
              which SLEEPS at the site instead of raising (wedged
              dispatcher, black-holed reply, slow-loris writer — the
              failure modes an exception cannot model).

Schedules record every fired fault in `.fired` for assertions. Counters
are per-schedule, so nesting `inject()` restarts the count.
"""
from __future__ import annotations

import random
import re
import threading
import time
from typing import Dict, List, Optional

from ..core import flags as _flags

__all__ = ["ChaosFault", "Rule", "Schedule", "inject", "maybe_fail",
           "active_schedule", "fail_once", "SITES", "register_site",
           "registered_sites", "sites_markdown"]

_EXC_REGISTRY = {
    "OSError": OSError,
    "IOError": OSError,
    "ConnectionError": ConnectionError,
    "ConnectionResetError": ConnectionResetError,
    "BrokenPipeError": BrokenPipeError,
    "TimeoutError": TimeoutError,
    "RuntimeError": RuntimeError,
}


class ChaosFault(RuntimeError):
    """Raised only when a rule names no concrete exception type."""


class Rule:
    """One armed site: which calls fire and what they raise."""

    def __init__(self, site: str, calls=None, from_call: int = None,
                 prob: float = None, seed: int = 0, exc=OSError,
                 hang_s: float = None):
        self.site = site
        self.calls = set(calls) if calls else None
        self.from_call = from_call
        self.prob = prob
        self.exc = exc
        self.hang_s = hang_s       # action rule: sleep instead of raise
        self._rng = random.Random(seed)

    def matches(self, site: str) -> bool:
        if self.site.endswith("*"):
            return site.startswith(self.site[:-1])
        return site == self.site

    def should_fire(self, n: int) -> bool:
        if self.prob is not None:
            return self._rng.random() < self.prob
        if self.from_call is not None and n >= self.from_call:
            return True
        return self.calls is not None and n in self.calls

    def make_exc(self, site: str, n: int, detail=None) -> BaseException:
        msg = f"chaos[{site}#{n}]" + (f" {detail}" if detail else "")
        return self.exc(msg)

    @classmethod
    def parse(cls, text: str) -> "Rule":
        parts = text.strip().split(":")
        if len(parts) != 3:
            raise ValueError(
                f"chaos rule {text!r}: want <site>:<calls>:<ExcName>")
        site, calls_s, exc_s = parts
        exc, hang_s = _EXC_REGISTRY.get(exc_s), None
        if exc is None:
            hm = re.fullmatch(r"[Hh]ang@([0-9.]+)", exc_s)
            if hm is None:
                raise ValueError(
                    f"chaos rule {text!r}: unknown exception {exc_s!r} "
                    f"(one of {sorted(_EXC_REGISTRY)} or Hang@<seconds>)")
            hang_s = float(hm.group(1))
        m = re.fullmatch(r"p([0-9.]+)@(\d+)", calls_s)
        if m:
            return cls(site, prob=float(m.group(1)), seed=int(m.group(2)),
                       exc=exc, hang_s=hang_s)
        if calls_s.endswith("+"):
            return cls(site, from_call=int(calls_s[:-1]), exc=exc,
                       hang_s=hang_s)
        calls = set()
        for tok in calls_s.split(","):
            if "-" in tok:
                a, b = tok.split("-")
                calls.update(range(int(a), int(b) + 1))
            else:
                calls.add(int(tok))
        return cls(site, calls=calls, exc=exc, hang_s=hang_s)


class Schedule:
    """A set of rules plus per-site call counters (thread-safe)."""

    def __init__(self, rules: List[Rule]):
        self.rules = list(rules)
        self.counts = {}
        self.fired = []          # [(site, call_no, exc_type_name)]
        self._lock = threading.Lock()

    @classmethod
    def coerce(cls, spec) -> "Schedule":
        if isinstance(spec, Schedule):
            return spec
        if isinstance(spec, Rule):
            return cls([spec])
        if isinstance(spec, str):
            return cls([Rule.parse(r) for r in spec.split(";") if r.strip()])
        return cls(list(spec))    # iterable of Rules

    def hit(self, site: str, detail=None):
        hangs = []
        with self._lock:
            n = self.counts.get(site, 0) + 1
            self.counts[site] = n
            for r in self.rules:
                if r.matches(site) and r.should_fire(n):
                    if r.hang_s is not None:
                        # action rule: wedge the site (sleep OUTSIDE the
                        # lock so other sites keep counting meanwhile)
                        self.fired.append((site, n, f"Hang@{r.hang_s:g}"))
                        hangs.append(r.hang_s)
                        continue
                    self.fired.append((site, n, r.exc.__name__))
                    raise r.make_exc(site, n, detail)
        for s in hangs:
            time.sleep(s)


_STACK: List[Schedule] = []
_ENV_SPEC: Optional[str] = None
_ENV_SCHED: Optional[Schedule] = None


def active_schedule() -> Optional[Schedule]:
    """The innermost `inject()` schedule, else the PADDLE_TPU_CHAOS env
    schedule (parsed once per distinct value), else None."""
    global _ENV_SPEC, _ENV_SCHED
    if _STACK:
        return _STACK[-1]
    spec = _flags.env_raw("PADDLE_TPU_CHAOS")
    if not spec:
        _ENV_SPEC = _ENV_SCHED = None
        return None
    if spec != _ENV_SPEC:
        _ENV_SPEC, _ENV_SCHED = spec, Schedule.coerce(spec)
    return _ENV_SCHED


# ---------------------------------------------------------------------------
# Site registry.  Every maybe_fail()/fail_once() site name must be declared
# here (name -> where it is compiled into the production path).  tpulint
# rule TPL053 cross-checks this table against the call sites and the table
# in docs/fault_tolerance.md, which is generated by sites_markdown().
# ---------------------------------------------------------------------------
SITES: Dict[str, str] = {}


def register_site(name: str, doc: str) -> None:
    """Declare one chaos injection site (idempotent; last doc wins)."""
    SITES[name] = doc


def registered_sites() -> Dict[str, str]:
    """name -> doc for every registered site, sorted by name."""
    return dict(sorted(SITES.items()))


def sites_markdown() -> str:
    """The docs/fault_tolerance.md site table, generated from the registry."""
    width = max(len(n) for n in SITES) + 2 if SITES else 10
    lines = [f"| {'site'.ljust(width)} | where |",
             f"|{'-' * (width + 2)}|-------|"]
    for name, doc in sorted(SITES.items()):
        lines.append(f"| {('`' + name + '`').ljust(width)} | {doc} |")
    return "\n".join(lines)


register_site("ckpt.write", "each shard write in `save_sharded`")
register_site("ckpt.rename", "the atomic commit rename")
register_site("fs.put", "`LocalFS.put/put_file`, `RemoteFS.put/put_file`")
register_site("store.req", "every `TCPStore` request, `FileStore` mutators")
register_site("step.fn", "each step of `run_with_recovery`")
register_site("serve.conn.read", "each request decode in a serve conn thread")
register_site("serve.conn.reply", "each reply send in a serve conn thread")
register_site("batcher.dispatch", "each batch the dispatcher forms")
register_site("batcher.worker", "each batch a pool worker executes")
register_site("router.forward", "each router->backend forward attempt")
register_site("router.stream_relay",
              "each stream relay attempt against one backend")
register_site("serve.stream_write",
              "each stream frame write (token or done) in decode serving")
register_site("decode.stream", "each token delivery in the decode engine")
register_site("decode.page_alloc",
              "each KV page allocation in the paged decode engine")
register_site("decode.preempt",
              "each preempt-to-host eviction in the decode engine "
              "(a raise abandons the preemption: the victim keeps "
              "decoding and the candidate is requeued)")
register_site("batcher.quota",
              "each per-tenant quota check during anchor selection "
              "(a raise defers the tenant as if quota-blocked; "
              "requests queue, never drop)")
register_site("page.migrate",
              "each host<->device page-migration batch in the KV-tier "
              "migration worker (memory/migration.py); a raise fails "
              "that batch — spill failures drop the affected cache "
              "entries, refetch failures degrade the waiting stream to "
              "a re-prefill — and a hang stalls only streams parked on "
              "those pages")
register_site("handoff.send",
              "each prefill->decode KV handoff the router orchestrates "
              "for a routed stream (inference/router.py); a raise cuts "
              "the handoff before any page ships and the stream "
              "degrades to a plain re-prefill on its decode worker, "
              "token-identically")


def maybe_fail(site: str, detail=None):
    """Injection-site hook: no-op unless a schedule arms `site`."""
    sched = active_schedule()
    if sched is not None:
        # Validated only when armed, so the idle production path stays a
        # dict lookup + None check.  An unregistered site is a programming
        # error: the registry (and docs/fault_tolerance.md generated from
        # it) must name every site compiled into the code.
        if site not in SITES:
            raise ValueError(
                f"chaos site {site!r} is not registered — add a "
                "register_site() entry in testing/chaos.py")
        sched.hit(site, detail)


class inject:
    """Context manager arming a schedule for the enclosed block.

    `spec` is a grammar string (module docstring), a Rule, an iterable
    of Rules, or a prebuilt Schedule. Yields the Schedule so tests can
    assert on `.fired` / `.counts`.
    """

    def __init__(self, spec):
        self.schedule = Schedule.coerce(spec)

    def __enter__(self) -> Schedule:
        _STACK.append(self.schedule)
        return self.schedule

    def __exit__(self, *exc):
        _STACK.pop()
        return False


def fail_once(site: str, call: int = 1, exc=OSError) -> inject:
    """Shorthand: `with chaos.fail_once("ckpt.rename"): ...`."""
    return inject(Rule(site, calls={call}, exc=exc))
