"""Quantization: QAT (fake-quant with straight-through gradients) and
post-training conversion to int8 inference layers.

Reference: the slim quantization stack —
/root/reference/python/paddle/fluid/contrib/slim/quantization/
(ImperativeQuantAware imperative/qat.py, QuantizationTransformPass,
fake_quantize_* ops in paddle/fluid/operators/fake_quantize_op.cc:
abs-max / moving-average-abs-max / channel-wise-abs-max).

TPU-native: int8 is a first-class MXU dtype — an int8 x int8 -> int32
`lax.dot_general` runs at double the bf16 rate on current TPUs, so the
converted inference layer does REAL integer matmuls (dynamic per-tensor
activation scales + per-channel weight scales), not just simulated
rounding. Fake-quant in QAT uses the straight-through estimator, exactly
the reference's fake_quantize semantics.

    model = ...                             # nn.Layer with Linear inside
    qat = QAT()                             # ImperativeQuantAware analog
    qat.quantize(model)                     # in-place: Linear -> QATLinear
    ... train as usual (fake-quant in fwd, STE in bwd) ...
    qat.convert(model)                      # QATLinear -> Int8Linear

    # or post-training (no retraining):
    ptq = PTQ()
    ptq.quantize(model)                     # observers only
    for batch in calib: model(batch)        # collect abs-max stats
    ptq.convert(model)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import nn
from ..core.tensor import Tensor, apply
from ..nn import functional as F

from .kv import (dequantize_kv, kv_pool_sds, kv_pool_zeros, quantize_kv,
                 validate_kv_dtype)
from .ptq import (SCALE_SUFFIX, dequantize_params, is_quantized,
                  quantize_params)

__all__ = ["fake_quant_abs_max", "QATLinear", "Int8Linear", "QAT", "PTQ",
           "quanted_layers",
           # serving-side PTQ (quant.ptq) + int8 KV pools (quant.kv)
           "SCALE_SUFFIX", "quantize_params", "dequantize_params",
           "is_quantized", "quantize_kv", "dequantize_kv", "kv_pool_zeros",
           "kv_pool_sds", "validate_kv_dtype"]


# ---------------------------------------------------------------------------
# fake-quant primitive (STE)
# ---------------------------------------------------------------------------

@jax.custom_vjp
def _fq(x, scale):
    q = jnp.clip(jnp.round(x / scale * 127.0), -127.0, 127.0)
    return q * scale / 127.0


def _fq_fwd(x, scale):
    return _fq(x, scale), (x, scale)


def _fq_bwd(res, g):
    x, scale = res
    # straight-through inside the clip range (reference fake_quantize
    # grad); no gradient to the scale (it is a statistic, not a weight)
    mask = (jnp.abs(x) <= scale).astype(g.dtype)
    return g * mask, jnp.zeros_like(scale)


_fq.defvjp(_fq_fwd, _fq_bwd)


def fake_quant_abs_max(x, scale=None, channel_axis=None):
    """Simulated int8 round-trip. scale=None: dynamic abs-max (per tensor,
    or per `channel_axis` slice — the channel_wise_abs_max variant)."""
    def f(raw, *maybe_scale):
        if maybe_scale:
            s = maybe_scale[0]
        elif channel_axis is not None:
            axes = tuple(i for i in range(raw.ndim) if i != channel_axis)
            s = jnp.max(jnp.abs(raw), axis=axes, keepdims=True)
        else:
            s = jnp.max(jnp.abs(raw))
        s = jnp.maximum(s, 1e-8)
        return _fq(raw, s)
    args = (x,) if scale is None else (x, scale)
    return apply(f, *args, op_name="fake_quantize_abs_max")


# ---------------------------------------------------------------------------
# QAT layer
# ---------------------------------------------------------------------------

class QATLinear(nn.Layer):
    """Linear with fake-quant on activations (moving-average abs-max, the
    reference's moving_average_abs_max observer) and weights (per-channel
    abs-max), trained with STE."""

    def __init__(self, inner, ema_decay=0.9):
        super().__init__()
        self.inner = inner
        self._decay = ema_decay
        # PTQ sets this so observers run during calibration even with the
        # model in eval() (dropout/BN must be off while stats collect —
        # tying observation to `training` would make the two mutually
        # exclusive for any model containing dropout)
        self._calibrating = False
        self.register_buffer("act_scale",
                             Tensor(np.zeros((), np.float32)))

    def forward(self, x):
        if self.training or self._calibrating:
            from ..ops.math import abs as _abs, max as _max
            cur_t = _max(_abs(x))       # this batch's dynamic abs-max
            # EMA update of the observer buffer (host-side state, mirrors
            # the reference's moving-average state variable); under jit
            # tracing the value is abstract — the buffer keeps its state
            try:
                prev = float(self.act_scale._data)
                cur_f = float(cur_t._data if hasattr(cur_t, "_data")
                              else cur_t)
                new = cur_f if prev == 0.0 else \
                    self._decay * prev + (1 - self._decay) * cur_f
                self.act_scale._data = jnp.asarray(new, jnp.float32)
            except (jax.errors.TracerArrayConversionError,
                    jax.errors.ConcretizationTypeError):
                pass
            x = fake_quant_abs_max(x)               # quantize w/ batch stat
        else:
            # frozen observer; a never-calibrated (zero) observer falls
            # back to this batch's dynamic scale instead of collapsing
            # activations to ~0
            def f(raw, s):
                dyn = jnp.maximum(jnp.max(jnp.abs(raw)), 1e-8)
                return _fq(raw, jnp.where(s > 0, s, dyn))
            x = apply(f, x, self.act_scale,
                      op_name="fake_quantize_moving_average_abs_max")
        w = fake_quant_abs_max(self.inner.weight, channel_axis=1)
        return F.linear(x, w, self.inner.bias)


# ---------------------------------------------------------------------------
# converted int8 inference layer
# ---------------------------------------------------------------------------

class Int8Linear(nn.Layer):
    """Real-int8 inference linear: int8 weights (per-out-channel scales),
    int8 activations (the calibrated observer scale when one was trained,
    else dynamic per-tensor), int32 MXU accumulation."""

    def __init__(self, weight_f32: np.ndarray, bias, act_scale=None,
                 name=None):
        super().__init__()
        w = np.asarray(weight_f32, np.float32)           # [in, out]
        w_scale = np.maximum(np.abs(w).max(axis=0), 1e-8)  # per out-channel
        w_q = np.clip(np.round(w / w_scale * 127.0), -127, 127) \
            .astype(np.int8)
        self.register_buffer("w_q", Tensor(w_q))
        self.register_buffer("w_scale",
                             Tensor(w_scale.astype(np.float32)))
        # static activation scale from QAT/PTQ calibration (0 = dynamic)
        self._static_act = (act_scale is not None
                            and float(act_scale) > 0.0)
        self.register_buffer(
            "act_scale",
            Tensor(np.float32(float(act_scale) if self._static_act
                              else 0.0)))
        self.bias = bias

    def forward(self, x):
        static = self._static_act

        def f(raw, wq, ws, a_s, *b):
            if static:
                a_scale = a_s
            else:
                a_scale = jnp.maximum(jnp.max(jnp.abs(raw)), 1e-8)
            a_q = jnp.clip(jnp.round(raw / a_scale * 127.0), -127, 127) \
                .astype(jnp.int8)
            acc = jax.lax.dot_general(
                a_q, wq, (((raw.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)
            out = acc.astype(jnp.float32) * (a_scale / 127.0) * \
                (ws / 127.0)
            if b:
                out = out + b[0]
            return out
        args = (x, self.w_q, self.w_scale, self.act_scale)
        if self.bias is not None:
            args = args + (self.bias,)
        return apply(f, *args, op_name="int8_linear")


# ---------------------------------------------------------------------------
# model rewriters (ImperativeQuantAware analog)
# ---------------------------------------------------------------------------

def _replace_children(layer, predicate, builder):
    replaced = []
    for name, child in list(layer.named_children()) \
            if hasattr(layer, "named_children") else []:
        if predicate(child):
            new = builder(child)
            setattr(layer, name, new)
            replaced.append((layer, name, new))
        elif isinstance(child, (QATLinear, Int8Linear)):
            # already-quantized wrappers hold an inner Linear; recursing
            # would wrap it a second time (double fake-quant) when
            # quantize() runs twice or PTQ follows QAT
            continue
        else:
            replaced += _replace_children(child, predicate, builder)
    return replaced


class QAT:
    """Quantization-aware training driver (ImperativeQuantAware)."""

    def __init__(self, ema_decay=0.9):
        self._decay = ema_decay

    def quantize(self, model):
        _replace_children(
            model, lambda c: isinstance(c, nn.Linear),
            lambda c: QATLinear(c, ema_decay=self._decay))
        return model

    def convert(self, model):
        """QATLinear -> Int8Linear for inference/export."""
        _replace_children(
            model, lambda c: isinstance(c, QATLinear),
            lambda c: Int8Linear(np.asarray(c.inner.weight._data),
                                 c.inner.bias,
                                 act_scale=float(c.act_scale._data)))
        model.eval()
        return model


class PTQ(QAT):
    """Post-training quantization: same observers, no training needed —
    quantize(), model.eval(), run calibration batches, convert().
    Observation is driven by a dedicated `_calibrating` flag, so
    model.eval() (required to silence dropout/BN during calibration)
    does NOT freeze the observers."""

    def quantize(self, model):
        super().quantize(model)
        for lyr in quanted_layers(model):
            lyr._calibrating = True
        return model


def quanted_layers(model):
    out = []
    for _, child in model.named_children() \
            if hasattr(model, "named_children") else []:
        if isinstance(child, (QATLinear, Int8Linear)):
            out.append(child)
        out += quanted_layers(child)
    return out
