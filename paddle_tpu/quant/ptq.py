"""Serving-side post-training quantization for decode param dicts.

The decode artifact (`save_for_decode`) stores a flat ``{name: array}``
dict. Quantization keeps that shape: an eligible weight is replaced by
its int8 tensor under the *original* key, and its per-output-channel
fp32 scale rides along under ``name + "::scale"``. Consumers that never
look for the suffix (``split_decode_params``, the npz writer, the
engine's host->device upload) work unchanged, and the decode fns in
``models.gpt`` route any matmul whose weight has a ``::scale`` sibling
through the fused dequant matmul (`ops.pallas.quant_matmul`).

Convention (symmetric, per-channel over the contraction axis)::

    scale = max(|w|, axis=-2) / 127          # shape [out] ([L, out] stacked)
    q     = clip(round(w / scale), -127, 127).astype(int8)
    w_hat = q * scale                        # |w - w_hat| <= scale / 2

Embedding tables (``wte.*`` / ``wpe.*``) and 1-D params (biases,
layernorm gains) stay fp32: the decode head reuses ``wte`` transposed,
and 1-D params are memory-trivial. In the scan-stacked layout every
block param carries a leading ``[L]`` axis, so "1-D" there means 2-D:
only ``[L, in, out]`` matmul weights quantize, a ``[L, hidden]``
stacked layernorm gain does not.
"""
from __future__ import annotations

import re
from typing import Dict

import numpy as np

SCALE_SUFFIX = "::scale"

_FP32_PREFIXES = ("wte.", "wpe.")

# "blocks.0.attn.qkv.weight" is a per-layer key; "blocks.attn.qkv.weight"
# is the scan-stacked layout where EVERY block param carries a leading
# [L] axis — there a 2-D tensor is a stacked 1-D gain (layernorm), not a
# matmul weight, and must stay fp32.
_PER_LAYER_BLOCK = re.compile(r"blocks\.\d+\.")


def _eligible(name: str, v) -> bool:
    if not name.endswith(".weight") or name.startswith(_FP32_PREFIXES):
        return False
    ndim = getattr(np.asarray(v), "ndim", 0)
    stacked = name.startswith("blocks.") and not _PER_LAYER_BLOCK.match(name)
    return ndim >= (3 if stacked else 2)


def is_quantized(params: Dict[str, object]) -> bool:
    """True if ``params`` carries any ``::scale`` sibling keys."""
    return any(k.endswith(SCALE_SUFFIX) for k in params)


def quantize_params(params: Dict[str, object]) -> Dict[str, np.ndarray]:
    """Symmetric per-channel int8 PTQ of a flat decode param dict.

    Returns a new dict: eligible ``*.weight`` tensors become int8 under
    their original key plus an fp32 ``name::scale`` sibling (reduced over
    the contraction axis, so shape ``[out]`` for 2-D weights and
    ``[L, out]`` for scan-stacked ``[L, in, out]`` weights); everything
    else is passed through as fp32/original dtype.
    """
    if is_quantized(params):
        raise ValueError("params already carry ::scale keys (double quantize)")
    out: Dict[str, np.ndarray] = {}
    for name, v in params.items():
        arr = np.asarray(v)
        if not _eligible(name, arr):
            out[name] = arr
            continue
        w = arr.astype(np.float32)
        scale = np.maximum(np.abs(w).max(axis=-2), 1e-8) / 127.0
        q = np.clip(np.rint(w / np.expand_dims(scale, -2)), -127, 127)
        out[name] = q.astype(np.int8)
        out[name + SCALE_SUFFIX] = scale.astype(np.float32)
    return out


def dequantize_params(params: Dict[str, object]) -> Dict[str, np.ndarray]:
    """Inverse of :func:`quantize_params` (up to rounding error)."""
    out: Dict[str, np.ndarray] = {}
    for name, v in params.items():
        if name.endswith(SCALE_SUFFIX):
            continue
        scale = params.get(name + SCALE_SUFFIX)
        if scale is None:
            out[name] = np.asarray(v)
        else:
            out[name] = np.asarray(v).astype(np.float32) * np.expand_dims(
                np.asarray(scale, np.float32), -2
            )
    return out
