"""Int8 KV page pools for the paged decode engine.

An fp32 pool is a bare ``[layers, pages, page_tokens, heads, head_dim]``
array; the int8 pool is the pytree ``(data int8, scale f32)`` where the
scale drops the trailing ``head_dim`` axis — one symmetric scale per
(layer, page, token row, head). Per-row scales mean a freshly written
token never forces requantization of its page, and a COW page copy is a
plain two-leaf copy. Every pool consumer (`memory.page_allocator` pool
ops, the decode fns in `models.gpt`, the engine's AOT signatures)
branches on the pytree structure at trace time, so the fp32 path traces
byte-identically to the pre-quantization code.

Byte math per element: 1 (int8 payload) + 4 / head_dim (amortized
scale) versus 4 fp32 — a 3.76x reduction at head_dim 64.
"""
from __future__ import annotations

from typing import Sequence, Tuple, Union

import jax
import jax.numpy as jnp

KV_DTYPES = ("float32", "int8")


def validate_kv_dtype(kv_dtype) -> str:
    """Normalize/validate a pool-dtype knob value ('' -> float32)."""
    s = str(kv_dtype or "float32").strip().lower()
    if s in ("float32", "fp32", "f32"):
        return "float32"
    if s == "int8":
        return "int8"
    raise ValueError(
        f"kv_dtype {kv_dtype!r}: expected one of {KV_DTYPES}"
    )


def quantize_kv(rows: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-(row, head) symmetric int8: ``[..., D] f32 -> (int8 [..., D],
    f32 scale [...])`` with ``scale = max(|row|) / 127`` (floored so an
    all-zero row quantizes to zeros, not NaNs)."""
    scale = jnp.maximum(jnp.max(jnp.abs(rows), axis=-1), 1e-8) / 127.0
    scale = scale.astype(jnp.float32)
    q = jnp.clip(jnp.round(rows / scale[..., None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def dequantize_kv(data: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_kv`: ``q * scale`` broadcast over D."""
    return data.astype(jnp.float32) * scale[..., None]


PoolLike = Union[jax.Array, Tuple[jax.Array, jax.Array]]


def kv_pool_zeros(shape: Sequence[int], kv_dtype: str = "float32") -> PoolLike:
    """Zero-initialized pool pytree for ``shape`` = [L, P, pt, nh, D]."""
    shape = tuple(int(s) for s in shape)
    if validate_kv_dtype(kv_dtype) == "int8":
        return (jnp.zeros(shape, jnp.int8), jnp.zeros(shape[:-1], jnp.float32))
    return jnp.zeros(shape, jnp.float32)


def kv_pool_sds(shape: Sequence[int], kv_dtype: str = "float32") -> PoolLike:
    """ShapeDtypeStruct pytree matching :func:`kv_pool_zeros` (warmup/AOT)."""
    shape = tuple(int(s) for s in shape)
    if validate_kv_dtype(kv_dtype) == "int8":
        return (
            jax.ShapeDtypeStruct(shape, jnp.int8),
            jax.ShapeDtypeStruct(shape[:-1], jnp.float32),
        )
    return jax.ShapeDtypeStruct(shape, jnp.float32)
