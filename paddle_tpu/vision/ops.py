"""paddle.vision.ops (reference python/paddle/vision/ops.py:25):
yolo_loss, yolo_box, deform_conv2d, DeformConv2D — thin v2-signature
facades over the nn.functional implementations."""
from __future__ import annotations

from .. import nn
from ..nn import functional as F

__all__ = ["yolo_loss", "yolo_box", "deform_conv2d", "DeformConv2D"]


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    return F.yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask,
                         class_num, ignore_thresh, downsample_ratio,
                         gt_score=gt_score,
                         use_label_smooth=use_label_smooth,
                         scale_x_y=scale_x_y)


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    return F.yolo_box(x, img_size, anchors, class_num, conf_thresh,
                      downsample_ratio, clip_bbox=clip_bbox,
                      scale_x_y=scale_x_y)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """v2 signature (weight explicit, mask None = v1)."""
    kh, kw = int(weight.shape[2]), int(weight.shape[3])
    return F.deformable_conv(x, offset, mask, int(weight.shape[0]),
                             (kh, kw), weight, bias=bias, stride=stride,
                             padding=padding, dilation=dilation,
                             groups=groups,
                             deformable_groups=deformable_groups,
                             modulated=mask is not None)


class DeformConv2D(nn.Layer):
    """Deformable conv layer (reference vision/ops.py DeformConv2D):
    owns the [out, in/groups, kh, kw] weight; offset (and mask for v2)
    arrive per call."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        from ..nn import initializer as I
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size, kernel_size)
        self._attrs = dict(stride=stride, padding=padding,
                           dilation=dilation,
                           deformable_groups=deformable_groups,
                           groups=groups)
        self._kernel = tuple(int(k) for k in ks)
        self.weight = self.create_parameter(
            [out_channels, in_channels // groups, *self._kernel],
            attr=weight_attr, default_initializer=I.XavierNormal())
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)
        if bias_attr is False:
            self.bias = None

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             mask=mask, **self._attrs)
