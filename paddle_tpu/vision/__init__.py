"""Vision: datasets, transforms, model zoo
(reference: python/paddle/vision/)."""
from . import datasets, models, transforms

__all__ = ["datasets", "models", "transforms"]
