"""Vision: datasets, transforms, model zoo
(reference: python/paddle/vision/)."""
from . import datasets, models, transforms

__all__ = ["datasets", "models", "transforms"]

from . import image, ops  # noqa: F401,E402
from .image import get_image_backend, image_load, set_image_backend  # noqa: F401,E402
