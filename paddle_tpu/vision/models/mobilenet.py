"""MobileNet V1/V2 (reference: python/paddle/vision/models/mobilenetv1.py,
mobilenetv2.py)."""
from __future__ import annotations

from ... import nn


class ConvBNReLU(nn.Sequential):
    def __init__(self, in_c, out_c, kernel, stride=1, groups=1, relu6=True):
        pad = (kernel - 1) // 2
        super().__init__(
            nn.Conv2D(in_c, out_c, kernel, stride=stride, padding=pad,
                      groups=groups, bias_attr=False),
            nn.BatchNorm2D(out_c),
            nn.ReLU6() if relu6 else nn.ReLU())


class DepthwiseSeparable(nn.Layer):
    def __init__(self, in_c, out_c1, out_c2, num_groups, stride, scale):
        super().__init__()
        self.dw = ConvBNReLU(int(in_c * scale), int(out_c1 * scale), 3,
                             stride=stride, groups=int(num_groups * scale),
                             relu6=False)
        self.pw = ConvBNReLU(int(out_c1 * scale), int(out_c2 * scale), 1,
                             relu6=False)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.scale = scale
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [  # in, out1, out2, groups, stride
            (32, 32, 64, 32, 1), (64, 64, 128, 64, 2),
            (128, 128, 128, 128, 1), (128, 128, 256, 128, 2),
            (256, 256, 256, 256, 1), (256, 256, 512, 256, 2),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 512, 512, 1),
            (512, 512, 512, 512, 1), (512, 512, 1024, 512, 2),
            (1024, 1024, 1024, 1024, 1)]
        self.conv1 = ConvBNReLU(3, int(32 * scale), 3, stride=2, relu6=False)
        self.blocks = nn.Sequential(*[
            DepthwiseSeparable(i, o1, o2, g, s, scale)
            for i, o1, o2, g, s in cfg])
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = nn.Linear(int(1024 * scale), num_classes)

    def forward(self, x):
        x = self.blocks(self.conv1(x))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


class InvertedResidual(nn.Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers.append(ConvBNReLU(inp, hidden, 1))
        layers += [
            ConvBNReLU(hidden, hidden, 3, stride=stride, groups=hidden),
            nn.Conv2D(hidden, oup, 1, bias_attr=False),
            nn.BatchNorm2D(oup)]
        self.conv = nn.Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class MobileNetV2(nn.Layer):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        input_channel = _make_divisible(32 * scale)
        features = [ConvBNReLU(3, input_channel, 3, stride=2)]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                features.append(InvertedResidual(
                    input_channel, out_c, s if i == 0 else 1, t))
                input_channel = out_c
        self.last_channel = _make_divisible(1280 * max(1.0, scale))
        features.append(ConvBNReLU(input_channel, self.last_channel, 1))
        self.features = nn.Sequential(*features)
        if with_pool:
            self.pool = nn.AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = nn.Sequential(
                nn.Dropout(0.2), nn.Linear(self.last_channel, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.classifier(x)
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("no bundled pretrained weights")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("no bundled pretrained weights")
    return MobileNetV2(scale=scale, **kwargs)
