"""Model zoo (reference: python/paddle/vision/models/ — lenet, resnet,
vgg, mobilenet v1/v2)."""
from .lenet import LeNet
from .mobilenet import (MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2)
from .resnet import (ResNet, resnet18, resnet34, resnet50, resnet101,
                     resnet152)
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19

__all__ = ["LeNet", "ResNet", "resnet18", "resnet34", "resnet50",
           "resnet101", "resnet152", "VGG", "vgg11", "vgg13", "vgg16",
           "vgg19", "MobileNetV1", "MobileNetV2", "mobilenet_v1",
           "mobilenet_v2"]

# reference submodule import paths (vision/models/{mobilenetv1,
# mobilenetv2}.py — one mobilenet module here carries both families)
from . import mobilenet as mobilenetv1  # noqa: E402
from . import mobilenet as mobilenetv2  # noqa: E402
__all__ += ["mobilenetv1", "mobilenetv2"]
