"""paddle.vision.image (reference python/paddle/vision/image.py):
image backend selection + image_load. Backends here: 'numpy' (raw
arrays / .npy) always, 'pil' when Pillow is importable — the reference's
cv2 backend has no library in this environment and raises the same
ValueError the reference gives for unknown backends."""
from __future__ import annotations

import os

import numpy as np

__all__ = ["set_image_backend", "get_image_backend", "image_load"]

_BACKEND = "numpy"


def set_image_backend(backend):
    global _BACKEND
    if backend not in ("numpy", "pil"):
        raise ValueError(
            f"Expected backend are one of ['numpy', 'pil'], but got "
            f"{backend}")
    _BACKEND = backend


def get_image_backend():
    return _BACKEND


def image_load(path, backend=None):
    """Load an image file honoring the backend contract (reference
    image_load dispatches cv2/PIL): 'numpy' accepts .npy/.npz and
    returns ndarrays; 'pil' loads through Pillow."""
    backend = backend or _BACKEND
    ext = os.path.splitext(path)[1].lower()
    if backend == "numpy":
        if ext == ".npy":
            return np.load(path)
        if ext == ".npz":
            data = np.load(path)
            return data[list(data.files)[0]]
        raise ValueError(
            f"image_load backend 'numpy' reads .npy/.npz, got {ext!r}; "
            "set_image_backend('pil') for image formats")
    try:
        from PIL import Image
    except ImportError:
        raise RuntimeError(
            "image_load backend 'pil' needs Pillow (zero-egress image; "
            "use the 'numpy' backend with .npy/.npz)")
    return Image.open(path)
