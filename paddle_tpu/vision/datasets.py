"""Vision datasets (reference: python/paddle/vision/datasets/ — mnist.py,
cifar.py, flowers.py; the reference auto-downloads via paddle.dataset).

This environment has no network egress, so constructors accept local files
(standard idx/pickle formats) and raise a clear error otherwise; FakeData
provides deterministic synthetic samples for smoke tests and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData",
           "Flowers", "VOC2012", "DatasetFolder", "ImageFolder"]


class FakeData(Dataset):
    """Deterministic synthetic images + labels (torchvision FakeData analog;
    no reference equivalent — exists because this build cannot download)."""

    def __init__(self, num_samples=1000, image_shape=(1, 28, 28),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        label = int(rng.integers(0, self.num_classes))
        # class-dependent mean so models can actually learn from it
        img = rng.normal(loc=label / self.num_classes, scale=0.3,
                         size=self.image_shape).astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return self.num_samples


def _require(path, name):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"{name}: no network egress in this environment — pass the "
            f"local file path (got {path!r}), or use "
            f"paddle_tpu.vision.datasets.FakeData for synthetic samples")
    return path


class MNIST(Dataset):
    """idx-format MNIST (reference: vision/datasets/mnist.py parses the same
    gzip idx files it downloads)."""

    NAME = "MNIST"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.mode = mode
        self.transform = transform
        _require(image_path, self.NAME)
        _require(label_path, self.NAME)
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), np.uint8).reshape(
                n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        if self.transform is not None:
            img = self.transform(self.images[idx])
        else:
            img = self.images[idx].astype(np.float32)[None]  # CHW
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "FashionMNIST"


class Cifar10(Dataset):
    """python-pickle CIFAR tarball (reference: vision/datasets/cifar.py)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        _require(data_file, "Cifar10")
        self.transform = transform
        self.mode = mode
        data, labels = [], []
        with tarfile.open(data_file) as tf:
            want = self._member_names(mode)
            names = [m for m in tf.getmembers()
                     if any(w in m.name for w in want) and m.isfile()]
            for m in sorted(names, key=lambda m: m.name):
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                data.append(d[b"data"])
                labels += list(d.get(b"labels", d.get(b"fine_labels", [])))
        self.data = np.concatenate(data).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    @staticmethod
    def _member_names(mode):
        return ("data_batch",) if mode == "train" else ("test_batch",)

    def __getitem__(self, idx):
        if self.transform is not None:
            img = self.transform(self.data[idx].transpose(1, 2, 0))
        else:
            img = self.data[idx].astype(np.float32)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    # CIFAR-100 archive members are named 'train'/'test' (not data_batch_*)
    @staticmethod
    def _member_names(mode):
        return ("train",) if mode == "train" else ("test",)


# ---------------------------------------------------------------------------
# archive / folder datasets (r2 verdict item 10)
# ---------------------------------------------------------------------------

_FLOWERS_MODE_FLAG = {"train": "trnid", "valid": "valid", "test": "tstid"}


class Flowers(Dataset):
    """Oxford 102 Flowers from the standard local archives (reference:
    vision/datasets/flowers.py — same 102flowers.tgz tarball layout
    jpg/image_%05d.jpg, imagelabels.mat, setid.mat; this build takes the
    files as paths instead of downloading)."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, backend="pil"):
        if backend not in ("pil", "cv2"):
            raise ValueError(
                f"Expected backend one of ['pil', 'cv2'], got {backend}")
        if mode.lower() not in _FLOWERS_MODE_FLAG:
            raise ValueError(f"mode must be train/valid/test, got {mode}")
        self.backend = backend
        self.flag = _FLOWERS_MODE_FLAG[mode.lower()]
        self.transform = transform
        self.data_file = _require(data_file, "Flowers(data_file=...)")
        self.label_file = _require(label_file, "Flowers(label_file=...)")
        self.setid_file = _require(setid_file, "Flowers(setid_file=...)")

        import scipy.io as scio
        self.data_tar = None      # opened lazily per process: TarFile
        self.name2mem = None      # is unpicklable (spawned DataLoader
        self._ensure_tar()        # workers re-open their own handle)
        self.labels = scio.loadmat(self.label_file)["labels"][0]
        self.indexes = scio.loadmat(self.setid_file)[self.flag][0]

    def _ensure_tar(self):
        if self.data_tar is None:
            self.data_tar = tarfile.open(self.data_file)
            self.name2mem = {m.name: m
                             for m in self.data_tar.getmembers()}

    def __getstate__(self):
        state = dict(self.__dict__)
        state["data_tar"] = None
        state["name2mem"] = None
        return state

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image
        self._ensure_tar()
        index = int(self.indexes[idx])
        label = np.array([self.labels[index - 1]])
        raw = self.data_tar.extractfile(
            self.name2mem["jpg/image_%05d.jpg" % index]).read()
        image = Image.open(_io.BytesIO(raw))
        if self.backend == "cv2":
            image = np.array(image)
        if self.transform is not None:
            image = self.transform(image)
        if self.backend == "pil":
            return image, label.astype("int64")
        return np.asarray(image, np.float32), label.astype("int64")

    def __len__(self):
        return len(self.indexes)

    def __del__(self):
        if getattr(self, "data_tar", None):
            self.data_tar.close()


class VOC2012(Dataset):
    """Pascal VOC2012 segmentation pairs from the standard local tarball
    (reference: vision/datasets/voc2012.py — VOCdevkit/VOC2012 layout:
    ImageSets/Segmentation/{train,trainval,val}.txt listing stems under
    JPEGImages/*.jpg + SegmentationClass/*.png)."""

    _SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
    _DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
    _LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
    # reference MODE_FLAG_MAP (voc2012.py:37): 'train' means the
    # combined trainval split; 'test' falls back to train.txt
    _MODE_FLAG = {"train": "trainval", "valid": "val", "test": "train",
                  "trainval": "trainval"}

    def __init__(self, data_file=None, mode="train", transform=None,
                 backend="pil"):
        if backend not in ("pil", "cv2"):
            raise ValueError(
                f"Expected backend one of ['pil', 'cv2'], got {backend}")
        if mode.lower() not in self._MODE_FLAG:
            raise ValueError(
                f"mode must be train/valid/test/trainval, got {mode}")
        self.backend = backend
        self.flag = self._MODE_FLAG[mode.lower()]
        self.transform = transform
        self.data_file = _require(data_file, "VOC2012(data_file=...)")

        self.data_tar = None      # lazy per-process (see Flowers)
        self.name2mem = None
        self._ensure_tar()
        self.data, self.labels = [], []
        listing = self.data_tar.extractfile(
            self.name2mem[self._SET_FILE.format(self.flag)])
        for line in listing:
            stem = line.strip().decode("utf-8")
            if not stem:
                continue
            self.data.append(self._DATA_FILE.format(stem))
            self.labels.append(self._LABEL_FILE.format(stem))

    def _ensure_tar(self):
        if self.data_tar is None:
            self.data_tar = tarfile.open(self.data_file)
            self.name2mem = {m.name: m
                             for m in self.data_tar.getmembers()}

    def __getstate__(self):
        state = dict(self.__dict__)
        state["data_tar"] = None
        state["name2mem"] = None
        return state

    def __getitem__(self, idx):
        import io as _io

        from PIL import Image
        self._ensure_tar()
        img = Image.open(_io.BytesIO(self.data_tar.extractfile(
            self.name2mem[self.data[idx]]).read()))
        lab = Image.open(_io.BytesIO(self.data_tar.extractfile(
            self.name2mem[self.labels[idx]]).read()))
        if self.backend == "cv2":
            img, lab = np.array(img), np.array(lab)
        if self.transform is not None:
            img = self.transform(img)
        if self.backend == "cv2":
            return (np.asarray(img, np.float32),
                    np.asarray(lab, np.float32))
        return img, lab

    def __len__(self):
        return len(self.data)

    def __del__(self):
        if getattr(self, "data_tar", None):
            self.data_tar.close()


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def _pil_loader(path):
    from PIL import Image
    with open(path, "rb") as f:
        return Image.open(f).convert("RGB")


class DatasetFolder(Dataset):
    """root/class_x/*.ext layout -> (sample, class_index) pairs
    (reference: vision/datasets/folder.py:62). Attributes: classes,
    class_to_idx, samples, targets."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        if extensions is not None and is_valid_file is not None:
            raise ValueError(
                "pass either extensions or is_valid_file, not both")
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        self.extensions = extensions if extensions is not None \
            else (None if is_valid_file else IMG_EXTENSIONS)
        classes = sorted(d.name for d in os.scandir(root) if d.is_dir())
        if not classes:
            raise RuntimeError(f"no class folders under {root!r}")
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        if is_valid_file is None:
            exts = (self.extensions,) if isinstance(
                self.extensions, str) else tuple(self.extensions)
            is_valid_file = lambda p: p.lower().endswith(exts)
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fn in sorted(files):
                    p = os.path.join(dirpath, fn)
                    if is_valid_file(p):
                        self.samples.append((p, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(
                f"found 0 files in subfolders of {root!r} with "
                f"extensions {self.extensions}")
        self.targets = [t for _, t in self.samples]

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Flat/recursive image listing WITHOUT labels (reference:
    vision/datasets/folder.py:219): every valid file under root is one
    sample; __getitem__ returns [sample]."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        if extensions is not None and is_valid_file is not None:
            raise ValueError(
                "pass either extensions or is_valid_file, not both")
        self.root = root
        self.transform = transform
        self.loader = loader or _pil_loader
        if extensions is None:
            exts = IMG_EXTENSIONS
        else:
            exts = (extensions,) if isinstance(extensions, str) \
                else tuple(extensions)
        if is_valid_file is None:
            is_valid_file = lambda p: p.lower().endswith(exts)
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fn in sorted(files):
                p = os.path.join(dirpath, fn)
                if is_valid_file(p):
                    self.samples.append(p)
        if not self.samples:
            raise RuntimeError(f"found 0 image files under {root!r}")

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)


# reference package layout (vision/datasets/{mnist,cifar,flowers,folder,
# voc2012}.py): the classes live in this one module; the names alias it
# so `paddle.vision.datasets.mnist.MNIST`-style paths resolve
import sys as _sys                                         # noqa: E402
mnist = cifar = flowers = folder = voc2012 = _sys.modules[__name__]
