"""Vision datasets (reference: python/paddle/vision/datasets/ — mnist.py,
cifar.py, flowers.py; the reference auto-downloads via paddle.dataset).

This environment has no network egress, so constructors accept local files
(standard idx/pickle formats) and raise a clear error otherwise; FakeData
provides deterministic synthetic samples for smoke tests and benchmarks.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "FakeData"]


class FakeData(Dataset):
    """Deterministic synthetic images + labels (torchvision FakeData analog;
    no reference equivalent — exists because this build cannot download)."""

    def __init__(self, num_samples=1000, image_shape=(1, 28, 28),
                 num_classes=10, transform=None, seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        label = int(rng.integers(0, self.num_classes))
        # class-dependent mean so models can actually learn from it
        img = rng.normal(loc=label / self.num_classes, scale=0.3,
                         size=self.image_shape).astype(np.float32)
        if self.transform is not None:
            img = self.transform(img)
        return img, np.int64(label)

    def __len__(self):
        return self.num_samples


def _require(path, name):
    if path is None or not os.path.exists(path):
        raise RuntimeError(
            f"{name}: no network egress in this environment — pass the "
            f"local file path (got {path!r}), or use "
            f"paddle_tpu.vision.datasets.FakeData for synthetic samples")
    return path


class MNIST(Dataset):
    """idx-format MNIST (reference: vision/datasets/mnist.py parses the same
    gzip idx files it downloads)."""

    NAME = "MNIST"

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.mode = mode
        self.transform = transform
        _require(image_path, self.NAME)
        _require(label_path, self.NAME)
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            self.images = np.frombuffer(f.read(), np.uint8).reshape(
                n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            self.labels = np.frombuffer(f.read(), np.uint8).astype(np.int64)

    def __getitem__(self, idx):
        if self.transform is not None:
            img = self.transform(self.images[idx])
        else:
            img = self.images[idx].astype(np.float32)[None]  # CHW
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = "FashionMNIST"


class Cifar10(Dataset):
    """python-pickle CIFAR tarball (reference: vision/datasets/cifar.py)."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        _require(data_file, "Cifar10")
        self.transform = transform
        self.mode = mode
        data, labels = [], []
        with tarfile.open(data_file) as tf:
            want = self._member_names(mode)
            names = [m for m in tf.getmembers()
                     if any(w in m.name for w in want) and m.isfile()]
            for m in sorted(names, key=lambda m: m.name):
                d = pickle.load(tf.extractfile(m), encoding="bytes")
                data.append(d[b"data"])
                labels += list(d.get(b"labels", d.get(b"fine_labels", [])))
        self.data = np.concatenate(data).reshape(-1, 3, 32, 32)
        self.labels = np.asarray(labels, np.int64)

    @staticmethod
    def _member_names(mode):
        return ("data_batch",) if mode == "train" else ("test_batch",)

    def __getitem__(self, idx):
        if self.transform is not None:
            img = self.transform(self.data[idx].transpose(1, 2, 0))
        else:
            img = self.data[idx].astype(np.float32)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.data)


class Cifar100(Cifar10):
    # CIFAR-100 archive members are named 'train'/'test' (not data_batch_*)
    @staticmethod
    def _member_names(mode):
        return ("train",) if mode == "train" else ("test",)
