"""Image transforms (reference: python/paddle/vision/transforms/ —
transforms.py + functional on numpy/PIL). Numpy-first: loaders feed numpy
HWC uint8/float arrays; ToTensor emits CHW float32 — device work stays in
the jitted step, host work stays in the DataLoader workers."""
from __future__ import annotations

import numbers
import random as pyrandom

import numpy as np

__all__ = ["Compose", "BaseTransform", "ToTensor", "Resize", "RandomCrop",
           "CenterCrop", "RandomHorizontalFlip", "RandomVerticalFlip",
           "Normalize", "Transpose", "BrightnessTransform",
           "ContrastTransform", "SaturationTransform", "HueTransform",
           "ColorJitter", "Pad", "RandomRotation", "Grayscale",
           "RandomResizedCrop", "to_tensor", "resize", "hflip", "vflip",
           "normalize", "crop", "center_crop", "pad"]


def _hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


# ---- functional -----------------------------------------------------------

def to_tensor(img, data_format="CHW"):
    img = _hwc(img)
    if img.dtype == np.uint8:
        img = img.astype(np.float32) / 255.0
    else:
        img = img.astype(np.float32)
    if data_format == "CHW":
        img = img.transpose(2, 0, 1)
    return img


def resize(img, size, interpolation="bilinear"):
    img = _hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h <= w:
            nh, nw = size, max(1, int(size * w / h))
        else:
            nh, nw = max(1, int(size * h / w)), size
    else:
        nh, nw = size
    if (nh, nw) == (h, w):
        return img
    # vectorised nearest/bilinear on numpy (PIL-free; loaders stay lean)
    ys = np.linspace(0, h - 1, nh)
    xs = np.linspace(0, w - 1, nw)
    if interpolation == "nearest":
        out = img[np.round(ys).astype(int)[:, None],
                  np.round(xs).astype(int)[None, :]]
    else:
        y0 = np.floor(ys).astype(int)
        x0 = np.floor(xs).astype(int)
        y1 = np.minimum(y0 + 1, h - 1)
        x1 = np.minimum(x0 + 1, w - 1)
        fy = (ys - y0)[:, None, None]
        fx = (xs - x0)[None, :, None]
        f = img.astype(np.float32)
        top = f[y0][:, x0] * (1 - fx) + f[y0][:, x1] * fx
        bot = f[y1][:, x0] * (1 - fx) + f[y1][:, x1] * fx
        out = top * (1 - fy) + bot * fy
        if img.dtype == np.uint8:
            out = np.clip(out, 0, 255).astype(np.uint8)
        else:
            out = out.astype(img.dtype)
    return out


def hflip(img):
    return _hwc(img)[:, ::-1]


def vflip(img):
    return _hwc(img)[::-1]


def crop(img, top, left, height, width):
    return _hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _hwc(img)
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    h, w = img.shape[:2]
    th, tw = output_size
    return crop(img, max(0, (h - th) // 2), max(0, (w - tw) // 2), th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    img = _hwc(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl = pr = padding[0]
        pt = pb = padding[1]
    else:
        pl, pt, pr, pb = padding
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(img, ((pt, pb), (pl, pr), (0, 0)), mode=mode, **kw)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    img = np.asarray(img, np.float32)
    mean = np.asarray(mean, np.float32)
    std = np.asarray(std, np.float32)
    if data_format == "CHW":
        shape = (-1, 1, 1)
    else:
        shape = (1, 1, -1)
    return (img - mean.reshape(shape)) / std.reshape(shape)


# ---- class transforms -----------------------------------------------------

class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, *inputs):
        if len(inputs) == 1:
            return self._apply_image(inputs[0])
        return tuple(self._apply_image(i) for i in inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img = _hwc(img)
        if self.padding is not None:
            img = pad(img, self.padding, self.fill, self.padding_mode)
        th, tw = self.size
        h, w = img.shape[:2]
        if self.pad_if_needed and (h < th or w < tw):
            # pad() unpacks (left, top, right, bottom)
            img = pad(img, (0, 0, max(0, tw - w), max(0, th - h)), self.fill,
                      self.padding_mode)
            h, w = img.shape[:2]
        if h < th or w < tw:
            raise ValueError(
                f"RandomCrop: image ({h}x{w}) smaller than crop size "
                f"({th}x{tw}); pass pad_if_needed=True")
        top = pyrandom.randint(0, h - th)
        left = pyrandom.randint(0, w - tw)
        return crop(img, top, left, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear", keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * pyrandom.uniform(*self.scale)
            ar = pyrandom.uniform(*self.ratio)
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                top = pyrandom.randint(0, h - ch)
                left = pyrandom.randint(0, w - cw)
                return resize(crop(img, top, left, ch, cw), self.size,
                              self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size,
                      self.interpolation)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return hflip(img) if pyrandom.random() < self.prob else _hwc(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        return vflip(img) if pyrandom.random() < self.prob else _hwc(img)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        return _hwc(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        super().__init__(keys)
        self.padding, self.fill, self.mode = padding, fill, padding_mode

    def _apply_image(self, img):
        return pad(img, self.padding, self.fill, self.mode)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _hwc(img)
        img = _hwc(img)
        factor = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        out = img.astype(np.float32) * factor
        return (np.clip(out, 0, 255).astype(np.uint8)
                if img.dtype == np.uint8 else out.astype(img.dtype))


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _hwc(img)
        img = _hwc(img)
        factor = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        mean = img.astype(np.float32).mean()
        out = (img.astype(np.float32) - mean) * factor + mean
        return (np.clip(out, 0, 255).astype(np.uint8)
                if img.dtype == np.uint8 else out.astype(img.dtype))


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return _hwc(img)
        img = _hwc(img)
        factor = pyrandom.uniform(max(0, 1 - self.value), 1 + self.value)
        f = img.astype(np.float32)
        gray = f.mean(axis=2, keepdims=True)
        out = gray + (f - gray) * factor
        return (np.clip(out, 0, 255).astype(np.uint8)
                if img.dtype == np.uint8 else out.astype(img.dtype))


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        # cheap hue shift via channel roll mix (full HSV omitted on purpose:
        # loaders must stay numpy-only and fast)
        if self.value == 0:
            return _hwc(img)
        img = _hwc(img)
        if img.shape[2] != 3:
            return img
        alpha = pyrandom.uniform(-self.value, self.value)
        f = img.astype(np.float32)
        out = (1 - abs(alpha)) * f + abs(alpha) * np.roll(
            f, 1 if alpha > 0 else -1, axis=2)
        return (np.clip(out, 0, 255).astype(np.uint8)
                if img.dtype == np.uint8 else out.astype(img.dtype))


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        pyrandom.shuffle(order)
        for i in order:
            img = self.ts[i]._apply_image(img)
        return img


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.fill = fill

    def _apply_image(self, img):
        img = _hwc(img)
        angle = pyrandom.uniform(*self.degrees)
        theta = np.deg2rad(angle)
        h, w = img.shape[:2]
        cy, cx = (h - 1) / 2, (w - 1) / 2
        yy, xx = np.mgrid[0:h, 0:w]
        ys = (yy - cy) * np.cos(theta) - (xx - cx) * np.sin(theta) + cy
        xs = (yy - cy) * np.sin(theta) + (xx - cx) * np.cos(theta) + cx
        yi = np.clip(np.round(ys).astype(int), 0, h - 1)
        xi = np.clip(np.round(xs).astype(int), 0, w - 1)
        out = img[yi, xi]
        invalid = (ys < 0) | (ys > h - 1) | (xs < 0) | (xs > w - 1)
        out[invalid] = self.fill
        return out


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _hwc(img)
        if img.shape[2] == 1:
            gray = img.astype(np.float32)
        else:
            gray = (0.299 * img[:, :, 0] + 0.587 * img[:, :, 1]
                    + 0.114 * img[:, :, 2]).astype(np.float32)[:, :, None]
        out = np.repeat(gray, self.num_output_channels, axis=2)
        return out.astype(img.dtype)


# reference package layout (vision/transforms/__init__.py imports the
# `transforms` and `functional` submodules): one module carries both
# the transform classes and the functional verbs here; the aliases keep
# `paddle.vision.transforms.functional.resize`-style paths working
import sys as _sys                                         # noqa: E402
transforms = functional = _sys.modules[__name__]
