"""Fluid-era compatibility aliases.

Reference surface: paddle.fluid.layers.* (fluid/layers/nn.py,
tensor.py, ops.py) — elementwise_*, reduce_*, fill_constant,
create_parameter, create_global_var, shard_index, crop_tensor, shape,
has_inf/has_nan — plus the genuinely top-level shard_index/
monkey_patch/dygraph-switch names from python/paddle/__init__.py.
Exported at the top level here as migration shims (a deliberate
superset of the reference's top-level contract: the reference keeps
most of these under paddle.fluid.layers); each delegates to the modern
op with the legacy signature adapted.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor, apply, to_tensor
from .framework import Parameter
from . import ops as _ops

__all__ = [
    "elementwise_add", "elementwise_sub", "elementwise_mul",
    "elementwise_div", "elementwise_floordiv", "elementwise_mod",
    "elementwise_pow", "elementwise_max", "elementwise_min",
    "reduce_sum", "reduce_mean", "reduce_max", "reduce_min",
    "reduce_prod", "reduce_all", "reduce_any",
    "fill_constant", "create_parameter", "create_global_var",
    "shard_index", "crop_tensor", "shape", "has_inf", "has_nan",
    "get_tensor_from_selected_rows", "enable_dygraph", "disable_dygraph",
    "in_dygraph_mode", "monkey_patch_math_varbase",
    "monkey_patch_variable", "get_cuda_rng_state", "set_cuda_rng_state",
    "get_cudnn_version", "is_compiled_with_xpu",
]


def _axis_broadcast(y, x_ndim, y_ndim, axis):
    """fluid elementwise axis semantics: y's dims align to x starting at
    `axis` (default -1 = trailing alignment, the numpy rule)."""
    if axis == -1 or axis is None or y_ndim == 0:
        return y
    pad_right = x_ndim - axis - y_ndim
    if pad_right <= 0:
        return y
    return y.reshape(tuple(y.shape) + (1,) * pad_right)


def _elementwise(fn, op_tag):
    def op(x, y, axis=-1, act=None, name=None):
        def f(a, b):
            b = _axis_broadcast(b, a.ndim, b.ndim, axis)
            out = fn(a, b)
            if act == "relu":
                out = jnp.maximum(out, 0)
            elif act is not None:
                raise ValueError(f"{op_tag}: act supports relu/None")
            return out
        return apply(f, x, y, op_name=op_tag)
    op.__name__ = op_tag
    op.__doc__ = (f"Legacy {op_tag} (reference python/paddle/__init__.py "
                  "fluid.layers re-export) with axis-aligned broadcast.")
    return op


elementwise_add = _elementwise(jnp.add, "elementwise_add")
elementwise_sub = _elementwise(jnp.subtract, "elementwise_sub")
elementwise_mul = _elementwise(jnp.multiply, "elementwise_mul")
elementwise_div = _elementwise(jnp.divide, "elementwise_div")
elementwise_floordiv = _elementwise(jnp.floor_divide, "elementwise_floordiv")
elementwise_mod = _elementwise(jnp.mod, "elementwise_mod")
elementwise_pow = _elementwise(jnp.power, "elementwise_pow")
elementwise_max = _elementwise(jnp.maximum, "elementwise_max")
elementwise_min = _elementwise(jnp.minimum, "elementwise_min")


def _reduce(fn, op_tag):
    def op(input, dim=None, keep_dim=False, name=None):
        axis = tuple(dim) if isinstance(dim, (list, tuple)) else dim

        def f(a):
            return fn(a, axis=axis, keepdims=keep_dim)
        return apply(f, input, op_name=op_tag)
    op.__name__ = op_tag
    op.__doc__ = f"Legacy {op_tag}(input, dim, keep_dim) reduction."
    return op


reduce_sum = _reduce(jnp.sum, "reduce_sum")
reduce_mean = _reduce(jnp.mean, "reduce_mean")
reduce_max = _reduce(jnp.max, "reduce_max")
reduce_min = _reduce(jnp.min, "reduce_min")
reduce_prod = _reduce(jnp.prod, "reduce_prod")
reduce_all = _reduce(jnp.all, "reduce_all")
reduce_any = _reduce(jnp.any, "reduce_any")


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    """Legacy fill_constant -> full (fluid/layers/tensor.py)."""
    if isinstance(shape, Tensor):
        shape = [int(v) for v in np.asarray(shape.numpy()).ravel()]
    res = _ops.creation.full(shape, value, dtype=dtype)
    if out is not None:
        out.set_value(np.asarray(res.numpy()))
        return out
    return res


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone trainable parameter (fluid/layers/tensor.py
    create_parameter)."""
    from .framework import ParamAttr
    from .nn import initializer as I
    attr = ParamAttr._to_attr(attr)
    init = None
    if attr is not None and attr is not False:
        init = attr.initializer
    init = init or default_initializer or (
        I.Constant(0.0) if is_bias else I.XavierNormal())
    data = init(tuple(int(s) for s in shape), dtype)
    return Parameter(data, name=name, trainable=True)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Persistent scalar/tensor variable (fluid create_global_var)."""
    return to_tensor(np.full([int(s) for s in shape], value,
                             np.dtype(dtype)), stop_gradient=True)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    """Re-map global ids into a shard's local range (reference
    paddle.shard_index): ids in [shard_id*size, (shard_id+1)*size) map to
    id - shard_id*size, everything else to ignore_value."""
    size = (int(index_num) + int(nshards) - 1) // int(nshards)
    lo = int(shard_id) * size

    def f(a):
        local = a - lo
        ok = (a >= lo) & (a < lo + size)
        return jnp.where(ok, local, ignore_value)
    return apply(f, input, op_name="shard_index")


def crop_tensor(x, shape=None, offsets=None, name=None):
    """Legacy crop_tensor -> ops.crop."""
    return _ops.manipulation.crop(x, shape=shape, offsets=offsets)


def shape(input):
    """Shape as an int32 tensor (fluid/layers/nn.py shape op)."""
    return to_tensor(np.asarray(input.shape, np.int32))


def has_inf(x):
    def f(a):
        return jnp.isinf(a).any().reshape(1)
    return apply(f, x, op_name="has_inf")


def has_nan(x):
    def f(a):
        return jnp.isnan(a).any().reshape(1)
    return apply(f, x, op_name="has_nan")


def get_tensor_from_selected_rows(x, name=None):
    """SelectedRows value rows as a dense tensor (fluid
    get_tensor_from_selected_rows)."""
    from .core.selected_rows import SelectedRows
    if not isinstance(x, SelectedRows):
        raise TypeError("expects a SelectedRows")
    return to_tensor(np.asarray(x.value))


# --- dygraph mode switches ---------------------------------------------------
# This framework is always eager (imperative over jax); to_static/jit
# handles the graph path. The switches keep import-compatibility and are
# observable through in_dygraph_mode.

_DYGRAPH = {"on": True}


def enable_dygraph(place=None):
    _DYGRAPH["on"] = True


def disable_dygraph():
    _DYGRAPH["on"] = False


def in_dygraph_mode():
    return _DYGRAPH["on"]


def monkey_patch_math_varbase():
    """No-op: Tensor already carries the full math surface (the
    reference patches methods onto VarBase at import)."""


def monkey_patch_variable():
    """No-op: see monkey_patch_math_varbase."""


def get_cuda_rng_state():
    """Maps to the device RNG state (no CUDA here; reference
    get_cuda_rng_state)."""
    from .core import random as random_mod
    return random_mod.get_rng_state()


def set_cuda_rng_state(state):
    from .core import random as random_mod
    random_mod.set_rng_state(state)


def get_cudnn_version():
    """None: no cuDNN on TPU (reference returns None when CUDA is
    absent)."""
    return None


def is_compiled_with_xpu():
    return False
