"""paddle.distributed parity surface — built out in stages:
env/collective/parallel (DP) first, fleet strategy layer, sharding,
pipeline, launcher, PS. See SURVEY.md §2 rows 26-38."""
from . import env  # noqa: F401
from .mesh import build_mesh, get_mesh, named_sharding, set_mesh
from .env import ParallelEnv, get_rank, get_world_size  # noqa: F401
