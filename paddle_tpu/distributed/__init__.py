"""paddle.distributed parity surface (SURVEY.md §2 rows 26-38)."""
from . import collective, env, fleet, sharding  # noqa: F401
from .collective import (ReduceOp, all_gather, all_reduce, alltoall,  # noqa: F401
                         barrier, broadcast, get_group, new_group, p2p,
                         recv, reduce, reduce_scatter, scatter, send)
from .env import get_rank, get_world_size, init_distributed  # noqa: F401
from .mesh import build_mesh, get_mesh, named_sharding, set_mesh  # noqa: F401
from .parallel import DataParallel, ParallelEnv, init_parallel_env  # noqa: F401
from .pipeline import PipelineLayer, pipeline_spmd, stack_stage_params  # noqa: F401

init = init_parallel_env  # paddle.distributed alias surface

# dataset readers at the distributed path (reference
# python/paddle/distributed/__init__.py:40-47 re-exports the fleet
# dataset family)
from ..io.data_feed import InMemoryDataset, QueueDataset  # noqa: F401,E402
from . import cloud_utils  # noqa: F401,E402  (PaddleCloud env discovery)


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    """paddle.distributed.spawn parity (reference spawn.py:317) —
    multiprocessing fan-out with the PADDLE_* env protocol. With
    join=False returns the process list for the caller to join."""
    import multiprocessing as mp
    ctx = mp.get_context("spawn")
    procs = []
    for rank in range(nprocs):
        env = {"PADDLE_TRAINER_ID": str(rank),
               "PADDLE_TRAINERS_NUM": str(nprocs)}
        p = ctx.Process(target=_spawn_entry, args=(func, args, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    if not join:
        return procs
    for p in procs:
        p.join()
    if any(p.exitcode != 0 for p in procs):
        raise RuntimeError(
            f"spawn: worker exit codes {[p.exitcode for p in procs]}")


def _spawn_entry(func, args, env):
    import os
    os.environ.update(env)
    func(*args)

from . import elastic  # noqa: F401
from . import sequence_parallel  # noqa: F401

from .store import Store, TCPStore, FileStore  # noqa: F401
from .entry_attr import CountFilterEntry, EntryAttr, ProbabilityEntry  # noqa: F401,E402
