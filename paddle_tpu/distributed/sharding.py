"""ZeRO-style sharding (reference: ShardingOptimizer
fleet/meta_optimizers/sharding_optimizer.py:33, algorithm detailed in
SURVEY.md §8.1 — segment program, broadcast non-owned params, allreduce
grads, prune non-owned optimizer ops).

TPU-native redesign: instead of rewriting a program with c_broadcast /
c_allreduce_sum ops, sharding is *data placement*. Stage semantics:

  stage 1 — optimizer states sharded over the axis; grads allreduced.
  stage 2 — optimizer states AND grads sharded: grads leave the backward
            as reduce_scatter (XLA emits it when the grad out_sharding is
            sharded while the loss is replicated... in practice we thread
            explicit psum_scatter inside the apply step under shard_map).
  stage 3 — parameters sharded too; allgather on use (XLA inserts it from
            in_shardings).

`shard_specs` assigns each array a PartitionSpec over `axis` by its first
dimension divisible by the axis size (round-robin-by-size analog of
sharding/shard.py — here the "assignment" is a dimension split, which on
TPU keeps every rank's MXU busy instead of idling non-owners).
"""
from __future__ import annotations

import math
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from . import mesh as mesh_mod

__all__ = ["shard_specs", "shard_params_and_state", "group_by_stage",
           "build_sharded_update"]


def _first_divisible_dim(shape, n, start=0):
    for i, d in enumerate(shape):
        if i >= start and d % n == 0 and d >= n:
            return i
    return None


def shard_specs(arrays: Dict[str, jax.Array], axis: str, n: int,
                min_size: int = 1024, skip_leading=()) -> Dict[str, P]:
    """PartitionSpec per array: split the first dim divisible by the axis
    size; small or indivisible arrays stay replicated (paddle's shard.py
    keeps whole params per rank; dimension-splitting is strictly more
    parallel and what pjit wants).

    ``skip_leading`` names arrays whose dim 0 must stay whole — the
    scan-stacked ``[layers, ...]`` params, where dim 0 is a lax.scan xs
    axis (splitting it puts the loop counter into partitioned
    dynamic-slice index arithmetic inside the scan transpose, which XLA's
    SPMD partitioner miscompiles under x64); the split moves to the first
    divisible per-block dim instead."""
    specs = {}
    for name, v in arrays.items():
        shape = tuple(getattr(v, "shape", ()))
        size = math.prod(shape) if shape else 0
        dim = _first_divisible_dim(shape, n,
                                   start=1 if name in skip_leading else 0)
        if dim is None or size < min_size:
            specs[name] = P(*([None] * len(shape)))
        else:
            spec = [None] * len(shape)
            spec[dim] = axis
            specs[name] = P(*spec)
    return specs


def shard_params_and_state(params, opt_state, mesh, axis="dp", stage=2,
                           min_size: int = 1024):
    """NamedShardings for (params, opt_state) per ZeRO stage."""
    n = int(mesh.shape[axis])
    pspecs = shard_specs(params, axis, n, min_size)
    rep = {k: P(*([None] * getattr(v, "ndim", 0))) for k, v in params.items()}
    param_spec = pspecs if stage >= 3 else rep

    def state_spec_for(name, slot, v):
        vshape = tuple(getattr(v, "shape", ()))
        if stage >= 1 and vshape == tuple(params[name].shape):
            return pspecs[name]
        return P(*([None] * len(vshape)))

    p_sh = {k: NamedSharding(mesh, param_spec[k]) for k in params}
    s_sh = {name: {slot: NamedSharding(mesh, state_spec_for(name, slot, v))
                   for slot, v in st.items()}
            for name, st in opt_state.items()}
    return p_sh, s_sh, pspecs


def group_by_stage(stage: int):
    return {"shard_optimizer": stage >= 1, "shard_grads": stage >= 2,
            "shard_params": stage >= 3}


def build_sharded_update(optimizer, params, mesh, axis="dp", stage=2,
                         min_size: int = 1024):
    """Build a jitted (params, grads, opt_state, lr) -> (params', state')
    whose arrays carry ZeRO shardings. XLA derives the collectives:
    grads enter replicated (from a dp-mean) and are resharded to the
    state's sharding (reduce_scatter for stage>=2); stage 3 params leave
    allgathered on use at the next forward."""
    opt_state = optimizer.functional_init(params)
    p_sh, s_sh, pspecs = shard_params_and_state(params, opt_state, mesh,
                                                axis, stage, min_size)
    g_sh = {k: (p_sh[k] if stage < 3 else
                NamedSharding(mesh, pspecs[k])) for k in params}
    if stage >= 2:
        g_sh = {k: NamedSharding(mesh, pspecs[k]) for k in params}

    def update(p, g, s, lr):
        return optimizer.functional_update(p, g, s, lr=lr)

    jitted = jax.jit(update,
                     in_shardings=(p_sh, g_sh, s_sh, None),
                     out_shardings=(p_sh, s_sh),
                     donate_argnums=(0, 2))
    return jitted, (p_sh, g_sh, s_sh)
