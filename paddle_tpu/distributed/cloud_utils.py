"""Cloud-environment cluster discovery (reference
python/paddle/distributed/cloud_utils.py:20 get_cloud_cluster, :101
get_cluster_and_pod): PaddleCloud exports the cluster topology through
PADDLE_* env vars; these helpers parse it into a (cluster, pod)
description the launcher consumes.

TPU-native note: on TPU pods the runtime (GKE/queued resources) plays
PaddleCloud's role, but the env protocol is kept verbatim so cloud
launch scripts port over — the same names feed `jax.distributed`
bootstrap in distributed/env.py."""
from __future__ import annotations

import dataclasses
import os
from typing import List

__all__ = ["Pod", "Cluster", "get_cloud_cluster", "get_cluster_and_pod",
           "get_trainers_num"]


@dataclasses.dataclass
class Pod:
    """One node's slice of the cluster: its rank, address, and the
    trainer endpoints it hosts (reference distributed/utils.py Pod)."""
    rank: int
    addr: str
    trainer_endpoints: List[str]

    def trainers_num(self) -> int:
        return len(self.trainer_endpoints)


@dataclasses.dataclass
class Cluster:
    """All pods (reference distributed/utils.py Cluster)."""
    pods: List[Pod]

    def trainers_num(self) -> int:
        return sum(p.trainers_num() for p in self.pods)

    def trainers_endpoints(self) -> List[str]:
        return [ep for p in self.pods for ep in p.trainer_endpoints]

    def pods_endpoints(self) -> List[str]:
        return [p.trainer_endpoints[0] for p in self.pods]


def _require(name):
    v = os.getenv(name)
    if v is None:
        raise RuntimeError(
            f"{name} should not be None — the cloud launcher exports it "
            "(reference cloud_utils.get_cloud_cluster asserts the same)")
    return v


def get_cloud_cluster(args_node_ips=None, args_node_ip=None,
                      args_port=6170, selected_devices=None):
    """Build the Cluster/Pod pair from the PaddleCloud env protocol:
    PADDLE_TRAINERS (node ip list), POD_IP, PADDLE_TRAINER_ID,
    TRAINER_PORTS_NUM (ports per node). `selected_devices` sizes the
    per-node trainer count (defaults to one per port).

    Endpoint precedence matches the reference (cloud_utils.py:53-60):
    DISTRIBUTED_TRAINER_ENDPOINTS, when the cloud exports it, IS the
    endpoint list (the ports the cloud actually allocated); otherwise
    ports are synthesized from PADDLE_PORT, falling back to args_port."""
    import warnings

    node_ips = _require("PADDLE_TRAINERS").split(",")
    node_ip = _require("POD_IP")
    node_rank = int(_require("PADDLE_TRAINER_ID"))
    if selected_devices:
        n_per_node = len(selected_devices)
    else:
        n_per_node = int(_require("TRAINER_PORTS_NUM"))
    base_port = int(os.getenv("PADDLE_PORT") or args_port or 6170)
    # the reference warns when launch args disagree with the cloud env
    # (env wins); keep that diagnostic rather than silently ignoring
    if args_node_ips and (sorted(str(args_node_ips).split(","))
                          != sorted(node_ips)):
        warnings.warn(
            f"--ips {args_node_ips} differs from PADDLE_TRAINERS "
            f"{node_ips}; the cloud env wins (reference behavior)")
    if args_node_ip and args_node_ip != node_ip:
        warnings.warn(
            f"--node_ip {args_node_ip} differs from POD_IP {node_ip}; "
            "the cloud env wins (reference behavior)")

    ep_env = os.getenv("DISTRIBUTED_TRAINER_ENDPOINTS")
    if ep_env:
        # cloud-allocated endpoints: n_per_node consecutive entries per
        # node, in PADDLE_TRAINERS order (reference layout)
        eps_all = [e.strip() for e in ep_env.split(",") if e.strip()]
        if len(eps_all) != len(node_ips) * n_per_node:
            raise RuntimeError(
                f"DISTRIBUTED_TRAINER_ENDPOINTS has {len(eps_all)} "
                f"entries, want {len(node_ips)} nodes x {n_per_node} "
                "trainers")
        chunks = [eps_all[i * n_per_node:(i + 1) * n_per_node]
                  for i in range(len(node_ips))]
    else:
        chunks = [[f"{ip}:{base_port + i}" for i in range(n_per_node)]
                  for ip in node_ips]
    pods = []
    for rank, (ip, eps) in enumerate(zip(node_ips, chunks)):
        pods.append(Pod(rank=rank, addr=ip, trainer_endpoints=eps))
    cluster = Cluster(pods=pods)
    if node_ip not in node_ips or node_rank >= len(pods):
        raise RuntimeError(
            f"POD_IP {node_ip} / PADDLE_TRAINER_ID {node_rank} not "
            f"consistent with PADDLE_TRAINERS {node_ips}")
    return cluster, pods[node_rank]


def get_trainers_num() -> int:
    """PADDLE_TRAINERS_NUM with a single-node default (reference
    cloud_utils._get_trainers_num)."""
    return int(os.getenv("PADDLE_TRAINERS_NUM", "1"))


def get_cluster_and_pod(args):
    """The launch-time entry (reference cloud_utils.get_cluster_and_pod):
    cloud env wins when present, else a single-node cluster from args
    (args needs .node_ip/.port/.selected_devices attrs or dict keys)."""
    def _arg(name, default=None):
        if isinstance(args, dict):
            return args.get(name, default)
        return getattr(args, name, default)

    if os.getenv("PADDLE_TRAINERS"):
        return get_cloud_cluster(
            _arg("node_ips"), _arg("node_ip"), _arg("port", 6170),
            _arg("selected_devices"))
    ip = _arg("node_ip", "127.0.0.1")
    port = int(_arg("port", 6170))
    devices = _arg("selected_devices") or [0]
    pod = Pod(rank=0, addr=ip,
              trainer_endpoints=[f"{ip}:{port + i}"
                                 for i in range(len(devices))])
    return Cluster(pods=[pod]), pod
