"""Rendezvous stores: TCPStore (native C++ server) and FileStore.

Reference: the gloo store layer fleet role makers rendezvous through —
HdfsStore / http / file stores behind gloo_wrapper
(/root/reference/paddle/fluid/framework/fleet/gloo_wrapper.h:113,
platform/gloo_context.cc, python fleet/base/role_maker.py). The TPU
collective path needs none of this (jax.distributed.initialize is the
comm-id bootstrap); the store serves everything control-plane: PS
worker/server rendezvous, elastic launcher state, user-level barriers.

    store = TCPStore.start()            # or TCPStore("host:port")
    store.set("k", b"v"); store.wait("k")
    store.add("counter", 1)
    store.barrier("init", world_size=8, rank=rank)
"""
from __future__ import annotations

import os
import socket
import struct
import subprocess
import time

from ...core import flags as _flags
from ...testing import chaos
from ...utils.retry import (WatchdogTimeout, backoff_delays,
                            call_with_watchdog)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")

__all__ = ["Store", "TCPStore", "FileStore", "BarrierTimeout"]


class BarrierTimeout(TimeoutError):
    """A store barrier did not release within its wall-clock bound
    (missing peer, wedged server, or lost release key)."""

_UNSET = object()   # wait(): distinguish "omitted" from "None = forever"


def build_store_binary(force=False) -> str:
    """Compile native/tcp_store.cpp once (g++ -O2); returns binary path."""
    src = os.path.join(_NATIVE_DIR, "tcp_store.cpp")
    out = os.path.join(_NATIVE_DIR, "tcp_store")
    if force or (not os.path.exists(out)
                 or os.path.getmtime(out) < os.path.getmtime(src)):
        cmd = ["g++", "-O2", "-std=c++17", "-pthread", "-o", out, src]
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(f"tcp_store build failed:\n{res.stderr}")
    return out


class Store:
    """Key-value store interface (gloo-store analog).

    Concrete stores implement set/get/wait/add/delete_key/num_keys;
    barrier() is derived (gloo-style ADD + WAIT)."""

    def set(self, key: str, value: bytes):
        raise NotImplementedError

    def get(self, key: str):
        """Value bytes, or None when the key is absent (non-blocking)."""
        raise NotImplementedError

    def wait(self, key: str, timeout: float | None = None) -> bytes:
        raise NotImplementedError

    def add(self, key: str, delta: int = 1) -> int:
        raise NotImplementedError

    def delete_key(self, key: str) -> bool:
        raise NotImplementedError

    def num_keys(self) -> int:
        raise NotImplementedError

    def barrier(self, name: str, world_size: int, rank: int = 0,
                timeout: float | None = 60.0):
        """All `world_size` callers block until everyone arrived.

        Reusable: the arrival counter only ever grows; each round of
        `world_size` arrivals releases its own epoch key, so calling the
        same barrier name every training step keeps synchronizing.

        With a finite `timeout` the whole arrival runs under a
        wall-clock watchdog (utils.retry.call_with_watchdog): even if a
        store RPC wedges past its own deadline, the caller gets a typed
        `BarrierTimeout` instead of blocking forever (the abandoned
        worker thread is a daemon and dies with the process)."""

        def _arrive():
            n = self.add(f"__barrier__/{name}/count", 1)
            epoch = (n - 1) // world_size
            if n == (epoch + 1) * world_size:  # last arrival of the round
                self.set(f"__barrier__/{name}/go/{epoch}", b"1")
            self.wait(f"__barrier__/{name}/go/{epoch}", timeout=timeout)

        if timeout is None:
            return _arrive()
        try:
            # small grace over the inner wait deadline so the watchdog
            # only fires when a call truly hangs past its own timeout
            call_with_watchdog(_arrive, timeout + 5.0,
                               what=f"barrier {name!r}")
        except (WatchdogTimeout, TimeoutError) as e:
            raise BarrierTimeout(
                f"barrier {name!r} (world_size={world_size}, rank={rank}) "
                f"not released within {timeout}s") from e

    def delete_barrier(self, name: str, max_epochs: int = 1):
        """Reclaim a barrier's keys (the schema is private to this class).
        Only safe once no caller can still be waiting on `name` — e.g.
        after a later barrier proved everyone moved on."""
        self.delete_key(f"__barrier__/{name}/count")
        for e in range(max_epochs):
            self.delete_key(f"__barrier__/{name}/go/{e}")


class TCPStore(Store):
    """Client for the native tcp_store server; `TCPStore.start()` also
    owns a server process (the rank-0 pattern)."""

    def __init__(self, endpoint: str, timeout: float = 60.0,
                 retries: int = None):
        self.endpoint = endpoint
        self._timeout = timeout
        self._retries = (int(_flags.env_value("PADDLE_TPU_STORE_RETRIES"))
                         if retries is None else retries)
        self._sock = self._connect()
        self._proc = None

    def _connect(self):
        host, port = self.endpoint.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)),
                                        timeout=self._timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock

    @classmethod
    def start(cls, port: int = 0, timeout: float = 60.0) -> "TCPStore":
        binary = build_store_binary()
        proc = subprocess.Popen([binary, str(port)],
                                stdout=subprocess.PIPE, text=True)
        line = proc.stdout.readline().strip()
        if not line.startswith("STORE_LISTENING"):
            raise RuntimeError(f"tcp_store failed to start: {line!r}")
        store = cls(f"127.0.0.1:{int(line.split()[1])}", timeout=timeout)
        store._proc = proc
        return store

    # -- wire --------------------------------------------------------------
    def _req_once(self, verb: int, key: str, n: int, payload: bytes, sock):
        chaos.maybe_fail("store.req", f"verb={verb} key={key}")
        kb = key.encode()
        sock.sendall(struct.pack("<BIQ", verb, len(kb), n) + kb + payload)
        status = self._recv_exact(1, sock)[0]
        (m,) = struct.unpack("<Q", self._recv_exact(8, sock))
        body = self._recv_exact(m, sock) if m else b""
        return status, body

    def _req(self, verb: int, key: str, n: int = 0, payload: bytes = b"",
             sock=None):
        if sock is not None:     # caller-owned socket (WAIT): single shot
            return self._req_once(verb, key, n, payload, sock)
        # transient connection faults reconnect + retry with backoff.
        # Caveat (documented, docs/fault_tolerance.md): a fault after the
        # request was sent but before the reply retries the verb, so ADD
        # is at-least-once under retry — rendezvous counters tolerate
        # over-count (a gang member counted twice releases the barrier
        # early only for itself to then wait on the next epoch key).
        delays = backoff_delays(self._retries, base_delay=0.05,
                                max_delay=1.0)
        attempt = 0
        while True:
            try:
                if self._sock is None:     # reconnect is retried too
                    self._sock = self._connect()
                return self._req_once(verb, key, n, payload, self._sock)
            except (ConnectionError, TimeoutError, OSError):
                attempt += 1
                if attempt > self._retries:
                    raise
                if self._sock is not None:
                    try:
                        self._sock.close()
                    except OSError:
                        pass
                    self._sock = None
                time.sleep(next(delays))

    def _recv_exact(self, n: int, sock=None) -> bytes:
        from ...utils.net import recv_exact
        return recv_exact(sock or self._sock, n, what="tcp_store")

    # -- Store interface ---------------------------------------------------
    def set(self, key, value):
        st, _ = self._req(1, key, len(value), bytes(value))
        if st:
            raise RuntimeError("tcp_store SET failed")

    def get(self, key):
        st, body = self._req(2, key)
        return None if st else body

    def wait(self, key, timeout=_UNSET):
        # omitted -> constructor default; explicit None -> block forever
        # (matches FileStore semantics; wire ms=0 means no deadline)
        timeout = self._timeout if timeout is _UNSET else timeout
        ms = 0 if timeout is None else max(int(timeout * 1000), 1)
        # the blocking WAIT holds this connection; use a fresh socket so a
        # concurrent set/add from the same client can't deadlock
        host, port = self.endpoint.rsplit(":", 1)
        with socket.create_connection(
                (host, int(port)),
                timeout=None if timeout is None else timeout + 5) as sock:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            st, body = self._req(3, key, ms, sock=sock)
        if st:
            raise TimeoutError(f"tcp_store WAIT({key!r}) timed out")
        return body

    def add(self, key, delta=1):
        st, body = self._req(4, key, 8, struct.pack("<q", delta))
        if st:
            raise RuntimeError("tcp_store ADD failed")
        return struct.unpack("<q", body)[0]

    def delete_key(self, key):
        st, _ = self._req(5, key)
        return st == 0

    def num_keys(self):
        _, body = self._req(6, key="")
        return struct.unpack("<Q", body)[0]

    def stop_server(self):
        try:
            self._req(7, "")
        except (ConnectionError, OSError):
            pass
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._proc.terminate()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if self._proc is not None:
            self.stop_server()
        if self._sock is not None:
            self._sock.close()


class FileStore(Store):
    """Shared-filesystem store (gloo FileStore analog) — zero-server
    rendezvous when ranks share an NFS/GCS-fuse path."""

    def __init__(self, path: str):
        self._dir = path
        os.makedirs(path, exist_ok=True)

    def _fn(self, key):
        return os.path.join(self._dir, key.replace("/", "%2F"))

    def set(self, key, value):
        # chaos site on the mutating verbs only (wait() polls get(), so
        # arming reads would make injection counts nondeterministic)
        chaos.maybe_fail("store.req", f"set {key}")
        tmp = self._fn(key) + ".tmp"
        with open(tmp, "wb") as f:
            f.write(bytes(value))
        os.replace(tmp, self._fn(key))     # atomic publish

    def get(self, key):
        try:
            with open(self._fn(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def wait(self, key, timeout=60.0):
        deadline = None if timeout is None else time.time() + timeout
        while True:
            v = self.get(key)
            if v is not None:
                return v
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(f"FileStore WAIT({key!r}) timed out")
            time.sleep(0.02)

    # a holder that crashes between lock and unlock must not wedge every
    # later add(): locks older than this are presumed orphaned and broken
    _LOCK_STALE_S = 10.0

    def add(self, key, delta=1):
        chaos.maybe_fail("store.req", f"add {key}")
        # lock via atomic O_EXCL lockfile (NFS-safe enough for rendezvous)
        lock = self._fn(key) + ".lock"
        token = f"{os.getpid()} {time.time_ns()} {id(self)}".encode()
        deadline = time.time() + 60.0
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, token)
                os.close(fd)
                break
            except FileExistsError:
                try:
                    age = time.time() - os.path.getmtime(lock)
                    if age > self._LOCK_STALE_S:
                        # atomic reclaim: rename first so exactly ONE
                        # waiter wins — a bare unlink could delete a
                        # FRESH lock created after our staleness check,
                        # admitting two writers
                        grave = f"{lock}.reclaim.{os.getpid()}-" \
                                f"{time.time_ns()}"
                        try:
                            os.rename(lock, grave)
                            # TOCTOU re-check: between our staleness stat
                            # and the rename, the stale holder may have
                            # released and ANOTHER waiter O_EXCL-created
                            # a fresh lock — which we just stole. If the
                            # grave is fresh, put it back and retry.
                            fresh = (time.time() - os.path.getmtime(grave)
                                     <= self._LOCK_STALE_S)
                            if fresh:
                                # no-clobber restore via hardlink (EEXIST
                                # = yet another waiter already locked;
                                # residual race is then the original
                                # holder's — documented). Filesystems
                                # without hardlinks fall back to rename,
                                # accepting the tiny clobber window.
                                try:
                                    os.link(grave, lock)
                                except FileExistsError:
                                    pass
                                except OSError:
                                    try:
                                        os.rename(grave, lock)
                                        continue
                                    except OSError:
                                        pass
                            try:
                                os.unlink(grave)
                            except OSError:
                                pass
                        except OSError:
                            pass        # another waiter won the rename
                        continue
                except OSError:
                    pass                # holder released it meanwhile
                if time.time() > deadline:
                    raise TimeoutError(f"FileStore ADD lock on {key!r}")
                time.sleep(0.01)
        try:
            cur = self.get(key)
            now = (int.from_bytes(cur, "little", signed=True)
                   if cur else 0) + delta
            self.set(key, now.to_bytes(8, "little", signed=True))
            return now
        finally:
            # release only OUR lock: if a reclaimer stole it mid-section
            # (we stalled past the staleness window), the current file
            # belongs to someone else
            try:
                with open(lock, "rb") as f:
                    mine = f.read() == token
            except OSError:
                mine = False
            if mine:
                try:
                    os.unlink(lock)
                except OSError:
                    pass

    def delete_key(self, key):
        try:
            os.unlink(self._fn(key))
            return True
        except FileNotFoundError:
            return False

    def num_keys(self):
        return sum(1 for f in os.listdir(self._dir)
                   if not f.endswith((".tmp", ".lock")))
