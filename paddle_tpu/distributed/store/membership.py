"""TTL'd fleet membership over a rendezvous :class:`Store`.

The serving fleet needs backends to join and leave a running router
without supervisor edits — the gen_comm_id_helper pattern (TCP
bootstrap exchanging endpoints) generalized into a tiny group registry
any :class:`Store` can back (TCPStore in production, FileStore in
tests).

The store interface has no key-scan verb, so the schema is a counter
plus per-slot keys under ``__members__/<group>/``:

    nslots          ADD counter; each publisher claims slot ``add(+1)``
    slot/<i>        JSON record {"key": "host:port", "admin_port": ...,
                    "status": "up" | "left"[, "meta": {...}]}
    hb/<i>          heartbeat ADD counter, bumped every ``interval``

The optional ``meta`` dict is opaque to this module: publishers attach
arbitrary JSON-serializable facts (serving role, KV page geometry,
model fingerprint, ...) and watchers surface the dict verbatim on the
member record, so schema evolution never needs a membership change.

Liveness is judged by the *watcher's* clock: a member is live while its
beat counter keeps changing (last observed change within ``ttl``), so
publisher/watcher clock skew cannot expire a healthy member. A clean
leave flips the slot record to ``"left"`` and takes effect on the next
poll; a crash simply stops the beats and ages out after ``ttl``.
``add`` is at-least-once under the store's retry loop, so a retried
slot claim can burn a slot — watchers skip slots with no record.
"""
from __future__ import annotations

import json
import threading
import time
import warnings
from typing import Dict, Optional

from . import FileStore, Store, TCPStore

__all__ = ["connect", "MembershipPublisher", "MembershipWatcher"]


def connect(endpoint: str) -> Store:
    """A store client for ``endpoint``: ``host:port`` dials a TCPStore,
    anything else is a FileStore directory path."""
    host, _, port = endpoint.rpartition(":")
    if host and port.isdigit():
        return TCPStore(endpoint)
    return FileStore(endpoint)


def _prefix(group: str) -> str:
    return f"__members__/{group}/"


class MembershipPublisher:
    """One backend's registration: claim a slot, publish the record,
    beat until :meth:`leave`."""

    def __init__(self, store: Store, key: str, group: str = "serve",
                 admin_port: Optional[int] = None, interval: float = 1.0,
                 meta: Optional[dict] = None):
        self._store = store
        self._p = _prefix(group)
        self.key = key
        self.admin_port = admin_port
        self.interval = float(interval)
        self.meta = dict(meta) if meta else None
        self.slot: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _record(self, status: str) -> bytes:
        rec = {"key": self.key, "admin_port": self.admin_port,
               "status": status}
        if self.meta:
            rec["meta"] = self.meta
        return json.dumps(rec).encode()

    def start(self) -> "MembershipPublisher":
        self.slot = int(self._store.add(self._p + "nslots", 1))
        self._store.set(f"{self._p}slot/{self.slot}", self._record("up"))
        self._store.add(f"{self._p}hb/{self.slot}", 1)
        self._thread = threading.Thread(
            target=self._beat_loop, daemon=True,
            name=f"membership-beat:{self.key}")
        self._thread.start()
        return self

    def _beat_loop(self):
        while not self._stop.wait(self.interval):
            try:
                self._store.add(f"{self._p}hb/{self.slot}", 1)
            except Exception:
                continue         # transient store fault: next beat retries

    def leave(self, timeout: float = 5.0):
        """Deregister cleanly: watchers drop the member on their next
        poll instead of waiting out the TTL."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
        if self.slot is not None:
            try:
                self._store.set(f"{self._p}slot/{self.slot}",
                                self._record("left"))
            except Exception:
                pass             # crash-equivalent: TTL expiry covers it

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.leave()


class MembershipWatcher:
    """Polls the group keyspace and reports the live member set.

    Not thread-safe: one owner calls :meth:`poll` (the router does so
    from its membership thread)."""

    def __init__(self, store: Store, group: str = "serve",
                 ttl: float = 5.0):
        self._store = store
        self._p = _prefix(group)
        self.ttl = float(ttl)
        # slot -> [last beat value, local monotonic time it last changed]
        self._beats: Dict[int, list] = {}
        self._warned_slots: set = set()

    def poll(self) -> Dict[str, dict]:
        """key -> member record for every live member, judged now."""
        now = time.monotonic()
        try:
            nslots = int(self._store.add(self._p + "nslots", 0))
        except Exception:
            nslots = 0
        live: Dict[str, dict] = {}
        for slot in range(1, nslots + 1):
            raw = self._store.get(f"{self._p}slot/{slot}")
            if raw is None:
                continue         # burned slot (retried claim), skip
            try:
                rec = json.loads(raw.decode())
                if not isinstance(rec, dict):
                    raise ValueError("slot record is not a JSON object")
            except (ValueError, UnicodeDecodeError) as e:
                # reject-with-warning: one corrupt slot must not take the
                # watcher (and with it the whole fleet view) down — warn
                # once per slot, keep polling the rest
                if slot not in self._warned_slots:
                    self._warned_slots.add(slot)
                    warnings.warn(
                        f"membership slot {slot} holds a malformed "
                        f"record ({e}); ignoring it", RuntimeWarning,
                        stacklevel=2)
                continue
            self._warned_slots.discard(slot)
            if rec.get("status") != "up" or not rec.get("key"):
                self._beats.pop(slot, None)
                continue
            hb = self._store.get(f"{self._p}hb/{slot}")
            beat = int.from_bytes(hb, "little", signed=True) if hb else 0
            seen = self._beats.get(slot)
            if seen is None or seen[0] != beat:
                self._beats[slot] = seen = [beat, now]
            if now - seen[1] > self.ttl:
                continue         # beats stopped: crashed / partitioned
            rec["slot"] = slot
            live[rec["key"]] = rec
        return live
