// TCPStore: key-value rendezvous store for DCN bootstrap and PS-mode
// coordination.
//
// Reference: the gloo store wrappers the fleet role makers rendezvous
// through (/root/reference/paddle/fluid/framework/fleet/gloo_wrapper.h:113
// HdfsStore/ParallelConnectContext: Set/Get/Wait over a shared medium, and
// platform/gloo_context.cc). The reference rides HDFS/HTTP/file stores;
// TPU-native multihost already has the jax coordination service for the
// collective path, so this store exists for everything *outside* it: PS
// worker/server rendezvous, launcher elastic state, user barriers.
//
// Dependency-free length-prefixed TCP, one thread per connection (same
// trade-offs as ps/native/ps_server.cpp: the store is a control-plane
// service, connection counts are O(hosts), not O(requests/sec)).
//
// Protocol (little endian):
//   request : u8 verb | u32 klen | u64 n | key | payload
//   reply   : u8 status | u64 n | payload        (status 0 = ok)
// Verbs:
//   1 SET      payload = value bytes (n = value length)
//   2 GET      -> value (status 1 if missing)
//   3 WAIT     n = timeout_ms (0: forever) -> value once the key exists
//   4 ADD      payload = i64 delta -> i64 new value (key created at 0)
//   5 DEL      -> status 0 deleted / 1 missing
//   6 NUMKEYS  -> u64 count
//   7 STOP
//   8 PING     -> 0 bytes

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct Store {
  std::unordered_map<std::string, std::vector<char>> kv;
  std::mutex mu;
  std::condition_variable cv;  // notified on every SET/ADD
  std::atomic<bool> stopping{false};
  int listen_fd = -1;
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool reply(int fd, uint8_t status, const void* payload, uint64_t n) {
  if (!write_full(fd, &status, 1)) return false;
  if (!write_full(fd, &n, sizeof(n))) return false;
  return n == 0 || write_full(fd, payload, n);
}

void handle(Store& s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    struct __attribute__((packed)) {
      uint8_t verb;
      uint32_t klen;
      uint64_t n;
    } hdr;
    if (!read_full(fd, &hdr, sizeof(hdr))) break;
    std::string key(hdr.klen, '\0');
    if (hdr.klen && !read_full(fd, key.data(), hdr.klen)) break;

    switch (hdr.verb) {
      case 1: {  // SET
        std::vector<char> val(hdr.n);
        if (hdr.n && !read_full(fd, val.data(), hdr.n)) return;
        {
          std::lock_guard<std::mutex> lk(s.mu);
          s.kv[key] = std::move(val);
        }
        s.cv.notify_all();
        if (!reply(fd, 0, nullptr, 0)) return;
        break;
      }
      case 2: {  // GET
        std::lock_guard<std::mutex> lk(s.mu);
        auto it = s.kv.find(key);
        if (it == s.kv.end()) {
          if (!reply(fd, 1, nullptr, 0)) return;
        } else if (!reply(fd, 0, it->second.data(), it->second.size())) {
          return;
        }
        break;
      }
      case 3: {  // WAIT (n = timeout_ms, 0 = forever)
        std::unique_lock<std::mutex> lk(s.mu);
        auto ready = [&] { return s.kv.count(key) || s.stopping.load(); };
        bool ok;
        if (hdr.n == 0) {
          s.cv.wait(lk, ready);
          ok = s.kv.count(key) != 0;
        } else {
          ok = s.cv.wait_for(lk, std::chrono::milliseconds(hdr.n), ready) &&
               s.kv.count(key);
        }
        if (!ok) {
          if (!reply(fd, 1, nullptr, 0)) return;
        } else {
          auto& v = s.kv[key];
          if (!reply(fd, 0, v.data(), v.size())) return;
        }
        break;
      }
      case 4: {  // ADD
        int64_t delta = 0;
        if (hdr.n == 8) {
          if (!read_full(fd, &delta, 8)) return;
        }
        int64_t now;
        {
          std::lock_guard<std::mutex> lk(s.mu);
          auto& v = s.kv[key];
          if (v.size() != 8) {
            v.assign(8, 0);
          }
          std::memcpy(&now, v.data(), 8);
          now += delta;
          std::memcpy(v.data(), &now, 8);
        }
        s.cv.notify_all();
        if (!reply(fd, 0, &now, 8)) return;
        break;
      }
      case 5: {  // DEL
        std::lock_guard<std::mutex> lk(s.mu);
        uint8_t status = s.kv.erase(key) ? 0 : 1;
        if (!reply(fd, status, nullptr, 0)) return;
        break;
      }
      case 6: {  // NUMKEYS
        uint64_t n;
        {
          std::lock_guard<std::mutex> lk(s.mu);
          n = s.kv.size();
        }
        if (!reply(fd, 0, &n, 8)) return;
        break;
      }
      case 7: {  // STOP
        reply(fd, 0, nullptr, 0);
        s.stopping.store(true);
        s.cv.notify_all();
        ::shutdown(s.listen_fd, SHUT_RDWR);
        return;
      }
      case 8: {  // PING
        if (!reply(fd, 0, nullptr, 0)) return;
        break;
      }
      default:
        return;  // protocol desync: drop the connection
    }
  }
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? std::atoi(argv[1]) : 0;
  // Heap-allocated and intentionally leaked: detached handler threads
  // may still be blocked in read() when main returns — a stack-resident
  // Store would leave scope under them (use-after-scope UB). The process
  // exits right after, so the leak is one object for one instant.
  Store& store = *new Store();

  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    std::perror("bind");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  if (listen(fd, 128) != 0) {
    std::perror("listen");
    return 1;
  }
  store.listen_fd = fd;
  std::printf("STORE_LISTENING %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);

  // Detached handlers: clients open a fresh connection per blocking WAIT,
  // so joined threads would accumulate one zombie per wait for the store's
  // lifetime. Handlers only touch `store` (stack-resident in main, alive
  // until exit) and their own fd.
  while (!store.stopping.load()) {
    int cfd = ::accept(fd, nullptr, nullptr);
    if (cfd < 0) break;
    std::thread([&store, cfd] {
      handle(store, cfd);
      ::close(cfd);
    }).detach();
  }
  ::close(fd);
  // brief drain so handlers finish writing replies; stragglers only
  // reference the leaked Store, which stays valid past return
  std::this_thread::sleep_for(std::chrono::milliseconds(100));
  return 0;
}
