"""Fleet facade (reference: fleet/base/fleet_base.py — init,
distributed_optimizer :598, minimize :1075, worker utilities; role maker
fleet/base/role_maker.py).

TPU-native: init() wires jax.distributed (the gen_comm_id/gloo-rendezvous
analog) and builds the hybrid mesh from DistributedStrategy; the
meta-optimizer stack is replaced by the strategy compiler
(compiler.compile_train_step)."""
from __future__ import annotations

import os
from typing import Optional

import jax

from .. import env as env_mod
from .. import mesh as mesh_mod
from .compiler import CompiledTrainStep, compile_train_step
from .strategy import DistributedStrategy

__all__ = ["init", "DistributedStrategy", "distributed_optimizer",
           "distributed_model", "compile_train_step", "CompiledTrainStep",
           "worker_num", "worker_index", "is_first_worker", "barrier_worker",
           "get_strategy", "get_mesh", "UserDefinedRoleMaker",
           "PaddleCloudRoleMaker"]

_state = {"strategy": None, "initialized": False, "role_maker": None}


class PaddleCloudRoleMaker:
    """Reads the PADDLE_* env protocol (reference role_maker.py — the env
    names are kept so cloud launch scripts port over)."""

    def __init__(self, is_collective=True, **kwargs):
        self.is_collective = is_collective

    def worker_num(self):
        return env_mod.get_world_size()

    def worker_index(self):
        return env_mod.get_rank()


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, current_id=0, workers_num=1, role=None, **kw):
        super().__init__(True)
        self._id = current_id
        self._n = workers_num

    def worker_num(self):
        return self._n

    def worker_index(self):
        return self._id


def init(role_maker=None, is_collective=True, strategy=None):
    """fleet.init parity: bootstrap multi-process jax (DCN), build the
    hybrid device mesh from the strategy, remember both."""
    if strategy is None:
        strategy = DistributedStrategy()
    env_mod.init_distributed()
    _state["strategy"] = strategy
    _state["role_maker"] = role_maker or PaddleCloudRoleMaker(is_collective)
    try:
        strategy.build_mesh()
    except ValueError:
        # device count does not match hybrid degrees: leave mesh unset,
        # compile_train_step may be given an explicit mesh later
        pass
    _state["initialized"] = True
    return None


def get_strategy() -> Optional[DistributedStrategy]:
    return _state["strategy"]


def get_mesh():
    return mesh_mod.get_mesh()


def worker_num():
    rm = _state["role_maker"]
    return rm.worker_num() if rm else env_mod.get_world_size()


def worker_index():
    rm = _state["role_maker"]
    return rm.worker_index() if rm else env_mod.get_rank()


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    from .. import collective
    collective.barrier()


class _DistributedOptimizer:
    """Wrapper marking the optimizer for strategy compilation
    (fleet_base.py:598). user_defined_strategy rides along; minimize()
    builds and runs nothing by itself — the compiled step owns the
    update (there is no per-op program to rewrite)."""

    def __init__(self, optimizer, strategy):
        self._inner = optimizer
        self.user_defined_strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.backward()
        self._inner.step()
        return [], []


def distributed_optimizer(optimizer, strategy=None):
    strategy = strategy or _state["strategy"] or DistributedStrategy()
    _state["strategy"] = strategy
    return _DistributedOptimizer(optimizer, strategy)


def distributed_model(model):
    """fleet.distributed_model parity: tags the layer with the active
    strategy; the jitted path (hapi Model / compile_train_step) consumes
    the tag. Eager forward/backward stays single-replica per process —
    on TPU data parallelism is sharding, not layer wrapping."""
    model._fleet_strategy = _state["strategy"]
    return model
