"""Fleet facade (reference: fleet/base/fleet_base.py — init,
distributed_optimizer :598, minimize :1075, worker utilities; role maker
fleet/base/role_maker.py).

TPU-native: init() wires jax.distributed (the gen_comm_id/gloo-rendezvous
analog) and builds the hybrid mesh from DistributedStrategy; the
meta-optimizer stack is replaced by the strategy compiler
(compiler.compile_train_step)."""
from __future__ import annotations

import os
from typing import Optional

import jax

from .. import env as env_mod
from .. import mesh as mesh_mod
from .compiler import CompiledTrainStep, compile_train_step
from .strategy import DistributedStrategy

__all__ = ["init", "DistributedStrategy", "distributed_optimizer",
           "distributed_model", "compile_train_step", "CompiledTrainStep",
           "worker_num", "worker_index", "is_first_worker", "barrier_worker",
           "get_strategy", "get_mesh", "UserDefinedRoleMaker",
           "PaddleCloudRoleMaker", "is_server", "is_worker", "init_server",
           "run_server", "server_endpoints", "ps_client", "stop_worker",
           "stop_server"]

_state = {"strategy": None, "initialized": False, "role_maker": None}


class PaddleCloudRoleMaker:
    """Reads the PADDLE_* env protocol (reference role_maker.py — the env
    names are kept so cloud launch scripts port over)."""

    def __init__(self, is_collective=True, **kwargs):
        self.is_collective = is_collective

    def worker_num(self):
        return env_mod.get_world_size()

    def worker_index(self):
        return env_mod.get_rank()


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, current_id=0, workers_num=1, role=None, **kw):
        super().__init__(True)
        self._id = current_id
        self._n = workers_num

    def worker_num(self):
        return self._n

    def worker_index(self):
        return self._id


def init(role_maker=None, is_collective=True, strategy=None):
    """fleet.init parity: bootstrap multi-process jax (DCN), build the
    hybrid device mesh from the strategy, remember both."""
    if strategy is None:
        strategy = DistributedStrategy()
    env_mod.init_distributed()
    _state["strategy"] = strategy
    _state["role_maker"] = role_maker or PaddleCloudRoleMaker(is_collective)
    try:
        strategy.build_mesh()
    except ValueError:
        # device count does not match hybrid degrees: leave mesh unset,
        # compile_train_step may be given an explicit mesh later
        pass
    _state["initialized"] = True
    return None


def get_strategy() -> Optional[DistributedStrategy]:
    return _state["strategy"]


def get_mesh():
    return mesh_mod.get_mesh()


def worker_num():
    rm = _state["role_maker"]
    return rm.worker_num() if rm else env_mod.get_world_size()


def worker_index():
    rm = _state["role_maker"]
    return rm.worker_index() if rm else env_mod.get_rank()


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    from .. import collective
    collective.barrier()


class _DistributedOptimizer:
    """Wrapper marking the optimizer for strategy compilation
    (fleet_base.py:598). user_defined_strategy rides along; minimize()
    builds and runs nothing by itself — the compiled step owns the
    update (there is no per-op program to rewrite)."""

    def __init__(self, optimizer, strategy):
        self._inner = optimizer
        self.user_defined_strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.backward()
        self._inner.step()
        return [], []


def distributed_optimizer(optimizer, strategy=None):
    strategy = strategy or _state["strategy"] or DistributedStrategy()
    _state["strategy"] = strategy
    return _DistributedOptimizer(optimizer, strategy)


def distributed_model(model):
    """fleet.distributed_model parity: tags the layer with the active
    strategy; the jitted path (hapi Model / compile_train_step) consumes
    the tag. Eager forward/backward stays single-replica per process —
    on TPU data parallelism is sharding, not layer wrapping."""
    model._fleet_strategy = _state["strategy"]
    return model


# ---------------------------------------------------------------------------
# parameter-server mode (reference: fleet PS role — fleet_base.py
# init_server/run_server/stop_worker, runtime fleet/runtime/the_one_ps.py;
# env protocol TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST)
# ---------------------------------------------------------------------------

_ps_state = {"server": None, "client": None}


def is_server() -> bool:
    return os.environ.get("TRAINING_ROLE", "").upper() == "PSERVER"

def is_worker() -> bool:
    return not is_server()


def init_server(port: int = 0, model_path: str = None):
    """Start the native table server in-process (the brpc_ps_server
    analog, distributed/ps/native/ps_server.cpp); optionally restore
    tables from a save() snapshot.

    If the launcher exported PADDLE_PSERVERS_IP_PORT_LIST, this host's
    entry decides the bind port (the documented env protocol); otherwise
    an ephemeral port is bound and published into the env."""
    from ..ps import PSClient, PSServer
    if port == 0:
        eps = server_endpoints()
        idx = int(os.environ.get("PADDLE_PSERVER_ID", "0"))
        if eps and idx < len(eps):
            port = int(eps[idx].rsplit(":", 1)[1])
    srv = PSServer(port=port)
    _ps_state["server"] = srv
    os.environ.setdefault("PADDLE_PSERVERS_IP_PORT_LIST", srv.endpoint)
    if model_path:
        c = PSClient(srv.endpoint)
        c.load(model_path)
        c.close()
    return srv


def run_server():
    """Block until a worker sends STOP (reference run_server)."""
    srv = _ps_state["server"]
    if srv is None:
        raise RuntimeError("call fleet.init_server() first")
    srv._proc.wait()


def server_endpoints():
    eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
    return [e for e in eps.replace(",", ";").split(";") if e]


def ps_client():
    """Worker-side connection to the server fleet: one endpoint gives a
    plain client, several give the sharded fleet client (tables
    key-shard / range-split across servers)."""
    from ..ps import PSClient
    if _ps_state["client"] is None:
        eps = server_endpoints()
        if not eps:
            raise RuntimeError("PADDLE_PSERVERS_IP_PORT_LIST not set")
        _ps_state["client"] = PSClient(eps)
    return _ps_state["client"]


def stop_worker():
    """Worker-side teardown: close this worker's client connection. The
    server keeps running (reference semantics: trainers call stop_worker;
    the server is stopped separately via stop_server)."""
    c = _ps_state.get("client")
    if c is not None:
        try:
            c.close()
        except Exception:
            pass
        _ps_state["client"] = None


def stop_server():
    """Shut the table server down via RPC (callable from any process that
    can reach PADDLE_PSERVERS_IP_PORT_LIST; typically trainer 0 after all
    workers barrier out, or the server host itself)."""
    from ..ps import PSClient
    eps = server_endpoints()
    srv = _ps_state.get("server")
    target = srv.endpoint if srv is not None else (eps[0] if eps else None)
    if target is None:
        return
    try:
        c = PSClient(target)
        c.stop_server()
        c.close()
    except Exception:
        if srv is not None:
            srv._proc.terminate()
