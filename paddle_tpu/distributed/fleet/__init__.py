"""Fleet facade (reference: fleet/base/fleet_base.py — init,
distributed_optimizer :598, minimize :1075, worker utilities; role maker
fleet/base/role_maker.py).

TPU-native: init() wires jax.distributed (the gen_comm_id/gloo-rendezvous
analog) and builds the hybrid mesh from DistributedStrategy; the
meta-optimizer stack is replaced by the strategy compiler
(compiler.compile_train_step)."""
from __future__ import annotations

import os
from typing import Optional

import jax

from .. import env as env_mod
from .. import mesh as mesh_mod
from .compiler import CompiledTrainStep, compile_train_step
from .strategy import DistributedStrategy

__all__ = ["init", "DistributedStrategy", "distributed_optimizer",
           "distributed_model", "compile_train_step", "CompiledTrainStep",
           "worker_num", "worker_index", "is_first_worker", "barrier_worker",
           "get_strategy", "get_mesh", "UserDefinedRoleMaker",
           "PaddleCloudRoleMaker", "is_server", "is_worker", "init_server",
           "run_server", "server_endpoints", "ps_client", "stop_worker",
           "stop_server", "Fleet", "UtilBase", "Role", "fleet", "util",
           "MultiSlotDataGenerator", "MultiSlotStringDataGenerator",
           "utils", "data_generator"]

_state = {"strategy": None, "initialized": False, "role_maker": None}


class Role:
    """Process roles (reference fleet/base/role_maker.py:26)."""
    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


class PaddleCloudRoleMaker:
    """Reads the PADDLE_* env protocol (reference role_maker.py — the env
    names are kept so cloud launch scripts port over)."""

    def __init__(self, is_collective=True, **kwargs):
        self.is_collective = is_collective

    def worker_num(self):
        return env_mod.get_world_size()

    def worker_index(self):
        return env_mod.get_rank()


class UserDefinedRoleMaker(PaddleCloudRoleMaker):
    def __init__(self, current_id=0, workers_num=1, role=None, **kw):
        super().__init__(True)
        self._id = current_id
        self._n = workers_num

    def worker_num(self):
        return self._n

    def worker_index(self):
        return self._id


def init(role_maker=None, is_collective=True, strategy=None):
    """fleet.init parity: bootstrap multi-process jax (DCN), build the
    hybrid device mesh from the strategy, remember both."""
    if strategy is None:
        strategy = DistributedStrategy()
    env_mod.init_distributed()
    _state["strategy"] = strategy
    _state["role_maker"] = role_maker or PaddleCloudRoleMaker(is_collective)
    try:
        strategy.build_mesh()
    except ValueError as e:
        # device count does not match hybrid degrees: leave mesh unset so
        # compile_train_step may be given an explicit mesh later — but say
        # so NOW. On multi-device runs a silently-missing mesh used to
        # surface much later as a hang or an opaque compile error
        # (MULTICHIP r05 died at timeout having printed nothing).
        import warnings
        warnings.warn(
            f"fleet.init: mesh build failed ({e}); no global mesh was "
            "set. Fix the strategy's hybrid degrees or pass an explicit "
            "mesh to compile_train_step.", RuntimeWarning, stacklevel=2)
    _state["initialized"] = True
    return None


def get_strategy() -> Optional[DistributedStrategy]:
    return _state["strategy"]


def get_mesh():
    return mesh_mod.get_mesh()


def worker_num():
    rm = _state["role_maker"]
    return rm.worker_num() if rm else env_mod.get_world_size()


def worker_index():
    rm = _state["role_maker"]
    return rm.worker_index() if rm else env_mod.get_rank()


def is_first_worker():
    return worker_index() == 0


def barrier_worker():
    from .. import collective
    collective.barrier()


class _DistributedOptimizer:
    """Wrapper marking the optimizer for strategy compilation
    (fleet_base.py:598). user_defined_strategy rides along; minimize()
    builds and runs nothing by itself — the compiled step owns the
    update (there is no per-op program to rewrite)."""

    def __init__(self, optimizer, strategy):
        self._inner = optimizer
        self.user_defined_strategy = strategy

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        loss.backward()
        self._inner.step()
        return [], []


def distributed_optimizer(optimizer, strategy=None):
    strategy = strategy or _state["strategy"] or DistributedStrategy()
    _state["strategy"] = strategy
    wrapped = _DistributedOptimizer(optimizer, strategy)
    _state["optimizer"] = wrapped
    return wrapped


def distributed_model(model):
    """fleet.distributed_model parity: tags the layer with the active
    strategy; the jitted path (hapi Model / compile_train_step) consumes
    the tag. Eager forward/backward stays single-replica per process —
    on TPU data parallelism is sharding, not layer wrapping."""
    model._fleet_strategy = _state["strategy"]
    return model


# ---------------------------------------------------------------------------
# parameter-server mode (reference: fleet PS role — fleet_base.py
# init_server/run_server/stop_worker, runtime fleet/runtime/the_one_ps.py;
# env protocol TRAINING_ROLE / PADDLE_PSERVERS_IP_PORT_LIST)
# ---------------------------------------------------------------------------

_ps_state = {"server": None, "client": None}


def is_server() -> bool:
    return os.environ.get("TRAINING_ROLE", "").upper() == "PSERVER"

def is_worker() -> bool:
    return not is_server()


def init_server(port: int = 0, model_path: str = None):
    """Start the native table server in-process (the brpc_ps_server
    analog, distributed/ps/native/ps_server.cpp); optionally restore
    tables from a save() snapshot.

    If the launcher exported PADDLE_PSERVERS_IP_PORT_LIST, this host's
    entry decides the bind port (the documented env protocol); otherwise
    an ephemeral port is bound and published into the env."""
    from ..ps import PSClient, PSServer
    if port == 0:
        eps = server_endpoints()
        idx = int(os.environ.get("PADDLE_PSERVER_ID", "0"))
        if eps and idx < len(eps):
            port = int(eps[idx].rsplit(":", 1)[1])
    srv = PSServer(port=port)
    _ps_state["server"] = srv
    os.environ.setdefault("PADDLE_PSERVERS_IP_PORT_LIST", srv.endpoint)
    if model_path:
        c = PSClient(srv.endpoint)
        c.load(model_path)
        c.close()
    return srv


def run_server():
    """Block until a worker sends STOP (reference run_server)."""
    srv = _ps_state["server"]
    if srv is None:
        raise RuntimeError("call fleet.init_server() first")
    srv._proc.wait()


def server_endpoints():
    eps = os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST", "")
    return [e for e in eps.replace(",", ";").split(";") if e]


def ps_client():
    """Worker-side connection to the server fleet: one endpoint gives a
    plain client, several give the sharded fleet client (tables
    key-shard / range-split across servers)."""
    from ..ps import PSClient
    if _ps_state["client"] is None:
        eps = server_endpoints()
        if not eps:
            raise RuntimeError("PADDLE_PSERVERS_IP_PORT_LIST not set")
        _ps_state["client"] = PSClient(eps)
    return _ps_state["client"]


def stop_worker():
    """Worker-side teardown: close this worker's client connection. The
    server keeps running (reference semantics: trainers call stop_worker;
    the server is stopped separately via stop_server)."""
    c = _ps_state.get("client")
    if c is not None:
        try:
            c.close()
        except Exception:
            pass
        _ps_state["client"] = None


def stop_server():
    """Shut the table server down via RPC (callable from any process that
    can reach PADDLE_PSERVERS_IP_PORT_LIST; typically trainer 0 after all
    workers barrier out, or the server host itself)."""
    from ..ps import PSClient
    eps = server_endpoints()
    srv = _ps_state.get("server")
    target = srv.endpoint if srv is not None else (eps[0] if eps else None)
    if target is None:
        return
    try:
        c = PSClient(target)
        c.stop_server()
        c.close()
    except Exception:
        if srv is not None:
            srv._proc.terminate()


# ---------------------------------------------------------------------------
# facade objects (reference fleet/__init__.py:16-34 binds module-level
# names to ONE Fleet() singleton's methods; same shape here, with the
# module-level functions as the implementation)
# ---------------------------------------------------------------------------

from . import utils            # noqa: E402,F401  (LocalFS/HDFSClient/...)
from . import data_generator   # noqa: E402
from . import dataset          # noqa: E402,F401  (MultiSlot readers)
from .data_generator import (MultiSlotDataGenerator,         # noqa: E402
                             MultiSlotStringDataGenerator)


class UtilBase:
    """Worker utilities (reference fleet/base/util_factory.py UtilBase):
    cross-worker collectives over the active communication backend plus
    the file-shard helper PS ingestion uses."""

    def all_reduce(self, input, mode="sum"):
        from .. import collective
        from ...core.tensor import to_tensor
        import numpy as np
        ops = {"sum": collective.ReduceOp.SUM,
               "max": collective.ReduceOp.MAX,
               "min": collective.ReduceOp.MIN}
        if mode not in ops:
            raise ValueError(f"all_reduce mode must be one of "
                             f"{sorted(ops)}, got {mode!r}")
        t = collective.all_reduce(to_tensor(np.asarray(input)),
                                  op=ops[mode])
        return np.asarray(t.numpy())

    def all_gather(self, input):
        from .. import collective
        from ...core.tensor import to_tensor
        import numpy as np
        t = collective.all_gather(to_tensor(np.asarray(input)))
        return [np.asarray(x.numpy()) for x in t] \
            if isinstance(t, (list, tuple)) else np.asarray(t.numpy())

    def barrier(self, comm_world="worker"):
        barrier_worker()

    def get_file_shard(self, files):
        """Split `files` across workers, contiguous blocks with the
        remainder spread over the first ranks (reference
        util_factory.get_file_shard)."""
        if not isinstance(files, list):
            raise TypeError("files should be a list of file names")
        n, idx = worker_num(), worker_index()
        base, extra = divmod(len(files), n)
        start = idx * base + min(idx, extra)
        return files[start:start + base + (1 if idx < extra else 0)]

    def print_on_rank(self, message, rank_id=0):
        if worker_index() == rank_id:
            print(message)


class Fleet:
    """The facade class itself (reference fleet/base/fleet_base.py
    Fleet): every method delegates to the module-level implementation,
    and `fleet` below is the singleton whose bound methods the module
    names mirror — reference code doing `Fleet().init(...)` or
    `fleet.init(...)` lands in the same place."""

    def __init__(self):
        self._util = UtilBase()

    # lifecycle / topology
    def init(self, role_maker=None, is_collective=True, strategy=None):
        return init(role_maker, is_collective, strategy)

    def is_first_worker(self):
        return is_first_worker()

    def worker_num(self):
        return worker_num()

    def worker_index(self):
        return worker_index()

    def is_worker(self):
        return is_worker()

    def worker_endpoints(self, to_string=False):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        lst = [e for e in eps.replace(";", ",").split(",") if e]
        return ",".join(lst) if to_string else lst

    def server_num(self):
        return len(server_endpoints())

    def server_index(self):
        return int(os.environ.get("PADDLE_PSERVER_ID", "0"))

    def server_endpoints(self, to_string=False):
        eps = server_endpoints()
        return ",".join(eps) if to_string else eps

    def is_server(self):
        return is_server()

    @property
    def util(self):
        return self._util

    def barrier_worker(self):
        return barrier_worker()

    # PS lifecycle
    def init_worker(self):
        return None            # table connections open lazily (ps_client)

    def init_server(self, *args, **kwargs):
        return init_server(*args, **kwargs)

    def run_server(self):
        return run_server()

    def stop_worker(self):
        return stop_worker()

    # training surface
    def distributed_optimizer(self, optimizer, strategy=None):
        return distributed_optimizer(optimizer, strategy)

    def distributed_model(self, model):
        return distributed_model(model)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        """backward + apply through the fleet optimizer (reference
        Fleet.minimize steps the inner optimizer too)."""
        loss.backward()
        opt = _state.get("optimizer")
        if opt is None:
            raise RuntimeError(
                "fleet.minimize needs a fleet optimizer: call "
                "fleet.distributed_optimizer(opt) first (the reference "
                "requires the same)")
        opt._inner.step()
        return [], []

    # dygraph optimizer delegation (reference fleet_base.py step/
    # clear_grad/set_lr/get_lr/state_dict act on the wrapped optimizer)
    def _opt(self):
        opt = _state.get("optimizer")
        if opt is None:
            raise RuntimeError(
                "no fleet optimizer yet: call fleet.distributed_optimizer "
                "(the reference raises the same way)")
        return opt

    def step(self):
        return self._opt().step()

    def clear_grad(self):
        return self._opt().clear_grad()

    def set_lr(self, value):
        opt = self._opt()
        if hasattr(opt, "set_lr"):
            return opt.set_lr(value)
        # reach THROUGH the _DistributedOptimizer wrapper: setattr on the
        # wrapper would shadow the inner optimizer's lr (get_lr would
        # report the new value while training kept the old one)
        opt._inner._learning_rate = value

    def get_lr(self):
        opt = self._opt()
        if hasattr(opt, "get_lr"):
            return opt.get_lr()
        lr = getattr(opt, "_learning_rate", None)
        return lr() if callable(lr) else lr

    def state_dict(self):
        opt = _state.get("optimizer")
        if opt is not None and hasattr(opt, "state_dict"):
            return opt.state_dict()
        st = _state["strategy"]
        return dict(st.__dict__) if st is not None else {}

    def set_state_dict(self, state):
        opt = _state.get("optimizer")
        if opt is not None and hasattr(opt, "set_state_dict"):
            return opt.set_state_dict(state)

    def shrink(self, threshold=None):
        """PS table shrink (reference fleet_base.shrink: drop sparse
        rows below the show/click threshold); delegated to the table
        server when one is connected, no-op otherwise."""
        c = _ps_state.get("client")
        if c is not None and hasattr(c, "shrink"):
            return c.shrink(threshold)

    def save_inference_model(self, executor, dirname, feeded_var_names=None,
                             target_vars=None, main_program=None,
                             export_for_deployment=True):
        """Export the layer/StaticFunction for serving. The reference
        passes feed NAMES (strings); shapes live in the program there —
        here the target must carry input_spec (a StaticFunction from
        to_static, or a layer with _input_spec), which supplies the real
        specs; bare name strings cannot."""
        from ... import jit as jit_mod
        target = target_vars or main_program
        if isinstance(target, (list, tuple)):
            target = target[0]
        if target is None:
            raise ValueError("fleet.save_inference_model needs the model "
                             "as target_vars (a Layer or to_static-"
                             "wrapped function)")
        spec = getattr(target, "_input_spec", None)
        if spec is None:
            raise ValueError(
                "fleet.save_inference_model: the target has no input "
                "spec — wrap it with paddle.jit.to_static(layer, "
                "input_spec=[...]) so the export knows shapes/dtypes "
                "(string feed names alone don't carry them here)")
        jit_mod.save(target, dirname, input_spec=spec)
        return dirname

    def save_persistables(self, executor, dirname, main_program=None,
                          mode=1):
        from ...static.compat import default_main_program, save as _save
        target = main_program
        if target is None:
            prog = default_main_program()
            if getattr(prog, "_parameters", None):
                target = prog
        if target is None or not hasattr(target, "named_parameters") and \
                not getattr(target, "_parameters", None):
            raise ValueError(
                "fleet.save_persistables: pass main_program (a layer or "
                "a static Program holding parameters); the default "
                "program has none to save")
        if not hasattr(target, "named_parameters"):
            # static Program: persist its registered parameter scope
            from ...framework import save as _fsave
            _fsave({k: v for k, v in target._parameters.items()},
                   dirname if dirname.endswith(".pdparams")
                   else dirname + ".pdparams")
            return dirname
        return _save(target, dirname)


fleet = Fleet()
util = fleet.util

# module-level bindings of the singleton's methods (reference
# fleet/__init__.py:36-63 binds exactly this set)
from . import metrics                    # noqa: E402,F401
init_worker = fleet.init_worker
worker_endpoints = fleet.worker_endpoints
server_num = fleet.server_num
server_index = fleet.server_index
minimize = fleet.minimize
save_inference_model = fleet.save_inference_model
save_persistables = fleet.save_persistables
state_dict = fleet.state_dict
set_state_dict = fleet.set_state_dict
step = fleet.step
clear_grad = fleet.clear_grad
set_lr = fleet.set_lr
get_lr = fleet.get_lr
shrink = fleet.shrink
