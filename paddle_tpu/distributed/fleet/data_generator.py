"""Fleet data generators — user-defined ETL emitting MultiSlot text.

Reference: python/paddle/distributed/fleet/data_generator/
data_generator.py (DataGenerator.run_from_stdin:94 /
MultiSlotDataGenerator._gen_str:296): users subclass, override
`generate_sample(line)`, and the runner streams stdin -> parsed sample
-> slot-count wire format on stdout, which the MultiSlot feed
(io/data_feed.py parse_multi_slot_line) consumes directly — the same
pipe protocol the PS trainers use for out-of-process ETL."""
from __future__ import annotations

import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator",
           "MultiSlotStringDataGenerator"]


class DataGenerator:
    def __init__(self):
        self.batch_size_ = 1
        self._proto_info = None

    def set_batch(self, batch_size):
        self.batch_size_ = int(batch_size)

    def generate_sample(self, line):
        """Override: return a zero-arg iterator yielding samples of the
        form [(slot_name, [values...]), ...]."""
        raise NotImplementedError(
            "generate_sample must be overridden: return an iterator of "
            "[(name, [value, ...]), ...] samples")

    def generate_batch(self, samples):
        """Override for batch-level rework; defaults to pass-through."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError(
            "use MultiSlotDataGenerator or MultiSlotStringDataGenerator")

    def _flush(self, batch_samples, write):
        for sample in self.generate_batch(batch_samples)():
            write(self._gen_str(sample))

    def run_from_memory(self):
        """Emit generate_sample(None) output to stdout (debug path)."""
        self._run_lines([None], sys.stdout.write)

    def run_from_stdin(self):
        """stdin lines -> generate_sample -> slot wire format on stdout."""
        self._run_lines(sys.stdin, sys.stdout.write)

    def _run_lines(self, lines, write):
        batch = []
        for line in lines:
            for sample in self.generate_sample(line)():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) == self.batch_size_:
                    self._flush(batch, write)
                    batch = []
        if batch:
            self._flush(batch, write)


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots: each (name, values) renders as `<n> v1 ... vn`
    (reference _gen_str data_generator.py:296; int => uint64 slot,
    any float => float slot in the proto info)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "sample must be [(name, [value, ...]), ...], got "
                f"{type(line).__name__}")
        if self._proto_info is None:
            self._proto_info = []
            for name, elements in line:
                kind = "float" if any(isinstance(e, float)
                                      for e in elements) else "uint64"
                self._proto_info.append((name, kind))
        parts = []
        for name, elements in line:
            if not elements:
                raise ValueError(
                    f"slot {name!r} is empty; pad it in generate_sample")
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(DataGenerator):
    """String slots: values pass through untouched (reference
    MultiSlotStringDataGenerator — faster, no type bookkeeping)."""

    def _gen_str(self, line):
        if not isinstance(line, (list, tuple)):
            raise ValueError(
                "sample must be [(name, [str, ...]), ...], got "
                f"{type(line).__name__}")
        parts = []
        for _name, elements in line:
            parts.append(str(len(elements)))
            parts.extend(str(e) for e in elements)
        return " ".join(parts) + "\n"
