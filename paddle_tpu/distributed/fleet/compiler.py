"""Strategy compiler: DistributedStrategy + Layer + Optimizer -> one jitted
SPMD train step.

Reference analog: fleet/base/strategy_compiler.py + the meta-optimizer
stack (fleet/meta_optimizers/*, SURVEY.md §2 row 37) which rewrite the
Program op-by-op (insert c_broadcast/c_allreduce, cast ops, recompute
clones). Here each strategy toggle maps to a functional transform or a
sharding assignment and XLA emits the collectives:

  amp            -> autocast ctx inside the traced step (+ bf16: no loss
                    scaling needed on TPU, bf16 exponent == fp32)
  recompute      -> jax.checkpoint around the forward
  tensor_parallel-> model-supplied param PartitionSpecs ('tp' axis)
  sharding (ZeRO)-> optimizer-state/grad/param specs over 'dp'
  dp             -> batch PartitionSpec over 'dp'
  gradient_merge -> microbatch lax.scan accumulating grads
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core import nan_inf
from ...core import random as random_mod
from ...framework import MethodAdapter, functional_call, param_arrays, \
    state_arrays, unaliased_put
from .. import sharding as zero_mod
from .strategy import DistributedStrategy


class CompiledTrainStep:
    """Holds the jitted step + sharded live arrays; call(step_fn) style:
        prog = compile_train_step(layer, opt, strategy, loss_method="loss")
        loss = prog.step(ids, labels)        # updates internal params
    """

    def __init__(self, step, params, state, opt_state, shardings, mesh,
                 layer, data_sharding):
        self._step = step
        self.params = params
        self.state = state
        self.opt_state = opt_state
        self.shardings = shardings
        self.mesh = mesh
        self.layer = layer
        self.data_sharding = data_sharding
        self._opt = None
        self._step_label = "fleet.train_step"
        self._aot = None
        self._guard = None
        self.compile_stats = None

    def step(self, *data, lr=None):
        data = tuple(self._put_data(d) for d in data)
        key = random_mod.next_key()
        if lr is None:
            # follow the optimizer's configured lr / scheduler
            lr = self._opt.get_lr() if self._opt is not None else 1e-3
        lr = jnp.asarray(lr, jnp.float32)
        args = (self.params, self.state, self.opt_state, key, lr, data)
        # every strategy path (SPMD jit, pipeline, grad_comm shard_map)
        # funnels here: AOT-compile once (timed, persistent-cache aware)
        # and watch the data signature instead of silently retracing
        from ...jit import compile_cache
        if self._guard is None:
            self._guard = compile_cache.RetraceGuard(self._step_label)
        verdict = self._guard.check(data=data)
        if self._aot is None or verdict == "retrace":
            # CPU + multi-device mesh: never serve this executable from
            # the persistent cache — deserializing a sub-mesh SPMD
            # executable on the CPU backend corrupts the heap (observed
            # under xla_force_host_platform_device_count); TPU keeps it
            n_mesh = int(getattr(self.mesh, "size", 1) or 1) \
                if self.mesh is not None else 1
            use_cache = not (n_mesh > 1
                             and jax.default_backend() == "cpu")
            try:
                self._aot, self.compile_stats = compile_cache.aot_compile(
                    self._step, *args, label=self._step_label,
                    use_cache=use_cache)
            except compile_cache.RetraceError:
                raise
            except Exception:  # exotic input: keep the implicit jit path
                self._aot = self._step
        loss, self.params, self.state, self.opt_state = self._aot(*args)
        return loss

    def eval_step(self, *data):
        """Loss on a batch under the SAME shardings as training — no
        host gather, no parameter replication onto one device (the
        reference evaluates pp/tp models through the sharded program
        too; a single-device eval of a model that only fits sharded
        would OOM). Built lazily on first use; traced in eval mode
        (dropout off)."""
        if getattr(self, "_eval_jitted", None) is None:
            builder = getattr(self, "_eval_builder", None)
            if builder is None:
                raise NotImplementedError(
                    "this compiled program has no eval path")
            self._eval_jitted = builder()
        data = tuple(self._put_data(d) for d in data)
        return self._eval_jitted(self.params, self.state, data)

    def _fit_sharding(self, d):
        """This program's input sharding for one data arg; the spec is
        truncated to the array's rank (a [B] per-sample tensor under
        dp x sp sharding takes P('dp'))."""
        sh = self.data_sharding
        if isinstance(sh, NamedSharding) and len(sh.spec) > d.ndim:
            sh = NamedSharding(sh.mesh, P(*sh.spec[:d.ndim]))
        return sh

    def _is_placed(self, d):
        """True when d already went through put_batch (prefetch thread)
        or is a committed device array on this program's input sharding
        with no pending host-side preproc — the per-step preproc +
        device_put is skipped so prefetched batches cost the step loop
        nothing."""
        placed = getattr(self, "_placed", None)
        try:
            if placed is not None and d in placed:
                return True
        except TypeError:
            return False
        if getattr(self, "_data_preproc", None) is not None:
            # sharding equality can't prove the microbatch reshape ran;
            # only put_batch-registered arrays skip on this path
            return False
        if not isinstance(d, jax.Array):
            return False
        try:
            return d.committed and d.sharding == self._fit_sharding(d)
        except Exception:
            return False

    def put_batch(self, d):
        """Public placement hook (io.device_prefetch `place=`): preproc
        + shard one data arg onto this program's input sharding ahead of
        the step. Idempotent — an array that already went through here
        passes straight through in step()."""
        out = self._put_data(d)
        if isinstance(out, jax.Array):
            if getattr(self, "_placed", None) is None:
                import weakref
                self._placed = weakref.WeakSet()
            self._placed.add(out)
        return out

    def _put_data(self, d):
        """Shard one data arg. An optional _data_preproc (pipeline:
        host-side microbatch reshape) runs BEFORE device_put so the
        program never reshapes across sharded dims — that reshape forced
        the SPMD partitioner into replicate-then-repartition fallbacks."""
        if self._is_placed(d):
            return d
        d = jnp.asarray(d)
        pre = getattr(self, "_data_preproc", None)
        if pre is not None:
            d = pre(d)
        return jax.device_put(d, self._fit_sharding(d))

    def write_back(self):
        """Copy sharded params back into the Layer tree (host-gathered)."""
        lookup = dict(self.layer.named_parameters())
        lookup.update(dict(self.layer.named_buffers()))
        for k, v in {**self.params, **self.state}.items():
            if k in lookup:
                lookup[k]._data = jax.device_get(v)

    # -- sharded checkpoint (io/checkpoint.py) -----------------------------
    def save_checkpoint(self, path, step=0, meta=None):
        """Per-process shard files + PartitionSpec metadata; resumable on a
        different mesh shape (io/checkpoint.py)."""
        from ...io.checkpoint import save_checkpoint as _save
        _save(path, self.params, self.opt_state, self.state, step=step,
              meta=meta)

    def restore_checkpoint(self, path):
        """Restore params/opt state onto THIS program's shardings (the
        saved mesh shape may differ — shards re-tile)."""
        from ...io.checkpoint import load_checkpoint as _load
        sh = {"params": self.shardings["params"],
              "opt": self.shardings["opt"]}
        params, opt, state, step, meta = _load(path, mesh=self.mesh,
                                               shardings=sh)
        self.params = params
        if opt:     # a params-only checkpoint keeps the live slots
            self.opt_state = opt
        if state:
            self.state = state
        return step, meta


def _maybe_swap_optimizer(optimizer, strategy):
    """lars/lamb meta-optimizers: the reference rewrites momentum ->
    lars_momentum / adam -> lamb ops in the program
    (fleet/meta_optimizers/lars_optimizer.py, lamb_optimizer.py); here the
    toggle swaps the optimizer class, carrying over lr and parameters."""
    from ... import optimizer as opt_mod
    # carry grad_clip over; weight decay uses Lars/Lamb's own decoupled
    # lars_weight_decay / lamb_weight_decay defaults (the reference meta-
    # optimizers likewise source decay from their own configs)
    kw = dict(grad_clip=optimizer._grad_clip)
    if getattr(strategy, "lamb", False) and not isinstance(
            optimizer, opt_mod.Lamb):
        return opt_mod.Lamb(learning_rate=optimizer._learning_rate,
                            parameters=optimizer._parameter_list, **kw)
    if getattr(strategy, "lars", False) and not isinstance(
            optimizer, opt_mod.Lars):
        return opt_mod.Lars(learning_rate=optimizer._learning_rate,
                            parameters=optimizer._parameter_list, **kw)
    return optimizer


def _tp_specs(layer, params, strategy) -> Dict[str, P]:
    """Tensor-parallel specs via the model's `param_shardings` protocol
    (GPT implements it with its Megatron rules); replicated otherwise."""
    fn = getattr(layer, "param_shardings", None)
    if callable(fn):
        return fn(params, mesh_axis_tp="tp")
    return {k: P(*([None] * getattr(v, "ndim", 0)))
            for k, v in params.items()}


def _merge_specs(base: Dict[str, P], extra: Dict[str, P]) -> Dict[str, P]:
    """Combine TP specs with ZeRO specs: ZeRO claims a dimension the TP
    spec left unsharded; on conflict TP wins (matches Megatron+ZeRO
    practice: never double-shard one dim)."""
    out = {}
    for k, tp in base.items():
        z = extra.get(k)
        if z is None:
            out[k] = tp
            continue
        merged = []
        for i in range(len(tp)):
            t = tp[i] if i < len(tp) else None
            s = z[i] if i < len(z) else None
            merged.append(t if t is not None else s)
        out[k] = P(*merged)
    return out


def _scan_stacked_names(layer):
    """Fully-qualified names of params living in a ScanBlockStack: their
    dim 0 is the lax.scan xs axis (see sharding.shard_specs
    ``skip_leading``)."""
    walk = getattr(layer, "named_sublayers", None)
    if walk is None:        # facade layers (hapi adapters) without one
        return set()
    names = set()
    for pfx, sub in [("", layer)] + list(walk()):
        if getattr(sub, "_scan_stack", False):
            p = pfx + "." if pfx else ""
            names.update(p + rel for rel in sub._rels)
    return names


def _slot_shardings(mesh, opt_state, params, slot_specs):
    """Optimizer-slot shardings: a slot shaped like its parameter follows
    the parameter's spec; scalars (beta powers, steps) replicate."""
    return {n: {sl: (NamedSharding(mesh, slot_specs[n])
                     if tuple(getattr(v, "shape", ())) ==
                     tuple(params[n].shape)
                     else NamedSharding(mesh, P()))
                for sl, v in st.items()}
            for n, st in opt_state.items()}


def _put_opt_state(opt_state, s_sh):
    return {n: {sl: jax.device_put(v, s_sh[n][sl]) for sl, v in st.items()}
            for n, st in opt_state.items()}


def compile_train_step(layer, optimizer, strategy: DistributedStrategy,
                       loss_method: str = "loss", mesh=None,
                       lr_default: float = 1e-3) -> CompiledTrainStep:
    mesh = mesh or strategy.build_mesh()
    optimizer = _maybe_swap_optimizer(optimizer, strategy)
    if not getattr(strategy, "scan_layers", True):
        # escape hatch: trace scan-stacked models as an unrolled Python
        # loop over the stacked params (depth-linear HLO again)
        setter = getattr(layer, "set_scan_unroll", None)
        if setter is not None:
            setter(True)
    if hasattr(layer, "named_parameters"):
        # per-param ParamAttr regularizers, keyed for the functional path
        # (pipeline layouts rename params — those fall back to the
        # optimizer-wide weight_decay)
        optimizer.collect_param_regularizers(layer)
    if int(mesh.shape.get("pp", 1)) > 1:
        return _compile_pipeline_step(layer, optimizer, strategy, mesh)
    from .grad_comm import active_mode, compile_explicit_dp_step
    if active_mode(strategy):
        # localsgd / adaptive_localsgd / dgc / fp16_allreduce need manual
        # control of the dp gradient exchange (fleet/grad_comm.py)
        return compile_explicit_dp_step(layer, optimizer, strategy, mesh,
                                        loss_method=loss_method)
    wrapped = MethodAdapter(layer, loss_method) if loss_method else layer
    params = param_arrays(layer)
    state = state_arrays(layer)
    opt_state = optimizer.functional_init(params)

    amp_on = bool(strategy.amp)
    pure_bf16 = amp_on and strategy.amp_configs.use_pure_bf16
    recompute = bool(strategy.recompute)
    n_tp = int(mesh.shape.get("tp", 1))
    n_dp = int(mesh.shape.get("dp", 1))
    n_sp = int(mesh.shape.get("sp", 1))
    n_ep = int(mesh.shape.get("ep", 1))
    stage = strategy.sharding_stage()
    k_merge = (strategy.gradient_merge_configs.k_steps
               if strategy.gradient_merge else 1)

    # ---- parameter/state shardings ---------------------------------------
    tp_specs = _tp_specs(layer, params, strategy) \
        if (n_tp > 1 or n_ep > 1) else \
        {k: P(*([None] * getattr(v, "ndim", 0))) for k, v in params.items()}
    scan_stacked = _scan_stacked_names(layer)
    if stage >= 1:
        zspecs = zero_mod.shard_specs(params, "dp", n_dp,
                                      skip_leading=scan_stacked)
        pspecs = _merge_specs(tp_specs, zspecs if stage >= 3 else
                              {k: P(*([None] * getattr(v, "ndim", 0)))
                               for k, v in params.items()})
        slot_specs = _merge_specs(tp_specs, zspecs)
    else:
        pspecs = tp_specs
        slot_specs = tp_specs

    p_sh = {k: NamedSharding(mesh, pspecs[k]) for k in params}
    s_sh = _slot_shardings(mesh, opt_state, params, slot_specs)
    buf_sh = {k: NamedSharding(mesh, P(*([None] * getattr(v, "ndim", 0))))
              for k, v in state.items()}
    # batch over dp; with sequence parallel the seq dim rides 'sp' too
    data_sh = NamedSharding(mesh, P("dp", "sp") if n_sp > 1 else P("dp"))

    # ---- the traced step -------------------------------------------------
    def _run_contexts():
        """One source of truth for the amp + sequence-parallel scopes the
        train AND eval traces run under."""
        import contextlib

        from ... import amp as amp_mod
        from ...nn.functional.attention import seq_parallel_scope
        sp_ctx = (seq_parallel_scope(
            mesh, "sp", impl=strategy.sequence_parallel_impl,
            batch_axis="dp" if n_dp > 1 else None,
            head_axis="tp" if n_tp > 1 else None)
            if n_sp > 1 else contextlib.nullcontext())
        amp_ctx = amp_mod.auto_cast(enable=amp_on,
                                    level="O2" if pure_bf16 else "O1",
                                    dtype="bfloat16")
        return sp_ctx, amp_ctx

    def forward_loss(p, st, key, *data):
        sp_ctx, amp_ctx = _run_contexts()
        with random_mod.key_scope(key):
            with amp_ctx:
                with sp_ctx:
                    out, new_state = functional_call(wrapped, p, st, *data)
        return out, new_state

    if recompute:
        # reference RecomputeOptimizer/backward.py:725; on TPU this is
        # jax.checkpoint — recompute activations in backward instead of
        # storing them (SURVEY.md §8.4). Models exposing the per-block
        # protocol get block-scoped checkpoints (peak memory = ONE
        # block's activations); a whole-forward checkpoint is the
        # fallback and only trades compute, not peak memory.
        policy = getattr(jax.checkpoint_policies,
                         strategy.recompute_configs.policy, None)
        if hasattr(layer, "enable_block_recompute"):
            # set/restore AROUND the traced forward only — a persistent
            # flag would leak block remat into later compiles of the
            # same layer and into eager jax.grad use
            _inner_fl = forward_loss

            def forward_loss(p, st, key, *data):
                prev = getattr(layer, "_recompute_blocks", False)
                prev_pol = getattr(layer, "_recompute_policy", None)
                layer.enable_block_recompute(True, policy=policy)
                try:
                    return _inner_fl(p, st, key, *data)
                finally:
                    layer._recompute_blocks = prev
                    layer._recompute_policy = prev_pol
        else:
            forward_loss = jax.checkpoint(
                forward_loss, policy=policy, static_argnums=())

    def train_step(p, st, opt_st, key, lr, data):
        if k_merge > 1:
            # gradient merge: split the batch into k microbatches and
            # accumulate grads in a scan (GradientMergeOptimizer analog)
            def micro(carry, mb):
                acc, st_c, i = carry
                def loss_of(pp):
                    out, new_st = forward_loss(pp, st_c,
                                               jax.random.fold_in(key, i),
                                               *mb)
                    return out, new_st
                (loss, new_st), g = jax.value_and_grad(
                    loss_of, has_aux=True)(p)
                acc = jax.tree_util.tree_map(jnp.add, acc, g)
                return (acc, new_st, i + 1), loss

            micro_data = [d.reshape((k_merge, d.shape[0] // k_merge)
                                    + d.shape[1:]) for d in data]
            zero = jax.tree_util.tree_map(jnp.zeros_like, p)
            (grads, new_state, _), losses = jax.lax.scan(
                micro, (zero, st, 0), tuple(micro_data))
            if strategy.gradient_merge_configs.avg:
                grads = jax.tree_util.tree_map(lambda g: g / k_merge, grads)
            loss = losses.mean()
        else:
            def loss_of(pp):
                out, new_st = forward_loss(pp, st, key, *data)
                return out, new_st
            (loss, new_state), grads = jax.value_and_grad(
                loss_of, has_aux=True)(p)
        grads = nan_inf.guard_tree(grads)   # FLAGS_check_nan_inf, jit path
        if scan_stacked and stage >= 1 and n_dp > 1:
            # pin scan-stacked grads replicated: letting the dp-sharded
            # Adam slots propagate a partition into the scan-transpose's
            # dynamic_update_slice accumulator miscompiles in XLA:CPU
            # (heap corruption) — reshard at the update instead
            grads = {k: (jax.lax.with_sharding_constraint(
                             v, NamedSharding(mesh,
                                              P(*([None] * v.ndim))))
                         if k in scan_stacked else v)
                     for k, v in grads.items()}
        new_p, new_opt = optimizer.functional_update(p, grads, opt_st, lr=lr)
        return loss, new_p, new_state, new_opt

    jitted = jax.jit(
        train_step,
        # data is a tuple pytree; a single sharding broadcasts to all leaves
        in_shardings=(p_sh, buf_sh, s_sh, None, None, None),
        out_shardings=(NamedSharding(mesh, P()), p_sh, buf_sh, s_sh),
        donate_argnums=(0, 2))

    # true copy on params only (donated argnum 0): an aliasing placement
    # would leave the program's donated buffers sharing storage with the
    # layer's own arrays, so the user's Tensors die after step 1 — and
    # device_put(may_alias=False) still aliases on this jax build's CPU
    # backend. state (argnum 1) is never donated.
    params = {k: unaliased_put(v, p_sh[k]) for k, v in params.items()}
    state = jax.device_put(state, buf_sh)
    opt_state = _put_opt_state(opt_state, s_sh)

    prog = CompiledTrainStep(jitted, params, state, opt_state,
                             {"params": p_sh, "opt": s_sh}, mesh, layer,
                             data_sh)
    prog._opt = optimizer

    def _eval_builder():
        # when the layer exposes loss_and_outs (hapi's adapter does),
        # the sharded eval also returns the forward outputs so Metric
        # states accumulate WITHOUT gathering params — only the batch's
        # outputs cross to host (reference hapi/model.py:810 runs
        # metrics uniformly through prepare/fit/evaluate)
        has_outs = getattr(layer, "loss_and_outs", None) is not None
        wrapped_eval = (MethodAdapter(layer, "loss_and_outs") if has_outs
                        else None)

        def eval_fn(p, st, data):
            # fixed key: eval-mode layers draw no dropout, and any
            # stray randomness must at least be deterministic
            if has_outs:
                sp_ctx, amp_ctx = _run_contexts()
                with random_mod.key_scope(jax.random.key(0)):
                    with amp_ctx:
                        with sp_ctx:
                            (loss, outs), _ = functional_call(
                                wrapped_eval, p, st, *data)
                return loss, outs
            out, _ = forward_loss(p, st, jax.random.key(0), *data)
            return out

        out_sh = ((NamedSharding(mesh, P()), None) if has_outs
                  else NamedSharding(mesh, P()))
        ejit = jax.jit(eval_fn, in_shardings=(p_sh, buf_sh, None),
                       out_shardings=out_sh)

        def runner(p, st, data):
            # trace under eval mode (dropout off, BN uses running stats)
            was = bool(getattr(layer, "training", False))
            if hasattr(layer, "eval"):
                layer.eval()
            try:
                return ejit(p, st, data)
            finally:
                if was and hasattr(layer, "train"):
                    layer.train()

        return runner

    prog._eval_builder = _eval_builder
    prog._eval_batch_divisor = max(n_dp, 1)
    prog._eval_returns_outs = (getattr(layer, "loss_and_outs", None)
                               is not None)
    return prog


# ---------------------------------------------------------------------------
# pipeline-parallel step (strategy.pipeline / pp_degree > 1)
# ---------------------------------------------------------------------------

def _claim_free_dim(spec, shape, axis, n):
    """Spec with `axis` claimed on the first unsharded dim divisible by n
    (unchanged if none qualifies) — the ZeRO slot-sharding rule."""
    dims = list(spec) + [None] * (len(shape) - len(spec))
    for i, (s, d) in enumerate(zip(dims, shape)):
        if s is None and d % n == 0 and d >= n:
            dims[i] = axis
            return P(*dims)
    return spec


def _check_pipeline_compat(strategy, mesh, what="pipeline",
                           allow_sp=False, allow_ep=False):
    if strategy.sharding and strategy.sharding_stage() >= 3:
        raise NotImplementedError(
            f"{what} + ZeRO-3 is not supported: stage-3 param sharding "
            "conflicts with the pipeline's stacked-over-'pp' param layout "
            "— use sharding stage 1/2 (optimizer-state sharding over dp)")
    if strategy.sharding and int(mesh.shape.get("dp", 1)) < 2:
        raise ValueError(f"{what} + sharding needs dp >= 2 in the mesh")
    if strategy.gradient_merge and strategy.gradient_merge_configs.k_steps > 1:
        raise NotImplementedError(
            f"{what} already microbatches via "
            "pipeline_configs.accumulate_steps; gradient_merge on top is "
            "not supported — fold k_steps into accumulate_steps")
    if int(mesh.shape.get("sp", 1)) > 1 and not allow_sp:
        raise NotImplementedError(
            f"{what} + sequence parallel needs the layer's "
            "pipeline_block_fn_sp protocol (models/gpt.py provides it)")
    if int(mesh.shape.get("ep", 1)) > 1 and not allow_ep:
        raise NotImplementedError(
            f"{what} + expert parallel needs the layer's "
            "pipeline_block_fn_ep protocol (models/gpt.py provides it "
            "for MoE configs)")


def _build_pipeline_program(layer, optimizer, strategy, mesh, *, block_fn,
                            embed_fn, head_loss_fn, ep, hp, stacked,
                            n_layers, stacked_pspec, prog_cls,
                            seq_axis=None, replicated_axes=(),
                            aux_from_blocks=False, aux_coef=0.01):
    """The machinery both pipeline branches share: flat param assembly
    (embed.* / head.* / stacked.*), shardings, the microbatched
    global-masked-mean loss, jit wiring and program construction. The
    branches differ only in how the stacked block params are laid out and
    what block_fn runs inside the pipeline shard_map."""
    from ..pipeline import pipeline_value_and_grad

    n_pp = int(mesh.shape["pp"])
    n_dp = int(mesh.shape.get("dp", 1))
    n_micro = max(int(strategy.pipeline_configs.accumulate_steps), 1)
    amp_on = bool(strategy.amp)
    pure_bf16 = amp_on and strategy.amp_configs.use_pure_bf16

    if strategy.recompute:
        policy = getattr(jax.checkpoint_policies,
                         strategy.recompute_configs.policy, None)
        block_fn = jax.checkpoint(block_fn, policy=policy)

    state = state_arrays(layer)
    flat = {}
    flat.update({f"embed.{k}": v for k, v in ep.items()})
    flat.update({f"head.{k}": v for k, v in hp.items()})
    flat.update({f"stacked.{k}": v for k, v in stacked.items()})
    opt_state = optimizer.functional_init(flat)

    def _pspec(k, v):
        if k.startswith("stacked."):
            return stacked_pspec(k[len("stacked."):], v)
        return P(*([None] * v.ndim))

    pspecs = {k: _pspec(k, v) for k, v in flat.items()}
    p_sh = {k: NamedSharding(mesh, pspecs[k]) for k in flat}
    # pipeline + ZeRO-1/2: optimizer slots additionally shard over 'dp'
    # on the first free, divisible dim (params keep the pipeline layout;
    # XLA re-tiles grads at the update boundary — the reduce-scatter)
    if strategy.sharding and strategy.sharding_stage() >= 1 and n_dp > 1:
        slot_specs = {k: _claim_free_dim(pspecs[k], flat[k].shape, "dp",
                                         n_dp)
                      for k in flat}
    else:
        slot_specs = pspecs
    s_sh = _slot_shardings(mesh, opt_state, flat, slot_specs)
    buf_sh = {k: NamedSharding(mesh, P(*([None] * getattr(v, "ndim", 0))))
              for k, v in state.items()}
    # data arrives pre-microbatched ([n_micro, mb, T] via _data_preproc),
    # so the spec leads with the unsharded micro dim
    data_sh = NamedSharding(
        mesh, P(None, "dp" if n_dp > 1 else None, seq_axis))

    # shard_map in_specs derive from the SAME pspecs the jit in_shardings
    # use — one source of truth for the stacked layout. Training runs the
    # true-1F1B fused fwd+bwd scheduler (O(n_stages) activation memory —
    # section_worker.cc:128-165's profile); jax.grad over the forward
    # scheduler would store residuals for all n_micro microbatches.
    import inspect as _inspect

    def _takes(fn_, name):
        try:
            return name in _inspect.signature(fn_).parameters
        except (TypeError, ValueError):
            return False

    schedule = getattr(strategy.pipeline_configs, "schedule_mode", "1F1B")
    if schedule not in ("1F1B", "F-then-B"):
        raise ValueError(
            f"pipeline_configs.schedule_mode must be '1F1B' or "
            f"'F-then-B', got {schedule!r} (reference "
            f"distributed_strategy.proto schedule_mode)")

    pipe_vag = pipeline_value_and_grad(
        block_fn, embed_fn, head_loss_fn, n_pp, n_micro, mesh, axis="pp",
        batch_axis="dp" if n_dp > 1 else None,
        param_specs={k[len("stacked."):]: v for k, v in pspecs.items()
                     if k.startswith("stacked.")},
        seq_axis=seq_axis,
        block_takes_key=_takes(block_fn, "key"),
        embed_takes_key=_takes(embed_fn, "key"),
        replicated_axes=replicated_axes,
        aux_from_blocks=aux_from_blocks, aux_coef=aux_coef)

    # F-then-B (stored residuals): jax.grad over the forward scheduler —
    # residuals for all n_micro microbatches stay live (GPipe memory
    # profile) but the backward re-executes NOTHING, the reference's
    # no-recompute SectionWorker profile (section_worker.cc:128-165).
    # 1F1B (default) re-linearizes per backward slot: O(n_stages)
    # activation memory at a ~1.3x forward-FLOPs tax.
    from ..pipeline import pipeline_spmd as _pipe_fwd_builder
    pipe_fwd = _pipe_fwd_builder(
        block_fn, n_pp, n_micro, mesh, axis="pp",
        batch_axis="dp" if n_dp > 1 else None,
        param_specs={k[len("stacked."):]: v for k, v in pspecs.items()
                     if k.startswith("stacked.")},
        seq_axis=seq_axis, aux_from_blocks=aux_from_blocks)
    embed_takes_key = _takes(embed_fn, "key")
    block_takes_key = _takes(block_fn, "key")

    def _sub(p, prefix):
        cut = len(prefix)
        return {k[cut:]: v for k, v in p.items() if k.startswith(prefix)}

    def _fthenb_loss(p, ids, labels, key):
        epp = _sub(p, "embed.")
        hpp = _sub(p, "head.")
        spp = _sub(p, "stacked.")
        n_local = n_layers // n_pp
        batch_axis = "dp" if n_dp > 1 else None

        if embed_takes_key and key is not None:
            # embed dropout must draw per-(data-shard, microbatch) masks
            # with the SAME fold order as the 1F1B scheduler
            # (data ranks -> microbatch -> embed tag) so the two
            # schedule modes are mask-identical
            def emb_sm(ep_, ids_, k_):
                from ..pipeline import embed_key_tag, fold_data_axes
                k_ = fold_data_axes(k_, batch_axis, seq_axis)
                t_loc = ids_.shape[-1]
                pos_off = (jax.lax.axis_index(seq_axis) * t_loc
                           if seq_axis is not None else 0)

                def one(ids_m, m):
                    k_m = jax.random.fold_in(k_, m)
                    kw = {"key": embed_key_tag(k_m, n_local * n_pp)}
                    if seq_axis is not None:
                        kw["pos_offset"] = pos_off
                    return embed_fn(ep_, ids_m, **kw)
                return jax.vmap(one)(ids_, jnp.arange(n_micro))
            rep = jax.tree_util.tree_map(
                lambda v: P(*([None] * v.ndim)), epp)
            hspec = P(None, batch_axis, seq_axis, None)
            h = jax.shard_map(
                emb_sm, mesh=mesh,
                in_specs=(rep, P(None, batch_axis, seq_axis), P()),
                out_specs=hspec, check_vma=False)(epp, ids, key)
        else:
            h = jax.vmap(lambda i_: embed_fn(epp, i_))(ids)
        out = pipe_fwd(spp, h, key if block_takes_key else None)
        if aux_from_blocks:
            h_out, aux_s = out
        else:
            h_out, aux_s = out, 0.0
        sums, counts = jax.vmap(
            head_loss_fn, in_axes=(None, None, 0, 0))(hpp, epp, h_out,
                                                      labels)
        loss = sums.sum() / jnp.maximum(counts.sum(), 1.0)
        if aux_from_blocks:
            loss = loss + aux_coef * aux_s / (n_layers * n_micro)
        return loss

    def train_step_fthenb(p, st, opt_st, key, lr, data):
        ids, labels = data
        from ... import amp as amp_mod
        with random_mod.key_scope(key):
            with amp_mod.auto_cast(enable=amp_on,
                                   level="O2" if pure_bf16 else "O1",
                                   dtype="bfloat16"):
                loss, grads = jax.value_and_grad(
                    lambda pp: _fthenb_loss(pp, ids, labels, key))(p)
        grads = nan_inf.guard_tree(grads)
        new_p, new_opt = optimizer.functional_update(p, grads, opt_st,
                                                     lr=lr)
        return loss, new_p, st, new_opt

    def train_step(p, st, opt_st, key, lr, data):
        ids, labels = data
        from ... import amp as amp_mod
        with random_mod.key_scope(key):
            with amp_mod.auto_cast(enable=amp_on,
                                   level="O2" if pure_bf16 else "O1",
                                   dtype="bfloat16"):
                epp = _sub(p, "embed.")
                hpp = _sub(p, "head.")
                spp = _sub(p, "stacked.")
                out = pipe_vag(spp, epp, hpp, ids, labels, key)
                if aux_from_blocks:
                    sums, counts, d_sp, d_ep, d_hp, aux_s = out
                else:
                    sums, counts, d_sp, d_ep, d_hp = out
        # global masked mean across all microbatches: grads came back as
        # grads of loss_SUM; the valid-count denominator is
        # label-determined (param-independent), so scaling is exact
        denom = jnp.maximum(counts, 1.0)
        loss = sums / denom
        if aux_from_blocks:
            # the scheduler pre-scaled the aux grad seed by denom, so
            # the /denom below lands both terms at this exact loss
            loss = loss + aux_coef * aux_s / (n_layers * n_micro)
        grads = {}
        grads.update({f"embed.{k}": v / denom for k, v in d_ep.items()})
        grads.update({f"head.{k}": v / denom for k, v in d_hp.items()})
        grads.update({f"stacked.{k}": v / denom for k, v in d_sp.items()})
        grads = nan_inf.guard_tree(grads)   # FLAGS_check_nan_inf, jit path
        new_p, new_opt = optimizer.functional_update(p, grads, opt_st, lr=lr)
        return loss, new_p, st, new_opt

    jitted = jax.jit(
        train_step_fthenb if schedule == "F-then-B" else train_step,
        in_shardings=(p_sh, buf_sh, s_sh, None, None, None),
        out_shardings=(NamedSharding(mesh, P()), p_sh, buf_sh, s_sh),
        donate_argnums=(0, 2))

    # true copy on the donated params only (see compile_train_step)
    flat = {k: unaliased_put(v, p_sh[k]) for k, v in flat.items()}
    state = jax.device_put(state, buf_sh)
    opt_state = _put_opt_state(opt_state, s_sh)

    prog = prog_cls(jitted, flat, state, opt_state,
                    {"params": p_sh, "opt": s_sh}, mesh, layer, data_sh)
    prog._opt = optimizer
    prog._n_layers = n_layers
    prog._step_label = "fleet.pipeline_step"

    def _microbatch(d):
        if d.shape[0] % n_micro:
            raise ValueError(
                f"pipeline batch {d.shape[0]} not divisible by "
                f"accumulate_steps {n_micro}")
        return d.reshape((n_micro, d.shape[0] // n_micro) + d.shape[1:])
    prog._data_preproc = _microbatch

    def _eval_builder():
        from ..pipeline import pipeline_spmd

        # forward-only pipeline: the GPipe-shaped residuals of
        # pipeline_spmd don't matter without a backward, and eval mode
        # draws no dropout so the blocks need no keys. MoE blocks keep
        # their aux so eval loss matches the train step's definition.
        pipe = pipeline_spmd(
            block_fn, n_pp, n_micro, mesh, axis="pp",
            batch_axis="dp" if n_dp > 1 else None,
            param_specs={k[len("stacked."):]: v
                         for k, v in pspecs.items()
                         if k.startswith("stacked.")},
            seq_axis=seq_axis, aux_from_blocks=aux_from_blocks)

        def eval_fn(p, st, data):
            ids, labels = data
            from ... import amp as amp_mod
            with amp_mod.auto_cast(enable=amp_on,
                                   level="O2" if pure_bf16 else "O1",
                                   dtype="bfloat16"):
                epp = _sub(p, "embed.")
                hpp = _sub(p, "head.")
                spp = _sub(p, "stacked.")
                h = jax.vmap(embed_fn, in_axes=(None, 0))(epp, ids)
                out = pipe(spp, h)
                h, aux_s = out if aux_from_blocks else (out, 0.0)
                sums, counts = jax.vmap(
                    head_loss_fn, in_axes=(None, None, 0, 0))(
                    hpp, epp, h, labels)
            loss = sums.sum() / jnp.maximum(counts.sum(), 1.0)
            if aux_from_blocks:
                loss = loss + aux_coef * aux_s / (n_layers * n_micro)
            return loss

        ejit = jax.jit(eval_fn, in_shardings=(p_sh, buf_sh, None),
                       out_shardings=NamedSharding(mesh, P()))

        def runner(p, st, data):
            was = bool(getattr(layer, "training", False))
            if hasattr(layer, "eval"):
                layer.eval()
            try:
                return ejit(p, st, data)
            finally:
                if was and hasattr(layer, "train"):
                    layer.train()

        return runner

    prog._eval_builder = _eval_builder
    # batch divisibility the sharded eval requires (partial final
    # batches fall back to the caller's synced path)
    prog._eval_batch_divisor = n_micro * max(n_dp, 1)
    return prog


def _compile_pipeline_step(layer, optimizer, strategy, mesh):
    """PP branch of the strategy compiler.

    Reference: PipelineOptimizer splits the Program into per-stage sections
    executed by SectionWorker 1F1B loops (optimizer.py:3718,
    section_worker.cc:98-165). TPU-native: the layer supplies an
    (embed, blocks, head) decomposition; homogeneous blocks are stacked on
    a leading layer axis sharded over 'pp' and driven by the SPMD schedule
    in distributed/pipeline.py (ppermute ring inside one jitted scan).
    Composes with dp (microbatch dim sharded over 'dp'), tp (the manual-tp
    branch below), recompute (jax.checkpoint per block) and AMP (autocast
    inside the traced blocks). Microbatches = accumulate_steps.
    """
    from ..pipeline import stack_stage_params

    n_tp = int(mesh.shape.get("tp", 1))
    n_sp = int(mesh.shape.get("sp", 1))
    if n_tp > 1:
        return _compile_pipeline_tp_step(layer, optimizer, strategy, mesh,
                                         n_tp, n_sp=n_sp)
    n_ep = int(mesh.shape.get("ep", 1))
    sp_block = getattr(layer, "pipeline_block_fn_sp", None)
    ep_block = getattr(layer, "pipeline_block_fn_ep", None)
    _check_pipeline_compat(strategy, mesh,
                           allow_sp=callable(sp_block),
                           allow_ep=callable(ep_block))
    split = getattr(layer, "pipeline_split_params", None)
    fns = getattr(layer, "pipeline_fns", None)
    if not (callable(split) and callable(fns)):
        raise TypeError(
            "pipeline=True requires the layer to implement "
            "pipeline_split_params(params) and pipeline_fns() "
            "(see models/gpt.py for the protocol)")

    params = param_arrays(layer)
    ep, blocks_list, hp = split(params)
    n_pp = int(mesh.shape["pp"])
    if len(blocks_list) % n_pp:
        raise ValueError(f"{len(blocks_list)} blocks not divisible by "
                         f"pp={n_pp}")
    embed_fn, block_fn, head_loss_fn = fns()
    if n_ep > 1:
        # pp x ep: activations replicate over 'ep'; each member runs its
        # local expert slab and one psum sums contributions (manual form
        # of the GSPMD einsum dispatch). Stacked expert banks shard their
        # E dim over 'ep' via the layer's block_ep_specs.
        experts = getattr(getattr(layer, "cfg", None), "moe_experts", None)
        if experts is not None and experts % n_ep:
            raise ValueError(f"{experts} experts not divisible by "
                             f"ep={n_ep}")
        # Switch load-balance aux rides the 1F1B backward slot (blocks
        # return (h, aux)); routing IS regularized on this path. With
        # sp > 1 the block additionally runs ring/Ulysses attention over
        # the sequence shards (pp x sp x ep — formerly refused)
        ep_kw = {}
        if n_sp > 1:
            heads_ep = getattr(getattr(layer, "cfg", None), "heads", None)
            if (strategy.sequence_parallel_impl == "ulysses"
                    and heads_ep is not None and heads_ep % n_sp):
                raise ValueError(
                    f"pipeline + ep + ulysses: {heads_ep} attention heads "
                    f"not divisible by sp={n_sp} (use impl='ring' or "
                    f"adjust sep_degree)")
            ep_kw = {"axis_sp": "sp",
                     "impl": strategy.sequence_parallel_impl}
        block_fn = ep_block(
            axis_ep="ep",
            compute_dtype="bfloat16" if strategy.amp else None,
            with_aux=True, **ep_kw)
        ep_specs = layer.block_ep_specs(axis_pp="pp", axis_ep="ep")

        def ep_pspec(rel, v):
            spec = ep_specs.get(rel)
            if spec is None:
                raise KeyError(f"block_ep_specs missing {rel!r}")
            return spec

        return _build_pipeline_program(
            layer, optimizer, strategy, mesh, block_fn=block_fn,
            embed_fn=embed_fn, head_loss_fn=head_loss_fn, ep=ep, hp=hp,
            stacked=stack_stage_params(blocks_list),
            n_layers=len(blocks_list), stacked_pspec=ep_pspec,
            prog_cls=_PipelineTrainStep, replicated_axes=("ep",),
            seq_axis="sp" if n_sp > 1 else None,
            aux_from_blocks=True,
            aux_coef=float(getattr(getattr(layer, "cfg", None),
                                   "moe_aux_coef", 0.01)))
    if n_sp > 1:
        # pp x sp: blocks see local sequence shards; attention is the
        # shard_map-inner ring/Ulysses (the sp collectives live in the
        # block, the pipeline just also shards the data's seq dim)
        heads = getattr(getattr(layer, "cfg", None), "heads", None)
        if (strategy.sequence_parallel_impl == "ulysses"
                and heads is not None and heads % n_sp):
            raise ValueError(
                f"pipeline + ulysses: {heads} attention heads not "
                f"divisible by sp={n_sp} (use impl='ring' or adjust "
                f"sep_degree)")
        sp_is_moe = bool(getattr(getattr(layer, "cfg", None),
                                 "moe_experts", 0))
        block_fn = sp_block(
            axis_sp="sp", impl=strategy.sequence_parallel_impl,
            compute_dtype="bfloat16" if strategy.amp else None,
            with_aux=sp_is_moe)
    return _build_pipeline_program(
        layer, optimizer, strategy, mesh, block_fn=block_fn,
        embed_fn=embed_fn, head_loss_fn=head_loss_fn, ep=ep, hp=hp,
        stacked=stack_stage_params(blocks_list),
        n_layers=len(blocks_list),
        stacked_pspec=lambda rel, v: P("pp", *([None] * (v.ndim - 1))),
        prog_cls=_PipelineTrainStep,
        seq_axis="sp" if n_sp > 1 else None,
        # plain-branch MoE blocks emit (h, aux) via collect_aux_losses;
        # the sp branch's raw-jnp MoE block threads its aux explicitly
        aux_from_blocks=bool(
            getattr(getattr(layer, "cfg", None), "moe_experts", 0)
            if n_sp > 1
            else getattr(layer, "pipeline_block_emits_aux", False)),
        aux_coef=float(getattr(getattr(layer, "cfg", None),
                               "moe_aux_coef", 0.01)))


def _compile_pipeline_tp_step(layer, optimizer, strategy, mesh, n_tp,
                              n_sp=1):
    """pp x tp (x sp) (x dp) branch: the pipeline shard_map keeps every
    mesh axis manual, so the block function is the layer's hand-written
    Megatron block (models/gpt.py pipeline_block_fn_tp: split qkv head
    groups, explicit psums over 'tp') and the stacked block params are
    physically sharded with the layer's block_tp_specs. With sp > 1 the
    block is pipeline_block_fn_tp_sp — ring/Ulysses attention over 'sp'
    on the local tp head group — and the data's sequence dim shards over
    'sp' (the v5p-64 long-context mesh). Reference analog: a program
    pass emitting c_allreduce inside each pipeline section."""
    from ..pipeline import stack_stage_params

    need_fns = ["split_block_params_tp", "block_tp_specs",
                "pipeline_split_params", "pipeline_fns",
                "pipeline_block_fn_tp_sp" if n_sp > 1
                else "pipeline_block_fn_tp"]
    for need in need_fns:
        if not callable(getattr(layer, need, None)):
            raise TypeError(
                f"pipeline + tensor_parallel{' + sequence_parallel' if n_sp > 1 else ''} "
                f"requires the layer to implement {need} "
                f"(see models/gpt.py)")
    _check_pipeline_compat(strategy, mesh,
                           what="pipeline+tp" + ("+sp" if n_sp > 1
                                                 else ""),
                           allow_sp=n_sp > 1)
    heads = getattr(getattr(layer, "cfg", None), "heads", None)
    if heads is not None and heads % n_tp:
        raise ValueError(f"{heads} attention heads not divisible by "
                         f"tp={n_tp}")
    if (n_sp > 1 and strategy.sequence_parallel_impl == "ulysses"
            and heads is not None and (heads // n_tp) % n_sp):
        raise ValueError(
            f"pipeline + tp + ulysses: local head count "
            f"{heads // n_tp} (= {heads} heads / tp={n_tp}) not "
            f"divisible by sp={n_sp} (use impl='ring' or adjust "
            f"degrees)")

    params = param_arrays(layer)
    ep, blocks_list, hp = layer.pipeline_split_params(params)
    n_pp = int(mesh.shape["pp"])
    if len(blocks_list) % n_pp:
        raise ValueError(f"{len(blocks_list)} blocks not divisible by "
                         f"pp={n_pp}")
    embed_fn, _, head_loss_fn = layer.pipeline_fns()
    tp_is_moe = bool(getattr(getattr(layer, "cfg", None),
                             "moe_experts", 0))
    if tp_is_moe:
        # expert hidden dims shard over tp (block_tp_specs moe.* rows)
        ffn_hidden = int(getattr(layer.cfg, "ffn_mult", 4)) * \
            int(getattr(layer.cfg, "hidden"))
        if ffn_hidden % n_tp:
            raise ValueError(f"MoE expert hidden {ffn_hidden} not "
                             f"divisible by tp={n_tp}")
    # raw-jnp block ops bypass the autocast dispatcher hook, so AMP is
    # delivered as an explicit compute dtype
    if n_sp > 1:
        block_fn = layer.pipeline_block_fn_tp_sp(
            axis_tp="tp", axis_sp="sp",
            impl=strategy.sequence_parallel_impl,
            compute_dtype="bfloat16" if strategy.amp else None,
            with_aux=tp_is_moe)
    else:
        block_fn = layer.pipeline_block_fn_tp(
            axis_tp="tp",
            compute_dtype="bfloat16" if strategy.amp else None,
            with_aux=tp_is_moe)
    split_blocks = [layer.split_block_params_tp(b) for b in blocks_list]
    tp_specs = layer.block_tp_specs(axis_pp="pp", axis_tp="tp")

    def stacked_pspec(rel, v):
        spec = tp_specs.get(rel)
        if spec is None:
            raise KeyError(f"block_tp_specs missing {rel!r}")
        return spec

    return _build_pipeline_program(
        layer, optimizer, strategy, mesh, block_fn=block_fn,
        embed_fn=embed_fn, head_loss_fn=head_loss_fn, ep=ep, hp=hp,
        stacked=stack_stage_params(split_blocks),
        n_layers=len(blocks_list), stacked_pspec=stacked_pspec,
        prog_cls=_PipelineTpTrainStep, replicated_axes=("tp",),
        seq_axis="sp" if n_sp > 1 else None,
        aux_from_blocks=tp_is_moe,
        aux_coef=float(getattr(getattr(layer, "cfg", None),
                               "moe_aux_coef", 0.01)))



class _PipelineTrainStep(CompiledTrainStep):
    """CompiledTrainStep whose param dict uses the pipeline layout
    (embed.* / head.* / stacked.*[L, ...]); write_back unstacks."""

    def write_back(self):
        lookup = dict(self.layer.named_parameters())
        lookup.update(dict(self.layer.named_buffers()))
        stacked = {}
        for k, v in self.params.items():
            if k.startswith("embed.") or k.startswith("head."):
                name = k.split(".", 1)[1]
                if name in lookup:
                    lookup[name]._data = jax.device_get(v)
            elif k.startswith("stacked."):
                stacked[k[len("stacked."):]] = jax.device_get(v)
        self._write_back_stacked(lookup, stacked)
        for k, v in self.state.items():
            if k in lookup:
                lookup[k]._data = jax.device_get(v)

    def _write_back_stacked(self, lookup, stacked):
        for rel, arr in stacked.items():
            name = "blocks." + rel
            if name in lookup and \
                    tuple(lookup[name]._data.shape) == tuple(arr.shape):
                # scan layout: the layer itself holds the [L, ...] stack
                lookup[name]._data = arr
                continue
            for i in range(self._n_layers):
                name = f"blocks.{i}.{rel}"
                if name in lookup:
                    lookup[name]._data = arr[i]


class _PipelineTpTrainStep(_PipelineTrainStep):
    """Pipeline layout with manual-tp split blocks: write_back merges the
    split q/k/v back into the packed qkv params (layer protocol
    merge_block_params_tp)."""

    def _write_back_stacked(self, lookup, stacked):
        scan_rows = {}          # scan layout: collect rows, stack once
        for i in range(self._n_layers):
            split_i = {rel: arr[i] for rel, arr in stacked.items()}
            for rel, arr in self.layer.merge_block_params_tp(
                    split_i).items():
                name = f"blocks.{i}.{rel}"
                if name in lookup:
                    lookup[name]._data = arr
                else:
                    scan_rows.setdefault(rel, []).append(arr)
        for rel, rows in scan_rows.items():
            name = "blocks." + rel
            if name in lookup and len(rows) == self._n_layers:
                lookup[name]._data = np.stack(
                    [np.asarray(r) for r in rows])
