"""DistributedStrategy (reference: fleet/base/distributed_strategy.py:104
backed by framework/distributed_strategy.proto:126 — amp, recompute,
sharding, pipeline, gradient_merge, hybrid degrees...).

TPU-native: the strategy compiles to (mesh axes, PartitionSpecs, step
transforms) instead of program rewrites. Field names keep paddle's
surface so fleet user code ports over.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Optional

import jax

from .. import mesh as mesh_mod


@dataclasses.dataclass
class HybridConfig:
    dp_degree: int = -1          # -1: fill with remaining devices
    mp_degree: int = 1           # tensor parallel ('tp' axis)
    pp_degree: int = 1           # pipeline ('pp' axis)
    sharding_degree: int = 1     # ZeRO group size over dp
    sep_degree: int = 1          # sequence parallel ('sp' axis)
    ep_degree: int = 1           # expert parallel ('ep' axis, MoE)


@dataclasses.dataclass
class ShardingConfig:
    stage: int = 2               # proto: sharding_segment_strategy analogue
    degree: int = -1
    fuse_broadcast_MB: float = 32.0   # kept for API parity; XLA fuses


@dataclasses.dataclass
class PipelineConfig:
    micro_batch_size: int = 1
    accumulate_steps: int = 1
    schedule_mode: str = "1F1B"  # proto distributed_strategy.proto:120-124


@dataclasses.dataclass
class RecomputeConfig:
    checkpoints: list = dataclasses.field(default_factory=list)
    policy: str = "dots_saveable"   # jax.checkpoint policy name


@dataclasses.dataclass
class AMPConfig:
    init_loss_scaling: float = 2.0 ** 15
    use_dynamic_loss_scaling: bool = True
    custom_white_list: list = dataclasses.field(default_factory=list)
    custom_black_list: list = dataclasses.field(default_factory=list)
    use_pure_bf16: bool = False


@dataclasses.dataclass
class GradientMergeConfig:
    k_steps: int = 1
    avg: bool = True


@dataclasses.dataclass
class LocalSGDConfig:
    k_steps: int = 4             # sync params every k local steps
    begin_step: int = 1          # warm-up: sync every step before this


@dataclasses.dataclass
class AdaptiveLocalSGDConfig:
    init_k_steps: int = 1
    begin_step: int = 1


@dataclasses.dataclass
class DGCConfig:
    rampup_begin_step: int = 0   # dense allreduce before this step
    sparsity: float = 0.999      # fraction dropped; keep ratio = 1-sparsity
    momentum: float = 0.9


class DistributedStrategy:
    """Mutable strategy object with paddle's toggles-as-properties shape."""

    def __init__(self):
        self.amp = False
        self.amp_configs = AMPConfig()
        self.recompute = False
        self.recompute_configs = RecomputeConfig()
        self.sharding = False
        self.sharding_configs = ShardingConfig()
        self.pipeline = False
        self.pipeline_configs = PipelineConfig()
        self.gradient_merge = False
        self.gradient_merge_configs = GradientMergeConfig()
        self.tensor_parallel = False
        self.sequence_parallel = False
        self.sequence_parallel_impl = "ring"   # "ring" | "ulysses"
        # scan-over-layers (depth-invariant compile): False asks models
        # built with a ScanBlockStack to unroll the stacked params in a
        # Python loop instead of jax.lax.scan (compiler calls the layer's
        # set_scan_unroll protocol). Per-model layout choice stays on the
        # model config (e.g. GPTConfig.scan_layers).
        self.scan_layers = True
        self.expert_parallel = False
        self.hybrid_configs = HybridConfig()
        self.find_unused_parameters = False
        self.fuse_all_reduce_ops = True     # parity no-op: XLA fuses
        self.fuse_grad_size_in_MB = 32      # parity no-op
        self.nccl_comm_num = 1              # parity no-op: no NCCL
        # gradient-communication meta-optimizers (fleet/grad_comm.py)
        self.localsgd = False
        self.localsgd_configs = LocalSGDConfig()
        self.adaptive_localsgd = False
        self.adaptive_localsgd_configs = AdaptiveLocalSGDConfig()
        self.dgc = False
        self.dgc_configs = DGCConfig()
        self.fp16_allreduce = False         # bf16 on TPU (f32 exponent)
        self.hierarchical_allreduce = False  # parity no-op: XLA owns topology
        # optimizer-swap toggles (lars/lamb meta-optimizers: the reference
        # rewrites momentum->lars_momentum ops; here fleet swaps the
        # optimizer class at compile time when these are set)
        self.lars = False
        self.lamb = False

    # -- mesh compilation --------------------------------------------------
    def resolve_degrees(self, n_devices: int):
        h = self.hybrid_configs
        mp = h.mp_degree if self.tensor_parallel or h.mp_degree > 1 else 1
        pp = h.pp_degree if self.pipeline or h.pp_degree > 1 else 1
        sp = h.sep_degree if self.sequence_parallel or h.sep_degree > 1 else 1
        ep = h.ep_degree if self.expert_parallel or h.ep_degree > 1 else 1
        fixed = mp * pp * sp * ep
        if n_devices % fixed:
            raise ValueError(f"{n_devices} devices not divisible by "
                             f"mp*pp*sp*ep={fixed}")
        dp = h.dp_degree if h.dp_degree > 0 else n_devices // fixed
        if dp * fixed != n_devices:
            raise ValueError(
                f"dp({dp})*mp({mp})*pp({pp})*sp({sp})*ep({ep}) "
                f"!= {n_devices}")
        return {"dp": dp, "pp": pp, "sp": sp, "tp": mp, "ep": ep}

    def build_mesh(self, devices=None):
        devices = list(devices if devices is not None else jax.devices())
        deg = self.resolve_degrees(len(devices))
        # axis order pp > dp > sp > tp: tp innermost rides the fastest ICI
        # links; pp outermost tolerates the most latency (scaling-book
        # ordering), mirroring the reference's ring nesting
        shape = {k: v for k, v in
                 (("pp", deg["pp"]), ("dp", deg["dp"]), ("ep", deg["ep"]),
                  ("sp", deg["sp"]), ("tp", deg["tp"]))}
        mesh = mesh_mod.build_mesh(shape, devices=devices)
        mesh_mod.set_mesh(mesh)
        return mesh

    def sharding_stage(self):
        if not self.sharding:
            return 0
        return int(self.sharding_configs.stage)

    def __repr__(self):
        on = [k for k in ("amp", "recompute", "sharding", "pipeline",
                          "gradient_merge", "tensor_parallel",
                          "sequence_parallel", "expert_parallel")
              if getattr(self, k)]
        return f"DistributedStrategy(enabled={on}, hybrid={self.hybrid_configs})"
