"""fleet.metrics — cross-worker metric aggregation.

Reference: python/paddle/distributed/fleet/metrics/metric.py (sum/max/
min/auc/mae/rmse/acc over a fleet allreduce of local accumulators). The
TPU transport is the collective backend (XLA psum over ICI / DCN
jax.distributed); each helper reduces a local numpy/Tensor value across
workers and returns the global metric on every rank."""
from __future__ import annotations

import numpy as np

__all__ = ["sum", "max", "min", "auc", "mae", "mse", "rmse", "acc"]

_pysum, _pymax, _pymin = sum, max, min


def _allreduce(value, mode="sum"):
    from .. import collective, env
    from ...core.tensor import to_tensor
    arr = np.asarray(value, np.float64)
    if env.get_world_size() <= 1:
        return arr
    op = {"sum": collective.ReduceOp.SUM, "max": collective.ReduceOp.MAX,
          "min": collective.ReduceOp.MIN}[mode]
    return np.asarray(collective.all_reduce(
        to_tensor(arr), op=op).numpy())


def sum(input, scope=None, util=None):  # noqa: A001
    """Global sum of a local accumulator (reference metrics.sum)."""
    return _allreduce(input, "sum")


def max(input, scope=None, util=None):  # noqa: A001
    return _allreduce(input, "max")


def min(input, scope=None, util=None):  # noqa: A001
    return _allreduce(input, "min")


def auc(stat_pos, stat_neg, scope=None, util=None):
    """Global AUC from per-worker positive/negative threshold histograms
    (reference metrics.auc: allreduce the two histograms, then the
    trapezoid sweep)."""
    pos = _allreduce(stat_pos, "sum").reshape(-1)
    neg = _allreduce(stat_neg, "sum").reshape(-1)
    # sweep thresholds high->low accumulating (fp, tp); trapezoid area
    tp = fp = 0.0
    area = 0.0
    for i in range(len(pos) - 1, -1, -1):
        new_tp = tp + pos[i]
        new_fp = fp + neg[i]
        area += (new_fp - fp) * (tp + new_tp) / 2.0
        tp, fp = new_tp, new_fp
    if tp == 0 or fp == 0:
        return 0.5
    return float(area / (tp * fp))


def mae(abserr, total_ins_num, scope=None, util=None):
    """Global mean absolute error: allreduce(|err| sum) / allreduce(n)."""
    err = float(_allreduce(abserr, "sum"))
    n = float(_allreduce(total_ins_num, "sum"))
    return err / _pymax(n, 1.0)


def rmse(sqrerr, total_ins_num, scope=None, util=None):
    err = float(_allreduce(sqrerr, "sum"))
    n = float(_allreduce(total_ins_num, "sum"))
    return float(np.sqrt(err / _pymax(n, 1.0)))


def acc(correct, total, scope=None, util=None):
    c = float(_allreduce(correct, "sum"))
    t = float(_allreduce(total, "sum"))
    return c / _pymax(t, 1.0)


def mse(sqrerr, total_ins_num, scope=None, util=None):
    """Global mean squared error (reference metrics.mse:323):
    allreduce(sq err sum) / allreduce(n)."""
    err = float(_allreduce(sqrerr, "sum"))
    n = float(_allreduce(total_ins_num, "sum"))
    return err / _pymax(n, 1.0)
