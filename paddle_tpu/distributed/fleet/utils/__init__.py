"""fleet.utils — filesystem clients + distributed-inference helper.

Reference: python/paddle/distributed/fleet/utils/__init__.py exports
LocalFS + HDFSClient (fs.py:34,419) and DistributedInfer (ps_util.py).
The FS verbs live in io/fs.py (LocalFS for mounted stores, the
fsspec-backed RemoteFS/HDFSClient for object stores); this module is
the fleet-path facade reference code imports from."""
from __future__ import annotations

from ....io.fs import FS, LocalFS, RemoteFS, HDFSClient, sync_dir

__all__ = ["FS", "LocalFS", "RemoteFS", "HDFSClient", "sync_dir",
           "DistributedInfer", "recompute"]


class DistributedInfer:
    """PS inference helper (reference fleet/utils/ps_util.py
    DistributedInfer): pulls the sharded sparse/dense parameters from
    the PS fleet into the local model so inference runs without the
    servers in the loop."""

    def __init__(self, main_program=None, startup_program=None):
        self.main_program = main_program
        self.startup_program = startup_program

    def init_distributed_infer_env(self, exe=None, loss=None,
                                   role_maker=None, dirname=None):
        if dirname:
            self.load_inference_params(dirname)

    def load_inference_params(self, dirname):
        """Load persisted parameters into the bound program/layer."""
        from ....static.compat import load_program_state, set_program_state
        if self.main_program is None:
            raise ValueError("DistributedInfer needs main_program (a "
                             "layer or program holding the parameters)")
        state = load_program_state(dirname)
        set_program_state(self.main_program, state)
        return state

    def get_dist_infer_program(self):
        return self.main_program


def recompute(function, *args, checkpoint_policy=None, **kwargs):
    """Activation recomputation for one block call: forward runs
    normally, residuals are rematerialized in backward (jax.checkpoint —
    the reference's RecomputeFunction CUDA autograd node, as a compiler
    policy). Tensor in/out preserving. `checkpoint_policy` is a
    jax.checkpoint_policies entry (consumed here, not forwarded)."""
    import jax

    from ....core.tensor import Tensor
    from ....framework import unwrap, wrap

    def raw_fn(*raw):
        out = function(*wrap(list(raw)), **kwargs)
        return unwrap(out)

    out = jax.checkpoint(raw_fn, policy=checkpoint_policy)(
        *unwrap(list(args)))
    return jax.tree_util.tree_map(Tensor, out)
