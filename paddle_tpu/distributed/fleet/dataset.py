"""paddle.distributed.fleet.dataset (reference fleet/dataset/
__init__.py re-exports the dataset family): the MultiSlot readers live
in io.data_feed; this is the fleet-path import surface."""
from ...io.data_feed import (InMemoryDataset, QueueDataset,  # noqa: F401
                             Slot, parse_multi_slot_line)

__all__ = ["InMemoryDataset", "QueueDataset", "Slot",
           "parse_multi_slot_line"]
