"""Gradient-communication meta-optimizers: LocalSGD, AdaptiveLocalSGD,
DGC, fp16_allreduce — the explicit-DP branch of the strategy compiler.

Reference analogs (rewrites of the Program's allreduce ops):
  LocalSGDOptimizer          fleet/meta_optimizers/localsgd_optimizer.py
  AdaptiveLocalSGDOptimizer  (same file, adaptive k from loss)
  DGCOptimizer               fleet/meta_optimizers/dgc_optimizer.py +
                             details/sparse_all_reduce_op_handle.cc
  FP16AllReduceOptimizer     fleet/meta_optimizers/fp16_allreduce_optimizer.py

TPU-native design: the implicit-SPMD step (compiler.py) lets XLA insert
the dp gradient mean, which leaves no seam to compress or skip it. These
modes therefore run the whole train step inside one `jax.shard_map` over
the 'dp' axis with *manual* collectives:

  plain            g <- pmean(g, 'dp')
  fp16_allreduce   g <- pmean(bf16(g), 'dp') upcast f32 (half the ICI
                   bytes; bf16 keeps the f32 exponent so no loss scaling)
  dgc              top-k sparsified momentum: u = m*u + g; v += u; send
                   only the top-k (values, indices) via all_gather
                   (2k words instead of n), scatter-add, keep the residual
                   locally (error feedback); momentum-factor masking
  localsgd         NO per-step comm; each dp rank trains on its own batch
                   shard and params are pmean-averaged every k steps
  adaptive_localsgd k recomputed from the loss ratio sqrt(loss0/loss_t)
                   (Wang et al. adaptive communication; paddle's
                   _adaptive_localsgd heuristic)

LocalSGD stores params STACKED on a leading dp axis (sharded P('dp')) so
replicas can genuinely diverge between syncs — under SPMD a "replicated"
array must be identical on every device, so divergence needs its own
axis. DGC's (u, v) residuals are per-rank state and are stacked the same
way.
"""
from __future__ import annotations

from typing import Dict

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ...core import nan_inf
from ...core import random as random_mod
from ...framework import MethodAdapter, functional_call, param_arrays, \
    state_arrays, unaliased_put


def active_mode(strategy) -> str | None:
    """Which explicit-DP mode the strategy asks for (None: implicit SPMD)."""
    on = [m for m in ("localsgd", "adaptive_localsgd", "dgc")
          if getattr(strategy, m, False)]
    if len(on) > 1:
        raise ValueError(f"at most one of localsgd/adaptive_localsgd/dgc "
                         f"may be enabled, got {on}")
    if on:
        if getattr(strategy, "fp16_allreduce", False):
            raise ValueError(
                f"{on[0]} controls the gradient exchange itself; "
                "fp16_allreduce would be a silent no-op — disable one")
        return on[0]
    if getattr(strategy, "fp16_allreduce", False):
        return "fp16_allreduce"
    return None


# ---------------------------------------------------------------------------
# DGC compress/exchange (runs per-rank inside shard_map)
# ---------------------------------------------------------------------------

def _dgc_exchange(g, u, v, momentum, keep_ratio, n_dp, axis="dp"):
    """One DGC round for a single flat gradient: returns (g_global, u', v').

    u: momentum accumulator, v: velocity/error residual (both local).
    Comm cost 2k*n_dp words via all_gather of (values, indices) versus n
    for a dense allreduce.
    """
    n = g.shape[0]
    k = max(1, int(n * keep_ratio))
    u = momentum * u + g
    v = v + u
    vals, idx = jax.lax.top_k(jnp.abs(v), k)
    sel = v[idx]                              # signed top-k values
    # residual: keep everything NOT sent (error feedback) and clear the
    # momentum for sent coordinates (momentum factor masking)
    mask = jnp.zeros((n,), jnp.bool_).at[idx].set(True)
    v = jnp.where(mask, 0.0, v)
    u = jnp.where(mask, 0.0, u)
    all_sel = jax.lax.all_gather(sel, axis)   # [dp, k]
    all_idx = jax.lax.all_gather(idx, axis)   # [dp, k]
    dense = jnp.zeros((n,), g.dtype).at[all_idx.reshape(-1)].add(
        all_sel.reshape(-1))
    return dense / n_dp, u, v


# ---------------------------------------------------------------------------
# the compiled explicit-DP step
# ---------------------------------------------------------------------------

def compile_explicit_dp_step(layer, optimizer, strategy, mesh,
                             loss_method="loss"):
    """Build a CompiledTrainStep whose grad exchange is hand-written inside
    shard_map over 'dp' (localsgd / adaptive_localsgd / dgc /
    fp16_allreduce). Single-axis only: tp/pp/sp/ep must be 1."""
    from .compiler import CompiledTrainStep

    mode = active_mode(strategy)
    assert mode is not None
    for ax in ("tp", "pp", "sp", "ep"):
        if int(mesh.shape.get(ax, 1)) > 1:
            raise NotImplementedError(
                f"{mode} composes only with data parallelism; got "
                f"{ax}={mesh.shape[ax]} (the shard_map region would need "
                f"the {ax} collectives inserted manually)")
    if strategy.sharding:
        raise NotImplementedError(f"{mode} + sharding (ZeRO) is not "
                                  "supported — disable one")
    if strategy.gradient_merge and strategy.gradient_merge_configs.k_steps > 1:
        raise NotImplementedError(f"{mode} + gradient_merge is not "
                                  "supported yet")

    n_dp = int(mesh.shape["dp"])
    amp_on = bool(strategy.amp)
    pure_bf16 = amp_on and strategy.amp_configs.use_pure_bf16
    local_params = mode in ("localsgd", "adaptive_localsgd")

    wrapped = MethodAdapter(layer, loss_method) if loss_method else layer
    params = param_arrays(layer)
    state = state_arrays(layer)
    opt_state = optimizer.functional_init(params)

    if mode == "localsgd":
        cfg = strategy.localsgd_configs
        k0 = max(int(cfg.k_steps), 1)
        begin = int(cfg.begin_step)
    elif mode == "adaptive_localsgd":
        cfg = strategy.adaptive_localsgd_configs
        k0 = max(int(cfg.init_k_steps), 1)
        begin = int(cfg.begin_step)
    elif mode == "dgc":
        cfg = strategy.dgc_configs
        keep_ratio = max(1.0 - float(cfg.sparsity), 1e-6)
        dgc_momentum = float(cfg.momentum)
        rampup = int(cfg.rampup_begin_step)

    # ---- forward/loss on the LOCAL batch shard ---------------------------
    def forward_loss(p, st, key, *data):
        with random_mod.key_scope(key):
            from ... import amp as amp_mod
            with amp_mod.auto_cast(enable=amp_on,
                                   level="O2" if pure_bf16 else "O1",
                                   dtype="bfloat16"):
                out, new_state = functional_call(wrapped, p, st, *data)
        return out, new_state

    if strategy.recompute:
        policy = getattr(jax.checkpoint_policies,
                         strategy.recompute_configs.policy, None)
        forward_loss = jax.checkpoint(forward_loss, policy=policy)

    def local_grads(p, st, key, data):
        def loss_of(pp):
            out, new_st = forward_loss(pp, st, key, *data)
            return out, new_st
        (loss, new_st), g = jax.value_and_grad(loss_of, has_aux=True)(p)
        return loss, new_st, g

    # ---- per-rank body (inside shard_map over 'dp') ----------------------
    def body(p, st, opt_st, comm, key, lr, data):
        if local_params:
            p = jax.tree_util.tree_map(lambda x: x[0], p)       # unstack
            opt_core = jax.tree_util.tree_map(lambda x: x[0], opt_st)
        else:
            opt_core = opt_st
        # decorrelate dropout across ranks
        key = jax.random.fold_in(key, jax.lax.axis_index("dp"))
        loss, new_st, g = local_grads(p, st, key, data)
        g = nan_inf.guard_tree(g)

        if mode == "fp16_allreduce":
            g = jax.tree_util.tree_map(
                lambda x: jax.lax.pmean(x.astype(jnp.bfloat16), "dp")
                .astype(x.dtype), g)
            new_p, new_opt = optimizer.functional_update(p, g, opt_core,
                                                         lr=lr)
            new_comm = comm
        elif mode == "dgc":
            step_i = comm["step"]
            flat, tree = jax.tree_util.tree_flatten(g)
            new_u, new_v, out = [], [], []
            for i, gl in enumerate(flat):
                gf = gl.reshape(-1)
                u = comm["u"][i][0].reshape(-1)
                v = comm["v"][i][0].reshape(-1)

                def dense_path(gf=gf, u=u, v=v):
                    return jax.lax.pmean(gf, "dp"), u, v

                def dgc_path(gf=gf, u=u, v=v):
                    return _dgc_exchange(gf, u, v, dgc_momentum,
                                         keep_ratio, n_dp)

                gg, uu, vv = jax.lax.cond(step_i < rampup, dense_path,
                                          dgc_path)
                out.append(gg.reshape(gl.shape))
                new_u.append(uu.reshape(gl.shape)[None])
                new_v.append(vv.reshape(gl.shape)[None])
            g = jax.tree_util.tree_unflatten(tree, out)
            new_p, new_opt = optimizer.functional_update(p, g, opt_core,
                                                         lr=lr)
            new_comm = {"u": new_u, "v": new_v, "step": step_i + 1}
        else:                                   # localsgd / adaptive
            new_p, new_opt = optimizer.functional_update(p, g, opt_core,
                                                         lr=lr)
            step_i = comm["step"] + 1
            since = comm["since"] + 1
            k_now = comm["k"]
            # warm-up: before begin_step, sync every step (paddle
            # LocalSGDOptimizer semantics); after it, every k steps
            do_sync = jnp.logical_or(step_i < begin, since >= k_now)

            def sync(tree_p):
                return jax.tree_util.tree_map(
                    lambda x: jax.lax.pmean(x, "dp"), tree_p)

            new_p, new_opt = jax.lax.cond(
                do_sync, lambda pp: (sync(pp[0]), sync(pp[1])),
                lambda pp: pp, (new_p, new_opt))
            gloss = jax.lax.pmean(loss, "dp")
            if mode == "adaptive_localsgd":
                # paddle _adaptive_localsgd: grow the interval as the loss
                # falls: k = clip(init_k * sqrt(loss0/loss), 1, 16)
                loss0 = jnp.where(comm["loss0"] <= 0.0, gloss, comm["loss0"])
                k_new = jnp.clip(
                    jnp.round(k0 * jnp.sqrt(loss0 /
                                            jnp.maximum(gloss, 1e-8))),
                    1, 16).astype(jnp.int32)
                k_now = jnp.where(do_sync, k_new, k_now)
            else:
                loss0 = comm["loss0"]
            new_comm = {"step": step_i,
                        "since": jnp.where(do_sync, 0, since),
                        "k": k_now, "loss0": loss0}
        loss = jax.lax.pmean(loss, "dp")
        # layer buffers (BN running stats) update per-rank on different
        # data shards but leave the shard_map under a replicated
        # out_spec: pmean the float buffers so every rank agrees
        # (sync-BN-style running stats); integer counters advance
        # identically per rank and stay as-is
        new_st = jax.tree_util.tree_map(
            lambda b: (jax.lax.pmean(b, "dp")
                       if jnp.issubdtype(b.dtype, jnp.floating) else b),
            new_st)
        if local_params:
            new_p = jax.tree_util.tree_map(lambda x: x[None], new_p)
            new_opt = jax.tree_util.tree_map(lambda x: x[None], new_opt)
        return loss, new_p, new_st, new_opt, new_comm

    # ---- stack/shard layout ----------------------------------------------
    def _stack(tree):
        return jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x[None], (n_dp,) + x.shape), tree)

    if local_params:
        params_l = _stack(params)
        opt_l = _stack(opt_state)
        pspec = jax.tree_util.tree_map(
            lambda x: P(*(("dp",) + (None,) * (x.ndim - 1))), params_l)
        ospec = jax.tree_util.tree_map(
            lambda x: P(*(("dp",) + (None,) * (x.ndim - 1))), opt_l)
        comm = {"step": jnp.zeros((), jnp.int32),
                "since": jnp.zeros((), jnp.int32),
                "k": jnp.asarray(k0, jnp.int32),
                "loss0": jnp.zeros((), jnp.float32)}
        comm_spec = {"step": P(), "since": P(), "k": P(), "loss0": P()}
    else:
        params_l = params
        opt_l = opt_state
        pspec = jax.tree_util.tree_map(lambda x: P(*((None,) * x.ndim)),
                                       params)
        ospec = jax.tree_util.tree_map(lambda x: P(*((None,) * x.ndim)),
                                       opt_state)
        if mode == "dgc":
            flat, _ = jax.tree_util.tree_flatten(
                jax.tree_util.tree_map(jnp.zeros_like, params))
            zstk = [jnp.zeros((n_dp,) + f.shape, f.dtype) for f in flat]
            comm = {"u": zstk, "v": [z.copy() for z in zstk],
                    "step": jnp.zeros((), jnp.int32)}
            comm_spec = {
                "u": [P(*(("dp",) + (None,) * (z.ndim - 1))) for z in zstk],
                "v": [P(*(("dp",) + (None,) * (z.ndim - 1))) for z in zstk],
                "step": P()}
        else:
            comm = {}
            comm_spec = {}

    buf_spec = jax.tree_util.tree_map(lambda x: P(*((None,) * x.ndim)),
                                      state)
    dspec = P("dp")

    smapped = jax.shard_map(
        body, mesh=mesh,
        in_specs=(pspec, buf_spec, ospec, comm_spec, P(), P(), dspec),
        out_specs=(P(), pspec, buf_spec, ospec, comm_spec),
        check_vma=False)

    def train_step(p, st, opt_bundle, key, lr, data):
        loss, new_p, new_st, new_opt, new_comm = smapped(
            p, st, opt_bundle["opt"], opt_bundle["comm"], key, lr, data)
        return loss, new_p, new_st, {"opt": new_opt, "comm": new_comm}

    p_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pspec)
    s_sh = {"opt": jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), ospec),
            "comm": jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), comm_spec)}
    buf_sh = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), buf_spec)
    data_sh = NamedSharding(mesh, P("dp"))

    jitted = jax.jit(train_step,
                     in_shardings=(p_sh, buf_sh, s_sh, None, None, None),
                     out_shardings=(NamedSharding(mesh, P()), p_sh, buf_sh,
                                    s_sh),
                     donate_argnums=(0, 2))

    # true copy: donated program buffers (params, argnum 0) must never
    # alias the layer's own arrays (see fleet/compiler.py)
    params_l = jax.tree_util.tree_map(unaliased_put, params_l, p_sh)
    state = jax.device_put(state, buf_sh)
    opt_bundle = jax.device_put({"opt": opt_l, "comm": comm}, s_sh)

    cls = _LocalParamsTrainStep if local_params else _ExplicitDPTrainStep
    prog = cls(jitted, params_l, state, opt_bundle,
               {"params": p_sh, "opt": s_sh}, mesh, layer, data_sh)
    prog._opt = optimizer
    # the shard_map step rides the shared CompiledTrainStep.step AOT +
    # persistent-cache + retrace-guard path; label it for compile reports
    prog._step_label = f"fleet.{mode}_step"
    return prog


# CompiledTrainStep import is deferred to avoid a circular import at module
# load (compiler.py imports grad_comm lazily); build the classes at bottom.
def _make_classes():
    from .compiler import CompiledTrainStep

    class ExplicitDP(CompiledTrainStep):
        pass

    class LocalParams(CompiledTrainStep):
        """Params carry a leading per-rank replica axis; write_back
        averages the replicas (what the final localsgd sync would do)."""

        def write_back(self):
            lookup = dict(self.layer.named_parameters())
            lookup.update(dict(self.layer.named_buffers()))
            for k, v in self.params.items():
                if k in lookup:
                    lookup[k]._data = jax.device_get(v).mean(axis=0)
            for k, v in self.state.items():
                if k in lookup:
                    lookup[k]._data = jax.device_get(v)

    return ExplicitDP, LocalParams


_ExplicitDPTrainStep, _LocalParamsTrainStep = _make_classes()
