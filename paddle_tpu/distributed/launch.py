"""Process launcher (reference: fleet/launch.py:334 launch(), process
management launch_utils.py:425 TrainerProc / :435 start_local_trainers /
:526 watch_local_trainers).

On TPU pods the unit is one process per HOST (all local chips belong to
it), coordinated by jax.distributed — so the launcher starts one worker
per host entry and exports the same PADDLE_* env protocol the reference
uses, plus the jax coordinator address.

Usage: python -m paddle_tpu.distributed.launch --nproc_per_node=1
           --ips=host1,host2 train.py [args...]
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time

__all__ = ["launch", "start_local_trainers", "watch_local_trainers", "main"]


class TrainerProc:
    def __init__(self, proc, rank, log_file=None):
        self.proc = proc
        self.rank = rank
        self.log_file = log_file


def start_local_trainers(script, script_args, nproc, node_rank, nnodes,
                         master, log_dir=None, hosts=None):
    """Spawn nproc workers on this node with the PADDLE_* env protocol
    (launch_utils.py:435). Endpoints pair each host with its local ranks'
    ports (rank r lives on hosts[r // nproc])."""
    procs = []
    world = nproc * nnodes
    base_port = int(master.split(":")[1])
    hosts = hosts or [master.split(":")[0]] * nnodes
    endpoints = ",".join(
        f"{hosts[r // nproc]}:{base_port + (r % nproc)}"
        for r in range(world))
    for local_rank in range(nproc):
        rank = node_rank * nproc + local_rank
        env = dict(os.environ)
        env.update({
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(world),
            "PADDLE_TRAINER_ENDPOINTS": endpoints,
            "PADDLE_MASTER_ENDPOINT": master,
            "PADDLE_LOCAL_RANK": str(local_rank),
            "FLAGS_selected_tpus": str(local_rank),
        })
        log = None
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log = open(os.path.join(log_dir, f"workerlog.{rank}"), "w")
        p = subprocess.Popen([sys.executable, script] + list(script_args),
                             env=env, stdout=log or None, stderr=log or None)
        procs.append(TrainerProc(p, rank, log))
    return procs


def watch_local_trainers(procs, poll_s=1.0):
    """Abort all if any worker dies (launch_utils.py:526)."""
    try:
        while True:
            alive = False
            for tp in procs:
                ret = tp.proc.poll()
                if ret is None:
                    alive = True
                elif ret != 0:
                    for other in procs:
                        if other.proc.poll() is None:
                            other.proc.send_signal(signal.SIGTERM)
                    raise RuntimeError(
                        f"worker rank {tp.rank} exited with code {ret}")
            if not alive:
                return 0
            time.sleep(poll_s)
    finally:
        for tp in procs:
            if tp.log_file:
                tp.log_file.close()


def launch(args=None):
    parser = argparse.ArgumentParser("paddle_tpu.distributed.launch")
    parser.add_argument("--nproc_per_node", type=int, default=1)
    parser.add_argument("--ips", type=str, default="127.0.0.1",
                        help="comma-separated host list")
    parser.add_argument("--node_rank", type=int,
                        default=int(os.environ.get("PADDLE_NODE_RANK", 0)))
    parser.add_argument("--master_port", type=int, default=6170)
    parser.add_argument("--log_dir", type=str, default=None)
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    ns = parser.parse_args(args)

    hosts = ns.ips.split(",")
    master = f"{hosts[0]}:{ns.master_port}"
    procs = start_local_trainers(ns.script, ns.script_args,
                                 ns.nproc_per_node, ns.node_rank,
                                 len(hosts), master, ns.log_dir, hosts=hosts)
    return watch_local_trainers(procs)


def main():
    sys.exit(launch())


if __name__ == "__main__":
    main()
