"""Pipeline parallelism — 1F1B/GPipe over a 'pp' mesh axis.

Reference: PipelineTrainer/SectionWorker (pipeline_trainer.cc:27,
section_worker.cc:98-165 — F-then-B and 1F1B loops over microbatch scopes,
cross-stage send_v2/recv_v2 over NCCL p2p; program split
optimizer.py:3718, SURVEY.md §8.2).

TPU-native redesign: the reference runs a *host thread per stage* issuing
ops; on TPU the whole pipeline is ONE jitted SPMD program over the 'pp'
axis. Stage-local layer stacks are a leading-axis-stacked pytree sharded
over 'pp'; activations move between neighbour stages with
lax.ppermute (ICI neighbour hops); the microbatch loop is a lax.scan with
a circular buffer, which XLA overlaps with compute (the 1F1B memory
profile falls out of steady-state: each stage holds at most
n_stages in-flight microbatch activations).

Design restriction (same as every SPMD pipeline): the pipelined body must
be homogeneous — L identical blocks split as L/pp per stage. Embedding and
head run replicated outside the pipelined region (negligible FLOPs vs the
block stack; params shared across ranks)."""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["pipeline_spmd", "stack_stage_params", "PipelineLayer"]


def stack_stage_params(block_params_list):
    """[{name: arr} per layer] -> {name: arr[L, ...]} stacked pytree.
    Shard the leading dim over 'pp' to place L/pp layers per stage."""
    out = {}
    for name in block_params_list[0]:
        out[name] = jnp.stack([bp[name] for bp in block_params_list])
    return out


def pipeline_spmd(block_fn: Callable, n_stages: int, n_micro: int,
                  mesh, axis: str = "pp", batch_axis: str = None,
                  param_specs=None, seq_axis: str = None):
    """Build pipelined_fn(stacked_params, x_micro) -> y_micro.

    block_fn(params_one_layer, x) -> x          (one transformer block)
    stacked_params: {name: [L, ...]} sharded P(axis) on dim 0 — each stage
      holds its local [L/pp, ...] slab.
    x_micro: [n_micro, micro_batch, ...] activations, replicated input;
      output is the fully-processed microbatch stack (valid on last stage,
      broadcast to all).

    Schedule: circular-shift loop of n_micro + n_stages - 1 ticks
    (fill + steady state + drain). Each tick: run local stage stack on the
    held activation, ppermute result to the next stage. This is the
    F-then-B schedule for the forward; because the whole loop lives inside
    one jit, jax.grad over it yields the reversed (B) schedule
    automatically — no hand-written 1F1B interleave is needed for
    correctness, and XLA's scheduler overlaps the ppermute with block
    compute (the throughput property 1F1B exists for)."""

    def run_local_stack(local_params, x):
        # scan over this stage's L/pp layers
        def body(h, layer_params):
            return block_fn(layer_params, h), None
        h, _ = jax.lax.scan(body, x, local_params)
        return h

    def staged(local_params, x_micro):
        stage = jax.lax.axis_index(axis)
        n_ticks = n_micro + n_stages - 1
        micro_shape = x_micro.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            held, outputs = carry
            # stage 0 injects microbatch t (if any left); others use held
            inject = jnp.where(t < n_micro, t, n_micro - 1)
            x_in = jnp.where(stage == 0, x_micro[inject], held)
            y = run_local_stack(local_params, x_in)
            # pass to next stage; last stage's output is recorded
            out_idx = t - (n_stages - 1)
            rec = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            outputs = jax.lax.cond(
                rec,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_idx, 0), 0),
                lambda o: o, outputs)
            held_next = jax.lax.ppermute(y, axis, perm)
            return (held_next, outputs), None

        outputs0 = jnp.zeros((n_micro,) + micro_shape, x_micro.dtype)
        held0 = jnp.zeros(micro_shape, x_micro.dtype)
        (_, outputs), _ = jax.lax.scan(
            tick, (held0, outputs0), jnp.arange(n_ticks))
        # broadcast last stage's outputs to every stage (psum of masked)
        mask = (stage == n_stages - 1).astype(x_micro.dtype)
        outputs = jax.lax.psum(outputs * mask, axis)
        return outputs

    def pipelined(stacked_params, x_micro, in_mesh=mesh):
        # x_micro [n_micro, micro_batch, ...]: the micro_batch dim may ride
        # a data-parallel axis so dp x pp composes in one shard_map
        nd_x = x_micro.ndim
        dspec = [None] * nd_x
        if batch_axis is not None:
            dspec[1] = batch_axis
        if seq_axis is not None:
            # sequence parallel: activations enter the pipeline as local
            # [.., T/sp, ..] shards; block_fn owns the sp collectives
            # (ring/Ulysses attention)
            dspec[2] = seq_axis
        dspec = P(*dspec)
        # default: params sharded over 'pp' only; a caller doing manual
        # tensor parallelism inside block_fn (models/gpt.py
        # pipeline_block_fn_tp) passes specs that also shard over 'tp' —
        # every mesh axis stays manual, tp collectives are block_fn's job
        pspecs = param_specs if param_specs is not None else \
            jax.tree_util.tree_map(
                lambda v: P(axis, *([None] * (v.ndim - 1))),
                stacked_params)
        f = jax.shard_map(
            staged, mesh=in_mesh,
            in_specs=(pspecs, dspec),
            out_specs=dspec,
            check_vma=False)
        return f(stacked_params, x_micro)

    return pipelined


class PipelineLayer:
    """User-facing wrapper (reference PipelineOptimizer surface): holds a
    GPT-like model whose homogeneous blocks get pipelined.

    pipeline_forward(params, ids) computes embed (replicated) -> pipelined
    blocks -> head, with microbatching over dim 0."""

    def __init__(self, embed_fn, block_fn, head_fn, n_stages, n_micro,
                 mesh, axis="pp"):
        self.embed_fn = embed_fn
        self.block_fn = block_fn
        self.head_fn = head_fn
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.mesh = mesh
        self.axis = axis
        self._pipe = pipeline_spmd(block_fn, n_stages, n_micro, mesh, axis)

    def __call__(self, embed_params, stacked_block_params, head_params, ids):
        n_micro = self.n_micro
        B = ids.shape[0]
        micro = ids.reshape((n_micro, B // n_micro) + ids.shape[1:])
        h = jax.vmap(lambda m: self.embed_fn(embed_params, m))(micro)
        h = self._pipe(stacked_block_params, h)
        out = jax.vmap(lambda m: self.head_fn(head_params, m))(h)
        return out.reshape((B,) + out.shape[2:])
