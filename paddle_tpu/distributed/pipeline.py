"""Pipeline parallelism — 1F1B/GPipe over a 'pp' mesh axis.

Reference: PipelineTrainer/SectionWorker (pipeline_trainer.cc:27,
section_worker.cc:98-165 — F-then-B and 1F1B loops over microbatch scopes,
cross-stage send_v2/recv_v2 over NCCL p2p; program split
optimizer.py:3718, SURVEY.md §8.2).

TPU-native redesign: the reference runs a *host thread per stage* issuing
ops; on TPU the whole pipeline is ONE jitted SPMD program over the 'pp'
axis. Stage-local layer stacks are a leading-axis-stacked pytree sharded
over 'pp'; activations move between neighbour stages with
lax.ppermute (ICI neighbour hops); the microbatch loop is a lax.scan.

Two schedulers live here:
- `pipeline_spmd`: forward-only circular-shift loop (fill + steady +
  drain). Differentiating *through* it (jax.grad) yields a GPipe-style
  F-then-B whose saved residuals scale with n_micro — fine for eval /
  small accumulate_steps, NOT the 1F1B memory profile.
- `pipeline_value_and_grad`: the train scheduler. A fused fwd+bwd 1F1B
  lockstep (section_worker.cc:128-165's interleave, re-derived for SPMD):
  at tick t stage s runs forward of microbatch (t - s) AND backward of
  microbatch (t - (2S-1-s)); boundary activations wait in a 2S-slot ring
  buffer, the backward re-linearises the stage stack per microbatch
  (full remat), so per-stage live activation memory is O(n_stages) and
  independent of n_micro.

Design restriction (same as every SPMD pipeline): the pipelined body must
be homogeneous — L identical blocks split as L/pp per stage. Embedding and
head run replicated outside the pipelined region (negligible FLOPs vs the
block stack; params shared across ranks)."""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["pipeline_spmd", "pipeline_value_and_grad",
           "stack_stage_params", "PipelineLayer"]


def fold_data_axes(key, batch_axis=None, seq_axis=None):
    """THE dropout key-fold prefix shared by every pipeline scheduler:
    decorrelate across data shards (dp batch shards, sp sequence shards),
    keep replicated axes (tp/ep) identical. Call only inside shard_map.
    Fold order is part of the mask contract — 1F1B, F-then-B and the
    compiler's embed shard_map must all agree bitwise."""
    for a_ in (batch_axis, seq_axis):
        if a_ is not None:
            key = jax.random.fold_in(key, jax.lax.axis_index(a_))
    return key


def embed_key_tag(k_m, n_layers_total):
    """The embed call's dropout key: the per-microbatch key folded with a
    tag one past the last global layer index (so embed masks never
    collide with block masks)."""
    return jax.random.fold_in(k_m, n_layers_total)


def stack_stage_params(block_params_list):
    """[{name: arr} per layer] -> {name: arr[L, ...]} stacked pytree.
    Shard the leading dim over 'pp' to place L/pp layers per stage."""
    out = {}
    for name in block_params_list[0]:
        out[name] = jnp.stack([bp[name] for bp in block_params_list])
    return out


def pipeline_spmd(block_fn: Callable, n_stages: int, n_micro: int,
                  mesh, axis: str = "pp", batch_axis: str = None,
                  param_specs=None, seq_axis: str = None,
                  aux_from_blocks: bool = False):
    """Build pipelined_fn(stacked_params, x_micro) -> y_micro
    (or (y_micro, aux_sum) with aux_from_blocks: blocks return (h, aux)
    and the masked per-microbatch auxes sum over stages — the MoE
    load-balance term for the eval path).

    block_fn(params_one_layer, x) -> x          (one transformer block)
    stacked_params: {name: [L, ...]} sharded P(axis) on dim 0 — each stage
      holds its local [L/pp, ...] slab.
    x_micro: [n_micro, micro_batch, ...] activations, replicated input;
      output is the fully-processed microbatch stack (valid on last stage,
      broadcast to all).

    Schedule: circular-shift loop of n_micro + n_stages - 1 ticks
    (fill + steady state + drain). Each tick: run local stage stack on the
    held activation, ppermute result to the next stage. jax.grad over it
    is correct but GPipe-shaped: the reversed scan stores residuals for
    ALL n_micro microbatches per stage — exactly the stored-residual
    ("F-then-B") schedule the reference's SectionWorker runs when
    recompute is off (section_worker.cc:128-165): ~1.3x fewer FLOPs than
    the remat 1F1B, O(n_micro) activation memory. Training selects it
    via strategy.pipeline_configs.schedule_mode = "F-then-B";
    `pipeline_value_and_grad` (true 1F1B, O(n_stages) memory) is the
    default. `key` threads dropout with the SAME (data-rank, microbatch,
    global-layer) folding as the 1F1B scheduler, so the two schedules
    draw identical masks."""
    import inspect as _inspect

    try:
        block_takes_key = "key" in _inspect.signature(block_fn).parameters
    except (TypeError, ValueError):
        block_takes_key = False

    def run_local_stack(local_params, x, k_m, stage):
        # scan over this stage's L/pp layers; global layer index folds
        # into the dropout key exactly like pipeline_value_and_grad
        n_local = jax.tree_util.tree_leaves(local_params)[0].shape[0]
        gidx = jnp.arange(n_local) + stage * n_local

        def body(carry, xs):
            h, aux = carry
            lp, li = xs
            if block_takes_key and k_m is not None:
                out = block_fn(lp, h, jax.random.fold_in(k_m, li))
            else:
                out = block_fn(lp, h)
            if aux_from_blocks:
                h2, a = out
                return (h2, aux + jnp.asarray(a, jnp.float32)), None
            return (out, aux), None
        (h, aux), _ = jax.lax.scan(
            body, (x, jnp.zeros((), jnp.float32)), (local_params, gidx))
        return h, aux

    def staged(local_params, x_micro, key=None):
        stage = jax.lax.axis_index(axis)
        n_ticks = n_micro + n_stages - 1
        micro_shape = x_micro.shape[1:]
        perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        if key is not None and block_takes_key:
            key = fold_data_axes(key, batch_axis, seq_axis)

        def tick(carry, t):
            held, outputs, aux_s = carry
            # stage 0 injects microbatch t (if any left); others use held
            inject = jnp.where(t < n_micro, t, n_micro - 1)
            x_in = jnp.where(stage == 0, x_micro[inject], held)
            m_now = jnp.clip(t - stage, 0, n_micro - 1)
            k_m = (jax.random.fold_in(key, m_now)
                   if key is not None and block_takes_key else None)
            y, aux = run_local_stack(local_params, x_in, k_m, stage)
            # stage s holds real microbatch t-s only inside the window —
            # fill/drain ticks run on garbage and must not count
            m = t - stage
            valid = jnp.logical_and(m >= 0, m < n_micro)
            aux_s = aux_s + valid.astype(jnp.float32) * aux
            # pass to next stage; last stage's output is recorded
            out_idx = t - (n_stages - 1)
            rec = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
            outputs = jax.lax.cond(
                rec,
                lambda o: jax.lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_idx, 0), 0),
                lambda o: o, outputs)
            held_next = jax.lax.ppermute(y, axis, perm)
            return (held_next, outputs, aux_s), None

        outputs0 = jnp.zeros((n_micro,) + micro_shape, x_micro.dtype)
        held0 = jnp.zeros(micro_shape, x_micro.dtype)
        (_, outputs, aux_s), _ = jax.lax.scan(
            tick, (held0, outputs0, jnp.zeros((), jnp.float32)),
            jnp.arange(n_ticks))
        # broadcast last stage's outputs to every stage (psum of masked)
        mask = (stage == n_stages - 1).astype(x_micro.dtype)
        outputs = jax.lax.psum(outputs * mask, axis)
        if aux_from_blocks:
            aux_s = jax.lax.psum(aux_s, axis)       # sum over stages
            for a_ in (batch_axis, seq_axis):
                if a_ is not None:                  # mean over data shards
                    aux_s = jax.lax.psum(aux_s, a_) / int(mesh.shape[a_])
            return outputs, aux_s
        return outputs

    def pipelined(stacked_params, x_micro, key=None, in_mesh=mesh):
        # x_micro [n_micro, micro_batch, ...]: the micro_batch dim may ride
        # a data-parallel axis so dp x pp composes in one shard_map
        nd_x = x_micro.ndim
        dspec = [None] * nd_x
        if batch_axis is not None:
            dspec[1] = batch_axis
        if seq_axis is not None:
            # sequence parallel: activations enter the pipeline as local
            # [.., T/sp, ..] shards; block_fn owns the sp collectives
            # (ring/Ulysses attention)
            dspec[2] = seq_axis
        dspec = P(*dspec)
        # default: params sharded over 'pp' only; a caller doing manual
        # tensor parallelism inside block_fn (models/gpt.py
        # pipeline_block_fn_tp) passes specs that also shard over 'tp' —
        # every mesh axis stays manual, tp collectives are block_fn's job
        pspecs = param_specs if param_specs is not None else \
            jax.tree_util.tree_map(
                lambda v: P(axis, *([None] * (v.ndim - 1))),
                stacked_params)
        if key is not None and block_takes_key:
            f = jax.shard_map(
                staged, mesh=in_mesh,
                in_specs=(pspecs, dspec, P()),
                out_specs=(dspec, P()) if aux_from_blocks else dspec,
                check_vma=False)
            return f(stacked_params, x_micro, key)
        f = jax.shard_map(
            staged, mesh=in_mesh,
            in_specs=(pspecs, dspec),
            out_specs=(dspec, P()) if aux_from_blocks else dspec,
            check_vma=False)
        return f(stacked_params, x_micro)

    return pipelined


def pipeline_value_and_grad(block_fn, embed_fn, head_loss_fn, n_stages,
                            n_micro, mesh, axis: str = "pp",
                            batch_axis: str = None, param_specs=None,
                            seq_axis: str = None,
                            block_takes_key: bool = False,
                            embed_takes_key: bool = False,
                            replicated_axes: tuple = (),
                            aux_from_blocks: bool = False,
                            aux_coef: float = 0.0):
    """True-1F1B fused train pipeline: loss AND grads in one SPMD scan.

    Reference: SectionWorker's 1F1B loop
    (/root/reference/paddle/fluid/framework/section_worker.cc:128-165 —
    warmup forwards, steady-state 1F+1B interleave, cooldown backwards,
    bounding each stage to <= n_stages in-flight microbatches). SPMD
    re-derivation: with unit F/B slots per tick, stage s runs
    F_{t-s} and B_{t-(2S-1-s)} at tick t; forward activations hop s->s+1
    and input-cotangents hop s->s-1 via ppermute each tick. In-flight
    microbatches at stage s peak at 2(S-s)-1 <= 2S-1, so a 2S-slot ring
    buffer of boundary activations suffices — per-stage live activation
    memory is O(n_stages), independent of n_micro (asserted by
    tests/test_distributed.py::test_pipeline_memory_scales_with_stages).
    The backward slot re-linearises the stage stack from the saved
    boundary input (full remat, the reference's recompute-mode trade);
    embed runs in stage 0's slots and head+loss in the last stage's, so
    no O(n_micro) activation or cotangent buffers exist anywhere.

    Returns f(stacked, embed_p, head_p, ids_micro, labels_micro, key) ->
    (loss_sum, valid_count, d_stacked, d_embed, d_head); grads are of
    loss_SUM — divide by the count for mean-loss grads.

    block_fn(bp, h[, key]) -> h;  embed_fn(ep, ids[, pos_offset][, key]);
    head_loss_fn(hp, ep, h, labels) -> (loss_sum, valid_count).
    Collectives inside block_fn (tp/sp/ep psums, ring ppermutes) are fine:
    they run unconditionally every tick. embed/head must be collective-free
    (they execute under a per-stage lax.cond).

    `aux_from_blocks`: blocks return (h, aux_scalar) — e.g. the MoE
    Switch load-balance loss — and the returned tuple gains a 6th
    element aux_sum (Σ over microbatches and blocks, averaged over data
    shards). The aux GRADIENT rides the backward slot's vjp as a second
    cotangent seed scaled by aux_coef * valid_count / (L * n_micro * n_data)
    (head_loss_fn must expose `.valid_count(labels)`), so after the
    caller divides the grad accumulators by the global valid count the
    aux term lands at exactly aux_coef * mean-over-blocks-and-microbatches
    — the same weighting GPT.loss gives it on the sequential path. Note
    the per-(shard, microbatch) aux is averaged where the non-pipeline
    path computes one global-batch aux; the load-balance pressure is
    statistically equivalent, not bitwise.

    `replicated_axes` names mesh axes over which activations are
    REPLICATED while block_fn contains psums (tp on the manual-Megatron
    path, ep on the expert path). Manual vjp inside shard_map transposes
    psum to psum, so replicated cotangent seeds would double-count by the
    axis size: instead the last stage seeds the stack vjp with dy/n and
    cotangents stay *partial* across those axes (their psum is the true
    cotangent — the invariant is self-maintaining through psum-transposes
    stage to stage). Consequently grads of params SHARDED over such an
    axis come out true directly, while grads of params replicated over it
    (and the embed grads) are partial and get one psum at the end."""
    S, M = n_stages, n_micro
    K = 2 * S
    n_ticks = 2 * S + M - 1
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]
    n_rep = 1
    for a in replicated_axes:
        n_rep *= int(mesh.shape[a])

    def staged(sp_, ep_, hp_, ids_m, lab_m, key):
        stage = jax.lax.axis_index(axis)
        is_last = stage == S - 1
        is_first = stage == 0
        f32 = jnp.float32

        # dropout keys: decorrelate across data axes (dp shards, sp seq
        # shards) but keep tp/ep members identical — replicated
        # activations need identical masks or the manual psums break
        if key is not None and (block_takes_key or embed_takes_key):
            key = fold_data_axes(key, batch_axis, seq_axis)
        T_loc = ids_m.shape[2] if ids_m.ndim >= 3 else ids_m.shape[-1]
        pos_off = (jax.lax.axis_index(seq_axis) * T_loc
                   if seq_axis is not None else 0)

        n_local = jax.tree_util.tree_leaves(sp_)[0].shape[0]

        def _embed_with(e_, m_idx, k_m):
            args = (e_, ids_m[m_idx])
            kw = {}
            if seq_axis is not None:
                kw["pos_offset"] = pos_off
            if embed_takes_key and k_m is not None:
                kw["key"] = embed_key_tag(k_m, n_local * S)
            return embed_fn(*args, **kw)

        def run_stack(p_, x, k_m):
            # global layer index rides the xs so recompute (backward
            # slot) reproduces the forward's dropout masks exactly
            gidx = jnp.arange(n_local) + stage * n_local

            def call(lp, h, li):
                if block_takes_key and k_m is not None:
                    return block_fn(lp, h, jax.random.fold_in(k_m, li))
                return block_fn(lp, h)

            def body(carry, xs):
                h, aux = carry
                lp, li = xs
                out = call(lp, h, li)
                if aux_from_blocks:
                    h2, a = out
                    return (h2, aux + jnp.asarray(a, jnp.float32)), None
                return (out, aux), None

            (h, aux), _ = jax.lax.scan(
                body, (x, jnp.zeros((), jnp.float32)), (p_, gidx))
            return h, aux

        # aux cotangent seed: constant per tick. Scaled so that after the
        # caller divides the accumulators by the global valid count, the
        # aux term weighs aux_coef / (total_blocks * n_micro); divided by
        # the data-shard count (the final psums SUM where aux wants a
        # mean) and by n_rep (partial-cotangent protocol).
        if aux_from_blocks:
            vc = getattr(head_loss_fn, "valid_count", None)
            if vc is None:
                raise TypeError(
                    "aux_from_blocks needs head_loss_fn.valid_count"
                    "(labels) so the aux gradient can pre-scale by the "
                    "global valid-token count")
            cnt0 = jnp.asarray(vc(lab_m), jnp.float32)
            n_data = 1
            for a_ in (batch_axis, seq_axis):
                if a_ is not None:
                    cnt0 = jax.lax.psum(cnt0, a_)
                    n_data *= int(mesh.shape[a_])
            denom0 = jnp.maximum(cnt0, 1.0)
            aux_seed = jnp.asarray(
                aux_coef * denom0 / (n_local * S * M * n_data * n_rep),
                jnp.float32)
        else:
            aux_seed = jnp.zeros((), jnp.float32)

        def tick(carry, t):
            (act_in, g_in, buf, d_sp, d_ep, d_hp, loss_s, cnt_s,
             aux_s) = carry

            # ---- forward slot: F_{t - stage} -------------------------
            m_f = t - stage
            mf_c = jnp.clip(m_f, 0, M - 1)
            k_f = (jax.random.fold_in(key, mf_c)
                   if key is not None and (block_takes_key or
                                           embed_takes_key) else None)
            x_f = jax.lax.cond(
                is_first, lambda: _embed_with(ep_, mf_c, k_f),
                lambda: act_in)
            y_f, _ = run_stack(sp_, x_f, k_f)
            # ring-buffer the boundary input for the backward's remat.
            # Slot m_f mod 2S is written even on invalid (fill/drain)
            # ticks: for m_f < 0 the slot lands in the never-pending
            # range (S, 2S); for m_f >= M it aliases microbatch
            # m_f - 2S = m_b - 1, already consumed last tick.
            buf = jax.lax.dynamic_update_index_in_dim(
                buf, x_f, m_f % K, 0)

            # ---- backward slot: B_{t - (2S-1-stage)} -----------------
            m_b = t - (2 * S - 1 - stage)
            v_b = jnp.logical_and(m_b >= 0, m_b < M).astype(f32)
            mb_c = jnp.clip(m_b, 0, M - 1)
            k_b = (jax.random.fold_in(key, mb_c)
                   if key is not None and (block_takes_key or
                                           embed_takes_key) else None)
            x_b = jax.lax.dynamic_index_in_dim(buf, m_b % K, 0,
                                               keepdims=False)
            lab = lab_m[mb_c]
            (y_b, aux_b), stk_vjp = jax.vjp(
                lambda p_, x_: run_stack(p_, x_, k_b), sp_, x_b)

            def last_branch(y_):
                def hl(hp__, ep__, y__):
                    s_, c_ = head_loss_fn(hp__, ep__, y__, lab)
                    return s_, c_

                (ls, c), (dhp, dep, dy) = jax.value_and_grad(
                    hl, argnums=(0, 1, 2), has_aux=True)(hp_, ep_, y_)
                # partial-cotangent protocol over replicated axes (see
                # docstring): seed with dy/n so psum-transposes inside
                # the stack reassemble the true cotangent. The head-side
                # tied-embedding grad joins the (partial) embed-side grad
                # in one accumulator, so it is made partial too.
                if n_rep > 1:
                    dy = dy / n_rep
                    dep = jax.tree_util.tree_map(
                        lambda g: g / n_rep, dep)
                return (jnp.asarray(ls, f32), jnp.asarray(c, f32),
                        dhp, dep, dy)

            def mid_branch(y_):
                return (jnp.zeros((), f32), jnp.zeros((), f32),
                        jax.tree_util.tree_map(jnp.zeros_like, hp_),
                        jax.tree_util.tree_map(jnp.zeros_like, ep_),
                        g_in)

            ls, c, dhp_m, dep_m, dy = jax.lax.cond(
                is_last, last_branch, mid_branch, y_b)
            d_sp_m, dx_m = stk_vjp((dy, aux_seed))

            # stage 0's input is the embedding: fold its vjp into d_ep
            dep_e = jax.lax.cond(
                is_first,
                lambda dx_: jax.vjp(
                    lambda e_: _embed_with(e_, mb_c, k_b), ep_)[1](dx_)[0],
                lambda dx_: jax.tree_util.tree_map(jnp.zeros_like, ep_),
                dx_m)

            acc = lambda a, g: a + v_b * g
            d_sp = jax.tree_util.tree_map(acc, d_sp, d_sp_m)
            d_hp = jax.tree_util.tree_map(acc, d_hp, dhp_m)
            d_ep = jax.tree_util.tree_map(
                lambda a, g1, g2: a + v_b * (g1 + g2),
                d_ep, dep_m, dep_e)
            loss_s = loss_s + v_b * ls
            cnt_s = cnt_s + v_b * c
            aux_s = aux_s + v_b * aux_b

            act_next = jax.lax.ppermute(y_f, axis, fwd_perm)
            g_next = jax.lax.ppermute(dx_m, axis, bwd_perm)
            return (act_next, g_next, buf, d_sp, d_ep, d_hp,
                    loss_s, cnt_s, aux_s), None

        # one dead embed call pins the activation shape/dtype (only its
        # static metadata is used — XLA DCEs the compute)
        x0 = _embed_with(ep_, 0, None)
        act0 = jnp.zeros(x0.shape, x0.dtype)
        zeros_like_tree = functools.partial(
            jax.tree_util.tree_map, jnp.zeros_like)
        init = (act0, act0, jnp.zeros((K,) + x0.shape, x0.dtype),
                zeros_like_tree(sp_), zeros_like_tree(ep_),
                zeros_like_tree(hp_), jnp.zeros((), jnp.float32),
                jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        (_, _, _, d_sp, d_ep, d_hp, loss_s, cnt_s, aux_s), _ = \
            jax.lax.scan(tick, init, jnp.arange(n_ticks))

        # reductions: loss/head/embed grads live on one stage (mask) and
        # are partial across data shards; stacked grads are stage-owned
        # (no pp psum) but partial across data shards. tp/ep members
        # compute replicated copies — never psum over those axes.
        data_axes = tuple(a for a in (batch_axis, seq_axis)
                          if a is not None)
        for a in data_axes + (axis,):
            loss_s = jax.lax.psum(loss_s, a)
            cnt_s = jax.lax.psum(cnt_s, a)
            aux_s = jax.lax.psum(aux_s, a)
            d_ep = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, a), d_ep)
            d_hp = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, a), d_hp)
        for a in data_axes:
            aux_s = aux_s / int(mesh.shape[a])  # mean over data shards
            d_sp = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, a), d_sp)
        # partial-cotangent cleanup: embed grads (stage-0 vjp of partial
        # dx) and grads of block params REPLICATED over a replicated axis
        # are partial there; params sharded over the axis came out true
        for a in replicated_axes:
            d_ep = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, a), d_ep)
            if param_specs is not None:
                d_sp = {k: (g if a in tuple(param_specs[k])
                            else jax.lax.psum(g, a))
                        for k, g in d_sp.items()}
            else:
                d_sp = jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, a), d_sp)
        if aux_from_blocks:
            return loss_s, cnt_s, d_sp, d_ep, d_hp, aux_s
        return loss_s, cnt_s, d_sp, d_ep, d_hp

    def fn(stacked, embed_p, head_p, ids_micro, labels_micro, key=None,
           in_mesh=mesh):
        nd = ids_micro.ndim
        dspec = [None] * nd
        if batch_axis is not None:
            dspec[1] = batch_axis
        if seq_axis is not None:
            dspec[2] = seq_axis
        dspec = P(*dspec)
        pspecs = param_specs if param_specs is not None else \
            jax.tree_util.tree_map(
                lambda v: P(axis, *([None] * (v.ndim - 1))), stacked)
        rep = lambda tree: jax.tree_util.tree_map(
            lambda v: P(*([None] * getattr(v, "ndim", 0))), tree)
        out_specs = (P(), P(), pspecs, rep(embed_p), rep(head_p))
        if aux_from_blocks:
            out_specs = out_specs + (P(),)
        use_key = key is not None and (block_takes_key or embed_takes_key)
        if use_key:
            f = jax.shard_map(
                staged, mesh=in_mesh,
                in_specs=(pspecs, rep(embed_p), rep(head_p), dspec, dspec,
                          P()),
                out_specs=out_specs, check_vma=False)
            return f(stacked, embed_p, head_p, ids_micro, labels_micro,
                     key)
        f = jax.shard_map(
            lambda a, b, c, d, e: staged(a, b, c, d, e, None),
            mesh=in_mesh,
            in_specs=(pspecs, rep(embed_p), rep(head_p), dspec, dspec),
            out_specs=out_specs, check_vma=False)
        return f(stacked, embed_p, head_p, ids_micro, labels_micro)

    return fn


class PipelineLayer:
    """User-facing wrapper (reference PipelineOptimizer surface): holds a
    GPT-like model whose homogeneous blocks get pipelined.

    pipeline_forward(params, ids) computes embed (replicated) -> pipelined
    blocks -> head, with microbatching over dim 0."""

    def __init__(self, embed_fn, block_fn, head_fn, n_stages, n_micro,
                 mesh, axis="pp"):
        self.embed_fn = embed_fn
        self.block_fn = block_fn
        self.head_fn = head_fn
        self.n_stages = n_stages
        self.n_micro = n_micro
        self.mesh = mesh
        self.axis = axis
        self._pipe = pipeline_spmd(block_fn, n_stages, n_micro, mesh, axis)

    def __call__(self, embed_params, stacked_block_params, head_params, ids):
        n_micro = self.n_micro
        B = ids.shape[0]
        micro = ids.reshape((n_micro, B // n_micro) + ids.shape[1:])
        h = jax.vmap(lambda m: self.embed_fn(embed_params, m))(micro)
        h = self._pipe(stacked_block_params, h)
        out = jax.vmap(lambda m: self.head_fn(head_params, m))(h)
        return out.reshape((B,) + out.shape[2:])
