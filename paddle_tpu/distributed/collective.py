"""Functional collectives — paddle.distributed.{all_reduce, all_gather, ...}
parity (reference: python/paddle/distributed/collective.py:101-457 and the
c_* op set operators/collective/, SURVEY.md §2 row 27).

TPU-native redesign: the reference issues NCCL calls on comm streams via
per-op kernels (c_allreduce_op.h:109). Here a collective is a *traceable
function*: inside a `shard_map`ped / pjit'ed region it lowers to the XLA
ICI collective (psum/all_gather/ppermute — compiler-scheduled, no streams,
no comm-init); called eagerly on a sharded array it jits a tiny psum over
the current mesh. `ReduceOp` and `group` keep the paddle API shape; a group
names a mesh axis instead of an NCCL ring id.
"""
from __future__ import annotations

from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from . import mesh as mesh_mod

__all__ = ["ReduceOp", "new_group", "get_group", "all_reduce", "all_gather",
           "reduce_scatter", "broadcast", "reduce", "scatter", "alltoall",
           "send", "recv", "barrier", "split_group_axis"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A communication group == a named mesh axis (the reference's ring_id →
    axis name)."""

    def __init__(self, axis: str, mesh=None):
        self.axis = axis
        self.mesh = mesh

    @property
    def nranks(self):
        m = self.mesh or mesh_mod.get_mesh()
        return int(m.shape[self.axis]) if m is not None else 1

    def __repr__(self):
        return f"Group(axis={self.axis!r}, nranks={self.nranks})"


_groups = {}


def new_group(ranks=None, axis: str = None, mesh=None, backend=None):
    """Create/fetch the group for a mesh axis (paddle's new_group takes rank
    lists; on TPU the mesh topology already fixes membership, so the axis
    name is the identity)."""
    axis = axis or "dp"
    if axis not in _groups:
        _groups[axis] = Group(axis, mesh)
    return _groups[axis]


def get_group(axis="dp"):
    return new_group(axis=axis)


def _axis_of(group) -> str:
    if group is None:
        return "dp"
    if isinstance(group, Group):
        return group.axis
    return str(group)


def _in_trace(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def _sharded_dim(arr, axis):
    """Index of the array dimension sharded over `axis`, or None."""
    sh = getattr(arr, "sharding", None)
    spec = getattr(sh, "spec", None)
    if spec is None:
        return None
    for d, entry in enumerate(spec):
        if entry == axis or (isinstance(entry, tuple) and axis in entry):
            return d
    return None


def _sharded_over(arr, axis) -> bool:
    return _sharded_dim(arr, axis) is not None


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else t


def _rewrap(out, like):
    if isinstance(like, Tensor):
        like._data = out
        return like
    return out


def _eager_collective(fn_name, arr, axis, **kw):
    """Run a collective on a (possibly sharded) concrete array by jitting a
    shard_map over the current mesh — eager-API parity for dygraph code."""
    m = mesh_mod.get_mesh()
    if m is None or axis not in m.axis_names or m.shape[axis] == 1:
        # single rank: collectives are identities (paddle does the same for
        # world_size == 1)
        return arr

    if not _sharded_over(arr, axis):
        # replicated operand: every "rank" already holds the same value, so
        # apply replicated SPMD semantics locally (eager DDP grads land
        # here — AVG of identical replicas is the identity)
        n = int(m.shape[axis])
        op = kw.get("op", ReduceOp.SUM)
        if fn_name in ("all_reduce", "reduce"):
            if op == ReduceOp.SUM:
                return arr * n
            if op == ReduceOp.PROD:
                return arr ** n
            return arr  # AVG/MAX/MIN of identical replicas
        if fn_name == "broadcast":
            return arr
        if fn_name == "all_gather":
            reps = [n if i == kw.get("gather_axis", 0) else 1
                    for i in range(arr.ndim)]
            return jnp.tile(arr, reps)
        raise ValueError(
            f"{fn_name}: operand must be sharded over mesh axis {axis!r} "
            f"(got sharding {getattr(arr, 'sharding', None)}); device_put "
            f"it with a NamedSharding first")

    def inner(a):
        return _traced_collective(fn_name, a, axis, **kw)

    # split along the dimension the array is actually sharded on (paddle
    # semantics: each rank's local shard is "its" tensor)
    d = _sharded_dim(arr, axis)
    spec = [None] * arr.ndim
    spec[d] = axis
    in_spec = P(*spec)
    if fn_name in ("all_reduce", "reduce", "all_gather"):
        out_spec = P(*([None] * arr.ndim))
    elif fn_name == "reduce_scatter":
        out_spec = in_spec
    else:
        out_spec = in_spec
    if fn_name == "all_gather":
        kw = {**kw, "gather_axis": kw.get("gather_axis", d)}
    if fn_name == "reduce_scatter":
        kw = {**kw, "scatter_axis": kw.get("scatter_axis", d)}
    f = jax.shard_map(inner, mesh=m, in_specs=(in_spec,),
                      out_specs=out_spec, check_vma=False)
    return jax.jit(f)(arr)


def _traced_collective(fn_name, a, axis, **kw):
    if fn_name == "all_reduce":
        op = kw.get("op", ReduceOp.SUM)
        if op == ReduceOp.SUM:
            return jax.lax.psum(a, axis)
        if op == ReduceOp.AVG:
            return jax.lax.pmean(a, axis)
        if op == ReduceOp.MAX:
            return jax.lax.pmax(a, axis)
        if op == ReduceOp.MIN:
            return jax.lax.pmin(a, axis)
        if op == ReduceOp.PROD:
            return jnp.exp(jax.lax.psum(jnp.log(a), axis))
        raise ValueError(f"unknown reduce op {op}")
    if fn_name == "all_gather":
        return jax.lax.all_gather(a, axis, axis=kw.get("gather_axis", 0),
                                  tiled=kw.get("tiled", True))
    if fn_name == "reduce_scatter":
        return jax.lax.psum_scatter(a, axis,
                                    scatter_dimension=kw.get("scatter_axis", 0),
                                    tiled=True)
    if fn_name == "broadcast":
        src = kw.get("src", 0)
        idx = jax.lax.axis_index(axis)
        masked = jnp.where(idx == src, a, jnp.zeros_like(a))
        return jax.lax.psum(masked, axis)
    if fn_name == "ppermute":
        return jax.lax.ppermute(a, axis, kw["perm"])
    if fn_name == "alltoall":
        return jax.lax.all_to_all(a, axis,
                                  split_axis=kw.get("split_axis", 0),
                                  concat_axis=kw.get("concat_axis", 0),
                                  tiled=True)
    raise ValueError(fn_name)


def _dispatch(fn_name, tensor, group=None, **kw):
    axis = _axis_of(group)
    arr = _unwrap(tensor)
    if _in_trace(arr):
        out = _traced_collective(fn_name, arr, axis, **kw)
    else:
        out = _eager_collective(fn_name, arr, axis, **kw)
    return _rewrap(out, tensor)


# ---- public API -----------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """SUM/MAX/MIN/PROD allreduce over the group axis
    (reference collective.py:157; kernel c_allreduce_op.h:109)."""
    return _dispatch("all_reduce", tensor, group, op=op)


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    """Reduce-to-root == allreduce on TPU (SPMD keeps all replicas; the
    reference's c_reduce writes only rank dst — XLA has no cheaper form)."""
    return _dispatch("all_reduce", tensor, group, op=op)


def all_gather(tensor_list, tensor=None, group=None, sync_op=True,
               gather_axis=0):
    """Gather shards from every rank (reference collective.py:313). Two
    call shapes: paddle's `all_gather(out_list, t)` eager form, or the
    functional `out = all_gather(t)` form for traced code."""
    if tensor is None:
        return _dispatch("all_gather", tensor_list, group,
                         gather_axis=gather_axis)
    out = _dispatch("all_gather", tensor, group, gather_axis=gather_axis)
    n = get_group(_axis_of(group)).nranks or 1
    arr = _unwrap(out)
    for i, piece in enumerate(jnp.split(arr, n, axis=gather_axis)):
        tensor_list.append(Tensor(piece))
    return out


def reduce_scatter(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
                   scatter_axis=0):
    """Sum + scatter shards (ZeRO's grad primitive; reference
    c_reducescatter op)."""
    return _dispatch("reduce_scatter", tensor, group,
                     scatter_axis=scatter_axis)


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Broadcast rank src's value (reference collective.py:101)."""
    return _dispatch("broadcast", tensor, group, src=src)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    """Rank src's i-th shard to rank i — on an SPMD mesh this is a dynamic
    slice by axis index after broadcasting src's data."""
    axis = _axis_of(group)
    arr = _unwrap(tensor)

    def traced(a):
        a = _traced_collective("broadcast", a, axis, src=src)
        n = jax.lax.psum(jnp.ones((), jnp.int32), axis)
        idx = jax.lax.axis_index(axis)
        shard = a.shape[0] // get_group(axis).nranks
        return jax.lax.dynamic_slice_in_dim(a, idx * shard, shard, 0)

    if _in_trace(arr):
        return _rewrap(traced(arr), tensor)
    m = mesh_mod.get_mesh()
    if m is None or axis not in m.axis_names:
        return tensor
    nd = arr.ndim
    f = jax.shard_map(traced, mesh=m,
                  in_specs=(P(axis, *([None] * (nd - 1))),),
                  out_specs=P(axis, *([None] * (nd - 1))))
    return _rewrap(jax.jit(f)(arr), tensor)


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True,
             split_axis=0, concat_axis=0):
    """All-to-all (the Ulysses sequence-parallel primitive; no reference
    analog — the reference has no SP, SURVEY.md §5). Two call shapes:
    paddle's eager `alltoall([t0..tn], out_list)` list form, or the
    functional single-array form for traced code."""
    if isinstance(in_tensor_list, (list, tuple)):
        stacked = jnp.concatenate([_unwrap(t) for t in in_tensor_list],
                                  axis=0)
        out = _dispatch("alltoall", stacked, group,
                        split_axis=split_axis, concat_axis=concat_axis)
        arr = _unwrap(out)
        pieces = jnp.split(arr, len(in_tensor_list), axis=0)
        if out_tensor_list is not None:
            out_tensor_list.extend(Tensor(piece) for piece in pieces)
        return [Tensor(piece) for piece in pieces]
    return _dispatch("alltoall", in_tensor_list, group,
                     split_axis=split_axis, concat_axis=concat_axis)


def p2p(tensor, src, dst, group=None):
    """Single-edge transfer src → dst as a ppermute (reference
    send_v2/recv_v2 over NCCL p2p). SPMD note: every rank executes this;
    dst receives src's value, all other ranks receive zeros. Pipeline
    schedules build full shift permutations instead (distributed.pipeline)."""
    return _dispatch("ppermute", tensor, group, perm=[(src, dst)])


_P2P_SEMANTICS_WARNING = (
    "SPMD {name}: under single-controller SPMD every rank executes this "
    "op and only dst receives src's value — OTHER RANKS RECEIVE ZEROS, "
    "unlike the reference's per-rank point-to-point. Pass src/dst "
    "explicitly (defaulting {defaults}) or build a full permutation with "
    "p2p/ppermute.")


def send(tensor, dst=0, group=None, sync_op=True, src=None):
    """paddle.distributed.send parity. In the reference the *calling rank*
    is the sender; under single-controller SPMD the sender must be named
    explicitly via src."""
    if src is None:
        import warnings
        warnings.warn(_P2P_SEMANTICS_WARNING.format(
            name="send", defaults="src=0"))
        src = 0
    return p2p(tensor, src, dst, group)


def recv(tensor, src=0, group=None, sync_op=True, dst=None):
    """paddle.distributed.recv parity; dst defaults to (src+1) % nranks."""
    if dst is None:
        import warnings
        warnings.warn(_P2P_SEMANTICS_WARNING.format(
            name="recv", defaults="dst=(src+1)%nranks"))
        dst = (src + 1) % max(get_group(_axis_of(group)).nranks, 1)
    return p2p(tensor, src, dst, group)


def barrier(group=None):
    """Device-level barrier: a tiny psum forces a sync point (the reference
    uses a barrier table / c_barrier op). In single-controller JAX the host
    is already in lockstep; this syncs outstanding device work."""
    m = mesh_mod.get_mesh()
    axis = _axis_of(group)
    if m is None or axis not in m.axis_names:
        return
    x = jnp.ones((int(m.shape[axis]),), jnp.float32)
    sharding = NamedSharding(m, P(axis))
    arr = jax.device_put(x, sharding)
    _eager_collective("all_reduce", arr, axis, op=ReduceOp.SUM)


def split_group_axis(mesh, axis: str, size: int):
    """Utility: split a mesh axis into two (e.g. 'dp' -> 'dp','sharding')."""
    import numpy as np
    devs = mesh.devices
    names = list(mesh.axis_names)
    i = names.index(axis)
    shape = list(devs.shape)
    outer = shape[i] // size
    new_shape = shape[:i] + [outer, size] + shape[i + 1:]
    new_names = names[:i] + [axis, f"{axis}_inner"] + names[i + 1:]
    return jax.sharding.Mesh(devs.reshape(new_shape), tuple(new_names))
