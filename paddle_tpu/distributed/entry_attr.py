"""Sparse-table entry admission policies (reference
python/paddle/distributed/entry_attr.py): decide whether a sparse
feature id gets an embedding entry — ProbabilityEntry admits with a
coin flip, CountFilterEntry after a show-count threshold. Consumed by
the PS sparse tables (distributed/ps)."""
from __future__ import annotations

__all__ = ["EntryAttr", "ProbabilityEntry", "CountFilterEntry"]


class EntryAttr:
    def _to_attr(self):
        raise NotImplementedError


class ProbabilityEntry(EntryAttr):
    def __init__(self, probability):
        if not isinstance(probability, float):
            raise ValueError("probability must be a float in (0,1)")
        if probability <= 0 or probability >= 1:
            raise ValueError("probability must be a float in (0,1)")
        self._name = "probability_entry"
        self._probability = probability

    def _to_attr(self):
        return ":".join([self._name, str(self._probability)])

    def admit(self, rng):
        """Host-side admission decision for the PS sparse table."""
        return float(rng.random()) < self._probability


class CountFilterEntry(EntryAttr):
    def __init__(self, count_filter):
        if not isinstance(count_filter, int):
            raise ValueError("count_filter must be a valid integer greater "
                             "than 0")
        if count_filter < 0:
            raise ValueError("count_filter must be a valid integer greater "
                             "or equal than 0")
        self._name = "count_filter_entry"
        self._count_filter = count_filter

    def _to_attr(self):
        return ":".join([self._name, str(self._count_filter)])

    def admit(self, seen_count):
        return int(seen_count) >= self._count_filter
