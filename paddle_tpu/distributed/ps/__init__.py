"""Parameter-server capability: native C++ table server + Python client.

Reference: the brpc PS stack — BrpcPsServer
(/root/reference/paddle/fluid/distributed/service/brpc_ps_server.h:40),
PSClient (service/ps_client.h:60), dense/sparse tables (table/table.h:32),
AsyncCommunicator with background merge-and-send threads
(service/communicator.h:346, FLAGS_communicator_max_merge_var_num).

TPU-native split: collective training never routes through this (XLA/ICI
owns it); the PS serves the embedding-heavy async-SGD workloads whose
sparse tables exceed chip memory. The server is dependency-free C++
(native/ps_server.cpp, compiled on demand with g++) speaking a
length-prefixed TCP protocol; SGD applies server-side like the reference's
server optimizer. The client is numpy-first; AsyncCommunicator batches
sparse pushes on a background thread.
"""
from __future__ import annotations

import os
import queue
import socket
import struct
import subprocess
import threading
import time
from typing import Dict, Optional

import numpy as np

__all__ = ["build_server_binary", "PSServer", "PSClient",
           "ShardedPSClient", "PSServerDownError",
           "AsyncCommunicator", "GeoCommunicator"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "native")

CREATE_DENSE, CREATE_SPARSE = 1, 2
PULL_DENSE, PUSH_DENSE = 3, 4
PULL_SPARSE, PUSH_SPARSE = 5, 6
BARRIER, STOP, PING, SAVE, LOAD = 7, 8, 9, 10, 11


def build_server_binary(force=False) -> str:
    """Compile native/ps_server.cpp once (g++ -O2); returns binary path."""
    src = os.path.join(_NATIVE_DIR, "ps_server.cpp")
    out = os.path.join(_NATIVE_DIR, "ps_server")
    if force or (not os.path.exists(out)
                 or os.path.getmtime(out) < os.path.getmtime(src)):
        cmd = ["g++", "-O2", "-std=c++17", "-pthread", "-o", out, src]
        res = subprocess.run(cmd, capture_output=True, text=True)
        if res.returncode != 0:
            raise RuntimeError(f"ps_server build failed:\n{res.stderr}")
    return out


class PSServer:
    """Owns one native server process (BrpcPsServer analog)."""

    def __init__(self, port: int = 0):
        binary = build_server_binary()
        self._proc = subprocess.Popen([binary, str(port)],
                                      stdout=subprocess.PIPE, text=True)
        line = self._proc.stdout.readline().strip()
        if not line.startswith("PS_LISTENING"):
            raise RuntimeError(f"ps_server failed to start: {line!r}")
        self.port = int(line.split()[1])
        self.endpoint = f"127.0.0.1:{self.port}"

    def stop(self):
        if self._proc.poll() is None:
            try:
                PSClient(self.endpoint).stop_server()
            except Exception:
                self._proc.terminate()
            self._proc.wait(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class PSServerDownError(RuntimeError):
    """A parameter server stopped answering (heartbeat timeout or broken
    RPC). Reference analog: HeartBeatMonitor marking a worker/server
    UNINITED (operators/distributed/heart_beat_monitor.h:51)."""


class PSClient:
    """Blocking RPC verbs over one TCP connection (ps_client.h:60 analog).
    Not thread-safe; AsyncCommunicator owns its own client.

    Constructing with a LIST of endpoints returns a ShardedPSClient —
    the multi-server fleet client (dense tables range-split, sparse
    tables key-sharded), mirroring ps_client.h:60's server-fleet
    management."""

    def __new__(cls, endpoint="", timeout: float = 30.0, **kw):
        if cls is PSClient and isinstance(endpoint, (list, tuple)) \
                and len(endpoint) > 1:
            return object.__new__(ShardedPSClient)
        return object.__new__(cls)

    def __init__(self, endpoint: str, timeout: float = 30.0):
        if isinstance(endpoint, (list, tuple)):
            (endpoint,) = endpoint
        host, port = endpoint.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    # -- wire helpers ------------------------------------------------------
    def _send(self, verb: int, table: int, n: int, *payloads: bytes):
        msg = struct.pack("<BIQ", verb, table, n) + b"".join(payloads)
        self._sock.sendall(msg)

    def _recv_reply(self) -> bytes:
        hdr = self._recv_exact(8)
        (n,) = struct.unpack("<Q", hdr)
        return self._recv_exact(n) if n else b""

    def _recv_exact(self, n: int) -> bytes:
        from ...utils.net import recv_exact
        return recv_exact(self._sock, n, what="ps server")

    # -- table verbs -------------------------------------------------------
    def create_dense_table(self, table: int, size: int,
                           init: Optional[np.ndarray] = None):
        if init is not None:
            init = np.ascontiguousarray(init, np.float32).ravel()
            self._send(CREATE_DENSE, table, init.size,
                       struct.pack("<Q", 1), init.tobytes())
        else:
            self._send(CREATE_DENSE, table, size, struct.pack("<Q", 0))
        self._recv_reply()

    def create_sparse_table(self, table: int, dim: int):
        self._send(CREATE_SPARSE, table, dim)
        self._recv_reply()

    def pull_dense(self, table: int) -> np.ndarray:
        self._send(PULL_DENSE, table, 0)
        return np.frombuffer(self._recv_reply(), np.float32).copy()

    def push_dense(self, table: int, grad: np.ndarray, lr: float = 1.0):
        g = np.ascontiguousarray(grad, np.float32).ravel()
        self._send(PUSH_DENSE, table, g.size, struct.pack("<f", lr),
                   g.tobytes())
        self._recv_reply()

    def pull_sparse(self, table: int, keys: np.ndarray,
                    dim: int) -> np.ndarray:
        k = np.ascontiguousarray(keys, np.uint64).ravel()
        self._send(PULL_SPARSE, table, k.size, struct.pack("<Q", dim),
                   k.tobytes())
        out = np.frombuffer(self._recv_reply(), np.float32).copy()
        return out.reshape(k.size, dim)

    def push_sparse(self, table: int, keys: np.ndarray, grads: np.ndarray,
                    lr: float = 1.0):
        k = np.ascontiguousarray(keys, np.uint64).ravel()
        g = np.ascontiguousarray(grads, np.float32).reshape(k.size, -1)
        self._send(PUSH_SPARSE, table, k.size, struct.pack("<f", lr),
                   struct.pack("<Q", g.shape[1]), k.tobytes(), g.tobytes())
        self._recv_reply()

    def barrier(self, world: int):
        self._send(BARRIER, 0, world)
        self._recv_reply()

    def ping(self):
        self._send(PING, 0, 0)
        self._recv_reply()

    def save(self, path: str):
        p = path.encode()
        self._send(SAVE, 0, len(p), p)
        self._recv_reply()

    def load(self, path: str):
        p = path.encode()
        self._send(LOAD, 0, len(p), p)
        self._recv_reply()

    def stop_server(self):
        self._send(STOP, 0, 0)
        self._recv_reply()

    def close(self):
        self._sock.close()


class ShardedPSClient(PSClient):
    """Fleet client over N servers (reference PSClient manages a server
    fleet, service/ps_client.h:60; tables shard across servers,
    table/table.h:32).

    Sharding is client-side and deterministic, so every worker routes
    identically with no coordination:
    - sparse tables: row for key k lives on server k % n (the
      reference's shard_num modulo in its sparse tables);
    - dense tables: range-split — server i holds a contiguous slice of
      ceil/floor(size/n) elements, pulls concatenate, pushes scatter;
    - barrier runs on server 0 (one rendezvous point);
    - create/save/load/stop broadcast (save/load get per-server
      ".shardN" paths).

    A heartbeat thread pings every server each `heartbeat_interval`
    seconds (reference heart_beat_monitor.h:51); a dead server turns
    every subsequent verb into a clean PSServerDownError naming the
    endpoint instead of a hung socket."""

    def __init__(self, endpoint, timeout: float = 30.0,
                 heartbeat_interval: float = 2.0,
                 heartbeat_misses: int = 3):
        endpoints = list(endpoint)
        if len(endpoints) < 2:
            raise ValueError("ShardedPSClient needs >= 2 endpoints")
        self.endpoints = endpoints
        self._timeout = timeout
        self._n = len(endpoints)
        self._dense_sizes: Dict[int, list] = {}
        self._dead: Dict[int, str] = {}
        self._misses = [0] * self._n
        self._hb_misses = max(int(heartbeat_misses), 1)
        self._hb_stop = threading.Event()
        self._hb_lock = threading.Lock()
        # probes use a SHORT timeout: a black-holed server must not stall
        # detection (or the probing of its neighbours) for the full RPC
        # timeout per round
        self._hb_timeout = min(timeout, max(float(heartbeat_interval), 1.0))
        self._clients = []
        self._hb_clients = []
        try:
            for ep in endpoints:
                self._clients.append(PSClient(ep, timeout=timeout))
            for ep in endpoints:
                self._hb_clients.append(
                    PSClient(ep, timeout=self._hb_timeout))
        except Exception:
            for c in self._clients + self._hb_clients:
                try:
                    c.close()
                except Exception:
                    pass
            raise
        import concurrent.futures as _fut
        self._pool = _fut.ThreadPoolExecutor(
            max_workers=self._n, thread_name_prefix="ps-shard")
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop, args=(heartbeat_interval,),
            daemon=True)
        self._hb_thread.start()

    # -- liveness ----------------------------------------------------------
    def _heartbeat_loop(self, interval: float):
        while not self._hb_stop.wait(interval):
            for i in range(self._n):
                try:
                    self._hb_clients[i].ping()
                except Exception as e:
                    # the probe socket dies WITH the server — a revived
                    # server is only visible through a fresh connection
                    if not self._hb_reconnect(i):
                        self._misses[i] += 1
                        if self._misses[i] >= self._hb_misses \
                                and i not in self._dead:
                            with self._hb_lock:
                                self._dead[i] = f"heartbeat failed: {e}"
                        continue
                self._misses[i] = 0
                if i in self._dead:
                    # server answers again: reconnect the verb socket
                    # and lift the quarantine
                    self._try_revive(i)

    def _hb_reconnect(self, i: int) -> bool:
        try:
            fresh = PSClient(self.endpoints[i], timeout=self._hb_timeout)
            fresh.ping()
        except Exception:
            return False
        old, self._hb_clients[i] = self._hb_clients[i], fresh
        try:
            old.close()
        except Exception:
            pass
        return True

    def _try_revive(self, i: int):
        try:
            fresh = PSClient(self.endpoints[i], timeout=self._timeout)
        except Exception:
            return
        with self._hb_lock:
            old, self._clients[i] = self._clients[i], fresh
            self._dead.pop(i, None)
        try:
            old.close()
        except Exception:
            pass

    def _check(self, i: int):
        why = self._dead.get(i)
        if why:
            raise PSServerDownError(
                f"parameter server {i} at {self.endpoints[i]} is down "
                f"({why}); its table shards are unavailable")

    def _call(self, i: int, fn, *args, mark_dead=True, **kw):
        self._check(i)
        try:
            return fn(self._clients[i], *args, **kw)
        except PSServerDownError:
            raise
        except socket.timeout:
            # slow != dead (a barrier legitimately blocks); leave
            # liveness to the heartbeat and surface the timeout
            raise
        except (OSError, ConnectionError, struct.error) as e:
            if mark_dead:
                with self._hb_lock:
                    self._dead[i] = f"rpc failed: {e}"
            raise PSServerDownError(
                f"parameter server {i} at {self.endpoints[i]} died "
                f"mid-request: {e}") from e

    def _fanout(self, fn_of_i):
        """Run fn_of_i(i) for every server on the connection pool —
        per-verb latency stays ~1 RTT instead of N serialized RTTs. Any
        shard failure propagates after all futures settle."""
        futs = [self._pool.submit(fn_of_i, i) for i in range(self._n)]
        out, err = [], None
        for f in futs:
            try:
                out.append(f.result())
            except Exception as e:
                err = err or e
                out.append(None)
        if err is not None:
            raise err
        return out

    def alive(self) -> list:
        return [i for i in range(self._n) if i not in self._dead]

    # -- dense: range-split ------------------------------------------------
    def _dense_split(self, size: int) -> list:
        base, rem = divmod(size, self._n)
        return [base + (1 if i < rem else 0) for i in range(self._n)]

    def create_dense_table(self, table: int, size: int,
                           init: Optional[np.ndarray] = None):
        if init is not None:
            init = np.ascontiguousarray(init, np.float32).ravel()
            size = init.size
        sizes = self._dense_split(size)
        offs = np.concatenate([[0], np.cumsum(sizes)])
        self._fanout(lambda i: self._call(
            i, PSClient.create_dense_table, table, sizes[i],
            init[offs[i]:offs[i + 1]] if init is not None else None))
        self._dense_sizes[table] = sizes

    def _sizes_of(self, table: int) -> list:
        sizes = self._dense_sizes.get(table)
        if sizes is None:
            # another worker created the table; discover shard sizes
            sizes = [p.size for p in self._fanout(
                lambda i: self._call(i, PSClient.pull_dense, table))]
            self._dense_sizes[table] = sizes
        return sizes

    def pull_dense(self, table: int) -> np.ndarray:
        parts = self._fanout(
            lambda i: self._call(i, PSClient.pull_dense, table))
        self._dense_sizes.setdefault(table, [p.size for p in parts])
        return np.concatenate(parts)

    def push_dense(self, table: int, grad: np.ndarray, lr: float = 1.0):
        g = np.ascontiguousarray(grad, np.float32).ravel()
        sizes = self._sizes_of(table)
        offs = np.concatenate([[0], np.cumsum(sizes)])
        self._fanout(lambda i: self._call(
            i, PSClient.push_dense, table, g[offs[i]:offs[i + 1]], lr))

    # -- sparse: key-sharded -----------------------------------------------
    def create_sparse_table(self, table: int, dim: int):
        self._fanout(lambda i: self._call(
            i, PSClient.create_sparse_table, table, dim))

    def _route(self, keys: np.ndarray):
        keys = np.ascontiguousarray(keys, np.uint64).ravel()
        owner = (keys % np.uint64(self._n)).astype(np.int64)
        return keys, owner

    def pull_sparse(self, table: int, keys: np.ndarray,
                    dim: int) -> np.ndarray:
        keys, owner = self._route(keys)
        out = np.empty((keys.size, dim), np.float32)
        idxs = [np.nonzero(owner == i)[0] for i in range(self._n)]

        def one(i):
            if idxs[i].size:
                out[idxs[i]] = self._call(
                    i, PSClient.pull_sparse, table, keys[idxs[i]], dim)

        self._fanout(one)
        return out

    def push_sparse(self, table: int, keys: np.ndarray, grads: np.ndarray,
                    lr: float = 1.0):
        keys, owner = self._route(keys)
        g = np.ascontiguousarray(grads, np.float32).reshape(keys.size, -1)
        idxs = [np.nonzero(owner == i)[0] for i in range(self._n)]
        self._fanout(lambda i: self._call(
            i, PSClient.push_sparse, table, keys[idxs[i]], g[idxs[i]], lr)
            if idxs[i].size else None)

    # -- control -----------------------------------------------------------
    def barrier(self, world: int):
        # barrier blocking is not a liveness signal
        self._call(0, PSClient.barrier, world, mark_dead=False)

    def ping(self):
        self._fanout(lambda i: self._call(i, PSClient.ping))

    def save(self, path: str):
        self._fanout(lambda i: self._call(
            i, PSClient.save, f"{path}.shard{i}"))

    def load(self, path: str):
        self._fanout(lambda i: self._call(
            i, PSClient.load, f"{path}.shard{i}"))

    def stop_server(self):
        for i in range(self._n):
            if i not in self._dead:
                try:
                    self._clients[i].stop_server()
                except Exception:
                    pass

    def close(self):
        self._hb_stop.set()
        self._hb_thread.join(timeout=5)
        self._pool.shutdown(wait=False)
        for c in self._clients + self._hb_clients:
            try:
                c.close()
            except Exception:
                pass


class AsyncCommunicator:
    """Background merge-and-send of sparse grads (communicator.h:346).

    push() enqueues (keys, grads); the sender thread coalesces up to
    `max_merge` pending updates per table (summing grads on duplicate keys
    — the reference's merge-before-send) and issues one push_sparse RPC.
    """

    def __init__(self, endpoint: str, lr: float = 0.1, max_merge: int = 20):
        self._client = PSClient(endpoint)
        self._lr = lr
        self._max_merge = max_merge
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._flushed = threading.Condition()
        self._pending = 0
        self._error = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def push(self, table: int, keys: np.ndarray, grads: np.ndarray):
        with self._flushed:
            self._pending += 1
        self._q.put((table, np.asarray(keys), np.asarray(grads)))

    def _loop(self):
        try:
            self._loop_inner()
        except Exception as e:      # surface RPC failures to flush()/push()
            self._error = e
            with self._flushed:
                self._flushed.notify_all()

    def _loop_inner(self):
        while not self._stop.is_set() or not self._q.empty():
            try:
                table, keys, grads = self._q.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [(keys, grads)]
            while len(batch) < self._max_merge:
                try:
                    t2, k2, g2 = self._q.get_nowait()
                except queue.Empty:
                    break
                if t2 != table:
                    self._q.put((t2, k2, g2))
                    break
                batch.append((k2, g2))
            merged: Dict[int, np.ndarray] = {}
            for k, g in batch:
                g = g.reshape(len(k), -1)
                for i, key in enumerate(np.asarray(k).ravel()):
                    key = int(key)
                    if key in merged:
                        merged[key] = merged[key] + g[i]
                    else:
                        merged[key] = g[i].astype(np.float32)
            keys_m = np.fromiter(merged.keys(), np.uint64, len(merged))
            grads_m = np.stack([merged[int(k)] for k in keys_m])
            self._client.push_sparse(table, keys_m, grads_m, lr=self._lr)
            with self._flushed:
                self._pending -= len(batch)
                self._flushed.notify_all()

    def flush(self, timeout: float = 30.0):
        with self._flushed:
            ok = self._flushed.wait_for(
                lambda: self._pending == 0 or self._error is not None,
                timeout=timeout)
        if self._error is not None:
            raise RuntimeError(
                "AsyncCommunicator sender failed; queued sparse updates "
                "were lost") from self._error
        if not ok:
            raise TimeoutError(
                f"AsyncCommunicator.flush: {self._pending} pending pushes "
                f"after {timeout}s")

    def stop(self):
        self.flush()
        self._stop.set()
        self._thread.join(timeout=10)
        self._client.close()


class GeoCommunicator:
    """Geo-SGD delta synchronization (reference: GeoCommunicator,
    communicator.h:495 + sparse_geo_table.cc).

    Workers train a LOCAL copy of the (sparse) embedding table; every
    `sync_steps` optimizer applications the worker pushes the *delta*
    against its last sync base, scaled by 1/nranks, and rebases onto the
    fresh global rows — async workers see each other's progress without
    per-step RPC. Server merge uses the existing server-side-SGD verb:
    push_sparse(keys, -delta, lr=1) == w_global += delta.
    """

    def __init__(self, endpoint: str, table: int, dim: int,
                 nranks: int = 1, sync_steps: int = 10):
        self._client = PSClient(endpoint)
        self._table = table
        self._dim = dim
        self._nranks = max(int(nranks), 1)
        self._sync_steps = max(int(sync_steps), 1)
        self._local: Dict[int, np.ndarray] = {}    # key -> local row
        self._base: Dict[int, np.ndarray] = {}     # key -> row at last sync
        self._touched: set = set()
        self._applies = 0

    def _ensure(self, keys: np.ndarray) -> np.ndarray:
        """Make `keys` resident locally (unseen keys fetch the global
        value and become the sync base); returns the raveled keys."""
        keys = np.asarray(keys, np.uint64).ravel()
        missing = [int(k) for k in keys if int(k) not in self._local]
        if missing:
            rows = self._client.pull_sparse(
                self._table, np.asarray(missing, np.uint64), self._dim)
            for k, r in zip(missing, rows):
                self._local[k] = r.astype(np.float32).copy()
                self._base[k] = r.astype(np.float32).copy()
        return keys

    def pull(self, keys: np.ndarray) -> np.ndarray:
        """Local rows for `keys`."""
        keys = self._ensure(keys)
        return np.stack([self._local[int(k)] for k in keys])

    def apply_grads(self, keys: np.ndarray, grads: np.ndarray,
                    lr: float = 0.1):
        """Local SGD on the worker copy; schedules a geo sync every
        sync_steps applies."""
        keys = self._ensure(keys)
        grads = np.asarray(grads, np.float32).reshape(len(keys), self._dim)
        for k, g in zip(keys, grads):
            k = int(k)
            self._local[k] = self._local[k] - lr * g
            self._touched.add(k)
        self._applies += 1
        if self._applies % self._sync_steps == 0:
            self.sync()

    def sync(self):
        """Push deltas/nranks for touched rows, pull fresh globals,
        rebase."""
        if not self._touched:
            return
        keys = np.fromiter(self._touched, np.uint64, len(self._touched))
        delta = np.stack([(self._local[int(k)] - self._base[int(k)])
                          / self._nranks for k in keys])
        # server-side: w -= lr * grad with grad = -delta, lr = 1
        self._client.push_sparse(self._table, keys, -delta, lr=1.0)
        fresh = self._client.pull_sparse(self._table, keys, self._dim)
        for k, r in zip(keys, fresh):
            k = int(k)
            self._local[k] = r.astype(np.float32).copy()
            self._base[k] = self._local[k].copy()
        self._touched.clear()

    def close(self):
        """Flush the partial sync window, then close (AsyncCommunicator
        likewise flushes in stop() — un-synced local progress must not be
        silently dropped)."""
        self.sync()
        self._client.close()


# heterogeneous trainer (SURVEY row 33): sparse tier on the PS hosts,
# dense tier on the accelerator — see heter.py
from .heter import HeterTrainer  # noqa: F401,E402
