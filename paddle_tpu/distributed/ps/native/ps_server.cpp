// Parameter-server core: dense + sparse tables over a TCP binary protocol.
//
// Reference: BrpcPsServer / Table
// (/root/reference/paddle/fluid/distributed/service/brpc_ps_server.h:40,
//  table/table.h:32, common_dense_table.cc, common_sparse_table.cc) and the
// RPC verbs of Communicator (service/communicator.h:215-233
// RpcRecvDense/RpcSendDense/RpcSendSparse/RpcRecvSparse, barrier :258).
//
// TPU-native context: the collective training path never touches this —
// XLA/ICI owns gradients there. The PS exists for the embedding-heavy
// async-SGD capability (PS mode in fleet): sparse tables too large for any
// chip, updated server-side. brpc is replaced by a dependency-free
// length-prefixed TCP protocol; one thread per connection, per-table
// sharded mutexes, server-side SGD apply (the reference's server optimizer).
//
// Protocol (little endian):
//   request : u8 verb | u32 table | u64 n | payload
//   reply   : u64 n   | payload
// Verbs:
//   1 CREATE_DENSE  n=size            payload: optional n f32 init
//   2 CREATE_SPARSE n=dim
//   3 PULL_DENSE                      -> n f32
//   4 PUSH_DENSE    n floats          payload: f32 lr | n f32 grad
//   5 PULL_SPARSE   n keys            payload: u64 dim | n u64  -> n*dim f32
//   6 PUSH_SPARSE   n keys            payload: f32 lr | u64 dim | n u64 | n*dim f32
//       (dim travels on the wire so a missing/mismatched table can drain
//        the request and return zeros instead of desyncing the stream)
//   7 BARRIER       n=world           blocks until n arrivals (generation)
//   8 STOP
//   9 PING                            -> 0
//  10 SAVE          payload: path     persist all tables
//  11 LOAD          payload: path

#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

struct DenseTable {
  std::vector<float> w;
  std::mutex mu;
};

struct SparseTable {
  uint64_t dim = 0;
  std::unordered_map<uint64_t, std::vector<float>> rows;
  std::mutex mu;
};

struct Server {
  std::unordered_map<uint32_t, DenseTable> dense;
  std::unordered_map<uint32_t, SparseTable> sparse;
  std::mutex tables_mu;

  std::mutex barrier_mu;
  std::condition_variable barrier_cv;
  uint64_t barrier_count = 0, barrier_gen = 0;

  std::atomic<bool> stopping{false};
  int listen_fd = -1;
};

bool read_full(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::read(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool write_full(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::write(fd, p, n);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool reply(int fd, const void* payload, uint64_t n_bytes) {
  if (!write_full(fd, &n_bytes, sizeof(n_bytes))) return false;
  return n_bytes == 0 || write_full(fd, payload, n_bytes);
}

void save_tables(Server& s, const std::string& path) {
  std::ofstream f(path, std::ios::binary);
  std::lock_guard<std::mutex> lk(s.tables_mu);
  uint64_t nd = s.dense.size(), ns = s.sparse.size();
  f.write(reinterpret_cast<char*>(&nd), 8);
  for (auto& [id, t] : s.dense) {
    std::lock_guard<std::mutex> lt(t.mu);  // racing pushes resize w
    uint64_t n = t.w.size();
    f.write(reinterpret_cast<const char*>(&id), 4);
    f.write(reinterpret_cast<char*>(&n), 8);
    f.write(reinterpret_cast<const char*>(t.w.data()), n * 4);
  }
  f.write(reinterpret_cast<char*>(&ns), 8);
  for (auto& [id, t] : s.sparse) {
    std::lock_guard<std::mutex> lt(t.mu);
    uint64_t n = t.rows.size();
    f.write(reinterpret_cast<const char*>(&id), 4);
    f.write(reinterpret_cast<const char*>(&t.dim), 8);
    f.write(reinterpret_cast<char*>(&n), 8);
    for (auto& [k, row] : t.rows) {
      f.write(reinterpret_cast<const char*>(&k), 8);
      f.write(reinterpret_cast<const char*>(row.data()), t.dim * 4);
    }
  }
}

void load_tables(Server& s, const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) return;
  std::lock_guard<std::mutex> lk(s.tables_mu);
  uint64_t nd = 0;
  f.read(reinterpret_cast<char*>(&nd), 8);
  for (uint64_t i = 0; i < nd; ++i) {
    uint32_t id;
    uint64_t n;
    f.read(reinterpret_cast<char*>(&id), 4);
    f.read(reinterpret_cast<char*>(&n), 8);
    auto& t = s.dense[id];
    std::lock_guard<std::mutex> lt(t.mu);
    t.w.resize(n);
    f.read(reinterpret_cast<char*>(t.w.data()), n * 4);
  }
  uint64_t ns = 0;
  f.read(reinterpret_cast<char*>(&ns), 8);
  for (uint64_t i = 0; i < ns; ++i) {
    uint32_t id;
    uint64_t dim, n;
    f.read(reinterpret_cast<char*>(&id), 4);
    f.read(reinterpret_cast<char*>(&dim), 8);
    f.read(reinterpret_cast<char*>(&n), 8);
    auto& t = s.sparse[id];
    std::lock_guard<std::mutex> lt(t.mu);
    t.dim = dim;
    for (uint64_t j = 0; j < n; ++j) {
      uint64_t k;
      f.read(reinterpret_cast<char*>(&k), 8);
      auto& row = t.rows[k];
      row.resize(dim);
      f.read(reinterpret_cast<char*>(row.data()), dim * 4);
    }
  }
}

void handle(Server& s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t verb;
    uint32_t table;
    uint64_t n;
    if (!read_full(fd, &verb, 1) || !read_full(fd, &table, 4) ||
        !read_full(fd, &n, 8))
      break;
    switch (verb) {
      case 1: {  // CREATE_DENSE
        std::vector<float> init;
        uint64_t have_init;
        if (!read_full(fd, &have_init, 8)) goto done;
        if (have_init) {
          init.resize(n);
          if (!read_full(fd, init.data(), n * 4)) goto done;
        }
        {
          std::lock_guard<std::mutex> lk(s.tables_mu);
          auto& t = s.dense[table];
          std::lock_guard<std::mutex> lt(t.mu);
          t.w.assign(n, 0.f);
          if (have_init) t.w = init;
        }
        if (!reply(fd, nullptr, 0)) goto done;
        break;
      }
      case 2: {  // CREATE_SPARSE
        std::lock_guard<std::mutex> lk(s.tables_mu);
        s.sparse[table].dim = n;
        if (!reply(fd, nullptr, 0)) goto done;
        break;
      }
      case 3: {  // PULL_DENSE
        DenseTable* t;
        {
          std::lock_guard<std::mutex> lk(s.tables_mu);
          t = &s.dense[table];
        }
        std::lock_guard<std::mutex> lt(t->mu);
        if (!reply(fd, t->w.data(), t->w.size() * 4)) goto done;
        break;
      }
      case 4: {  // PUSH_DENSE (server-side SGD)
        float lr;
        std::vector<float> g(n);
        if (!read_full(fd, &lr, 4) || !read_full(fd, g.data(), n * 4))
          goto done;
        DenseTable* t;
        {
          std::lock_guard<std::mutex> lk(s.tables_mu);
          t = &s.dense[table];
        }
        {
          std::lock_guard<std::mutex> lt(t->mu);
          if (t->w.size() < n) t->w.resize(n, 0.f);
          for (uint64_t i = 0; i < n; ++i) t->w[i] -= lr * g[i];
        }
        if (!reply(fd, nullptr, 0)) goto done;
        break;
      }
      case 5: {  // PULL_SPARSE
        uint64_t dim;
        std::vector<uint64_t> keys(n);
        if (!read_full(fd, &dim, 8) || !read_full(fd, keys.data(), n * 8))
          goto done;
        SparseTable* t;
        {
          std::lock_guard<std::mutex> lk(s.tables_mu);
          t = &s.sparse[table];
        }
        std::vector<float> out(n * dim, 0.f);
        {
          std::lock_guard<std::mutex> lt(t->mu);
          if (t->dim == dim) {
            for (uint64_t i = 0; i < n; ++i) {
              auto it = t->rows.find(keys[i]);
              if (it != t->rows.end())
                std::memcpy(out.data() + i * dim, it->second.data(),
                            dim * 4);
            }
          }
        }
        if (!reply(fd, out.data(), out.size() * 4)) goto done;
        break;
      }
      case 6: {  // PUSH_SPARSE (server-side SGD, rows created on demand)
        float lr;
        uint64_t dim;
        std::vector<uint64_t> keys(n);
        if (!read_full(fd, &lr, 4) || !read_full(fd, &dim, 8) ||
            !read_full(fd, keys.data(), n * 8))
          goto done;
        std::vector<float> g(n * dim);  // client dim: stream stays in sync
        if (!read_full(fd, g.data(), g.size() * 4)) goto done;
        SparseTable* t;
        {
          std::lock_guard<std::mutex> lk(s.tables_mu);
          t = &s.sparse[table];
        }
        {
          std::lock_guard<std::mutex> lt(t->mu);
          if (t->dim == 0) t->dim = dim;  // implicit create
          if (t->dim == dim) {
            for (uint64_t i = 0; i < n; ++i) {
              auto& row = t->rows[keys[i]];
              if (row.size() != dim) row.assign(dim, 0.f);
              for (uint64_t d = 0; d < dim; ++d)
                row[d] -= lr * g[i * dim + d];
            }
          } else {
            std::fprintf(stderr,
                         "ps_server: PUSH_SPARSE dim %llu != table dim "
                         "%llu, update dropped\n",
                         (unsigned long long)dim,
                         (unsigned long long)t->dim);
          }
        }
        if (!reply(fd, nullptr, 0)) goto done;
        break;
      }
      case 7: {  // BARRIER(n == world size)
        std::unique_lock<std::mutex> lk(s.barrier_mu);
        uint64_t gen = s.barrier_gen;
        if (++s.barrier_count >= n) {
          s.barrier_count = 0;
          ++s.barrier_gen;
          s.barrier_cv.notify_all();
        } else {
          s.barrier_cv.wait(lk, [&] { return s.barrier_gen != gen; });
        }
        if (!reply(fd, nullptr, 0)) goto done;
        break;
      }
      case 8:  // STOP
        reply(fd, nullptr, 0);
        s.stopping = true;
        ::shutdown(s.listen_fd, SHUT_RDWR);  // unblock accept()
        goto done;
      case 9:  // PING
        if (!reply(fd, nullptr, 0)) goto done;
        break;
      case 10:
      case 11: {  // SAVE / LOAD
        std::string path(n, '\0');
        if (!read_full(fd, path.data(), n)) goto done;
        if (verb == 10)
          save_tables(s, path);
        else
          load_tables(s, path);
        if (!reply(fd, nullptr, 0)) goto done;
        break;
      }
      default:
        goto done;
    }
  }
done:
  ::close(fd);
}

}  // namespace

int main(int argc, char** argv) {
  int port = argc > 1 ? std::atoi(argv[1]) : 0;
  Server server;

  int lfd = ::socket(AF_INET, SOCK_STREAM, 0);
  server.listen_fd = lfd;
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(lfd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0) {
    std::perror("bind");
    return 1;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(lfd, reinterpret_cast<sockaddr*>(&addr), &alen);
  ::listen(lfd, 64);  // must precede the announce: clients connect on it
  std::printf("PS_LISTENING %d\n", ntohs(addr.sin_port));
  std::fflush(stdout);

  std::vector<std::thread> threads;
  while (!server.stopping) {
    int cfd = ::accept(lfd, nullptr, nullptr);
    if (cfd < 0) break;
    if (server.stopping) {
      ::close(cfd);
      break;
    }
    threads.emplace_back([&server, cfd] { handle(server, cfd); });
  }
  ::close(lfd);
  for (auto& t : threads)
    if (t.joinable()) t.detach();  // connection threads exit on close
  return 0;
}
